"""R9 — synchronous checkpoint writes inside a step loop.

A ``checkpoint.save*`` call in the same loop that dispatches a jitted step
serializes the FULL train state to msgpack and writes + fsyncs it to disk
before the next step can even be enqueued — the step loop stalls on host
CPU and disk for work that has no ordering dependency on it beyond the
device→host snapshot.  The async checkpointer
(``pdnlp_tpu.train.async_ckpt``) exists to split the save at exactly that
line: the loop pays the snapshot, a writer thread pays serialization and
the crash-atomic publish, double-buffered with at most one save in flight.

Heuristic, per lexical ``for``/``while`` loop (sharing R7's loop-body
machinery): the loop body contains BOTH

- a step dispatch — a call whose name's last segment ends in ``step``/
  ``step_fn`` (the repo's jitted-step naming convention);
- a synchronous checkpoint write — a call resolving to
  ``pdnlp_tpu.train.checkpoint.save``/``save_state``/``save_params``
  (through import aliases, e.g. ``ckpt.save_state``), or any call whose
  last name segment is ``save_state``/``save_params``/``save_resume``/
  ``save_checkpoint``/``save_ckpt`` (``self.save_resume(...)``, the
  trainer convention).

``AsyncCheckpointer.submit`` and ``checkpoint.snapshot`` deliberately do
NOT match: snapshot-in-loop + submit IS the fix.  Epoch-level saves inside
an epoch loop that contains the step loop are still findings — they block
the NEXT epoch's first step the same way.  The finding lands on the save
call.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator

from pdnlp_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, dotted_name, is_step_call, loop_body_calls,
    register,
)

_CKPT_SAVE_FUNCS = {
    "pdnlp_tpu.train.checkpoint.save",
    "pdnlp_tpu.train.checkpoint.save_state",
    "pdnlp_tpu.train.checkpoint.save_params",
}
_SAVE_NAME_RE = re.compile(r"^save_(state|params|resume|checkpoint|ckpt)$")


@register
class BlockingCkptInStepLoop(Rule):
    rule_id = "R9"
    name = "blocking-ckpt-in-step-loop"
    hint = ("keep only the device->host snapshot on the step loop: route "
            "the write through pdnlp_tpu.train.async_ckpt.AsyncCheckpointer "
            "— writer.submit(path, checkpoint.snapshot(state)) — so "
            "serialization and the crash-atomic publish ride the writer "
            "thread (at most one save in flight, step loop never blocks "
            "on disk)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not self._relevant(mod):
            return
        for loop in ast.walk(mod.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            calls = loop_body_calls(mod, loop)
            if not any(is_step_call(c) for c in calls):
                continue
            for c in calls:
                if self._is_sync_save(mod, c):
                    yield self.finding(
                        mod, c,
                        "synchronous checkpoint write inside a loop that "
                        "dispatches a jitted step — the loop blocks on "
                        "msgpack serialization + disk every save instead "
                        "of paying the device->host snapshot only")

    @staticmethod
    def _relevant(mod: ModuleInfo) -> bool:
        """Train-loop-shaped modules only: the file must touch jax or the
        checkpoint module — a pure-host script's ``save_*`` helpers are
        not device-loop stalls."""
        if "jax" in mod.aliases or any(a.startswith("jax")
                                       for a in mod.aliases.values()):
            return True
        return any(a.startswith("pdnlp_tpu.train.checkpoint")
                   for a in mod.aliases.values())

    def _is_sync_save(self, mod: ModuleInfo, call: ast.Call) -> bool:
        if mod.resolves_to(call.func, _CKPT_SAVE_FUNCS):
            return True
        name = dotted_name(call.func)
        if not name:
            return False
        return bool(_SAVE_NAME_RE.fullmatch(name.split(".")[-1]))
