"""R18 — handoff export/import dispatch whose shape follows the live
page count.

The disaggregated KV handoff (``pdnlp_tpu.serve.decode`` — prefill-role
export, decode-role import) stays retrace-free by CONSTRUCTION: both
programs take the FULL ``[pages_per_stream]`` table row, sentinel-padded
past the stream's real pages, so ONE compiled export and ONE compiled
import serve every stream regardless of prompt length.  The tempting
spelling inverts that::

    pages = [p for p in table[slot] if p < n_pages]
    k, v = export_fn(cache_k, cache_v, np.asarray(pages))      # <- R18
    import_fn(cache_k, cache_v, pk, pv, dst[:len(pages)])      # <- R18

Sizing the gather/scatter index array to the runtime page count hands
jit a DIFFERENT shape for every distinct prompt-length bucket a stream
lands in — a handoff storm then compiles per page-count instead of
hitting the one warmed program, and TTFT eats the XLA queue.  The fix
is the engine's: dispatch the padded full-width row and let the program
drop sentinel rows internally (the real count rides as masked data).

Heuristic, per function: a HANDOFF dispatch — a call whose name's last
segment contains ``export``/``import`` — with an argument that is
(a) a subscript SLICE whose bound is not a compile-time constant
(``dst[:n_live]``, ``row[: len(pages)]``), or (b) a name bound to a
comprehension/``filter`` in the same function (the live-page list),
bare or wrapped in ``asarray``/``array``/``stack``/``concatenate``.
Full-width rows, sentinel ``np.full`` padding, literal-bound slices,
and runtime counts passed as scalar data (``len(pages)`` as an
argument) never match.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator, Set

from pdnlp_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, dotted_name, register,
)

_HANDOFF_CALL_RE = re.compile(r"(export|import)", re.I)
_WRAP_FUNCS = frozenset(("asarray", "array", "stack", "concatenate"))


@register
class PerStreamHandoffRetrace(Rule):
    rule_id = "R18"
    name = "per-stream-handoff-retrace"
    hint = ("dispatch the handoff export/import at the FULL fixed "
            "[pages_per_stream] table extent, sentinel-padded past the "
            "stream's live pages (pdnlp_tpu.serve.decode export_pages/"
            "import_pages are the engine forms — compile keys "
            "('export'|'import', pages_per_stream)) — sizing the index "
            "array to the runtime page count gives every prompt-length "
            "bucket its own program shape, so a handoff storm compiles "
            "per page-count instead of reusing the one warmed program")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not self._relevant(mod):
            return
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            varlen = self._varlen_names(fn)
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call) \
                        or not self._is_handoff_dispatch(call):
                    continue
                if self._has_runtime_slice(call) \
                        or self._passes_varlen_array(call, varlen):
                    yield self.finding(
                        mod, call,
                        "handoff export/import dispatched with a "
                        "runtime-page-count shape — every distinct live "
                        "page count is a new program, so the handoff "
                        "path retraces per prompt-length bucket instead "
                        "of reusing the one fixed [pages_per_stream] "
                        "padded program")

    @staticmethod
    def _relevant(mod: ModuleInfo) -> bool:
        return "jax" in mod.aliases or any(
            a.startswith("jax") for a in mod.aliases.values())

    @staticmethod
    def _is_handoff_dispatch(call: ast.Call) -> bool:
        name = dotted_name(call.func)
        if not name:
            return False
        return bool(_HANDOFF_CALL_RE.search(name.split(".")[-1]))

    @staticmethod
    def _varlen_names(fn: ast.AST) -> Set[str]:
        """Names bound (in this function) to a value whose LENGTH only
        runtime knows: a comprehension, a ``filter(...)`` call, or a
        ``list(...)`` wrapping either."""
        def varlen_value(v: ast.AST) -> bool:
            if isinstance(v, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
                return True
            if isinstance(v, ast.Call):
                fname = dotted_name(v.func) or ""
                last = fname.split(".")[-1]
                if last == "filter":
                    return True
                if last == "list" and v.args \
                        and varlen_value(v.args[0]):
                    return True
            return False

        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and varlen_value(node.value):
                out |= {t.id for t in node.targets
                        if isinstance(t, ast.Name)}
        return out

    @staticmethod
    def _has_runtime_slice(call: ast.Call) -> bool:
        """Any argument subscripted with a Slice whose bound contains an
        identifier — a extent only runtime knows (R17's test)."""
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for node in ast.walk(arg):
                if not isinstance(node, ast.Subscript):
                    continue
                sl = node.slice
                parts = [sl] if isinstance(sl, ast.Slice) else [
                    d for d in getattr(sl, "elts", [])
                    if isinstance(d, ast.Slice)]
                for dim in parts:
                    for bound in (dim.lower, dim.upper, dim.step):
                        if bound is None:
                            continue
                        if any(isinstance(n, ast.Name)
                               for n in ast.walk(bound)):
                            return True
        return False

    @staticmethod
    def _passes_varlen_array(call: ast.Call, varlen: Set[str]) -> bool:
        """An argument that IS a live-page list (or an array built from
        one): a bare varlen name, an inline comprehension, or an
        asarray/array/stack/concatenate over either.  A varlen name
        buried in other calls (``len(pages)``) is scalar DATA — the
        sanctioned spelling — and never matches."""
        def is_varlen_expr(e: ast.AST) -> bool:
            if isinstance(e, ast.Name):
                return e.id in varlen
            if isinstance(e, (ast.ListComp, ast.GeneratorExp)):
                return True
            if isinstance(e, ast.Call):
                fname = dotted_name(e.func) or ""
                if fname.split(".")[-1] in _WRAP_FUNCS:
                    return any(is_varlen_expr(a) for a in e.args)
            return False

        return any(is_varlen_expr(a) for a in
                   list(call.args) + [kw.value for kw in call.keywords])
