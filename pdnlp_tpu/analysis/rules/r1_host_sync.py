"""R1 — host-device sync points inside traced (jit) code.

``.item()`` / ``float()`` / ``int()`` on a traced value, ``np.asarray`` /
``np.array``, and ``jax.device_get`` all force the tracer to concretize:
under ``jit`` they either raise ``ConcretizationTypeError`` at trace time or
— worse, via callbacks or abstract-safe paths — silently serialize host and
device every step.  The training loop's whole async-dispatch discipline
(trainer.py fetches ONE loss per log line) exists to avoid exactly this.
"""
from __future__ import annotations

import ast
from typing import Iterator

from pdnlp_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, dotted_name, register,
)

#: canonical call targets that materialize on host
_HOST_CALLS = {
    "jax.device_get": "return the value instead and fetch it outside the "
                      "jitted function (jax.device_get at the call site)",
    "numpy.asarray": "use jax.numpy.asarray inside traced code; convert on "
                     "host only after the jitted call returns",
    "numpy.array": "use jax.numpy.asarray inside traced code; convert on "
                   "host only after the jitted call returns",
}

#: method calls on any object that concretize
_HOST_METHODS = {
    "item": "return the array and call .item() (or float()) on the host "
            "after the jitted call",
    "tolist": "return the array; .tolist() belongs on the host side",
    "numpy": "return the array; .numpy()/np conversion belongs on the host",
}


@register
class HostSyncInJit(Rule):
    rule_id = "R1"
    name = "host-sync-in-jit"
    hint = "move the host conversion outside the traced function"

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        traced = mod.traced_functions()
        for fn in traced:
            tainted = mod.tainted_names(fn)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    yield from self._check_call(mod, fn, node, tainted)

    def _check_call(self, mod, fn, node: ast.Call, tainted):
        target = mod.resolve(node.func)
        if target in _HOST_CALLS or (
                target and target.startswith("np.")
                and ("numpy." + target[3:]) in _HOST_CALLS):
            canon = target if target in _HOST_CALLS else "numpy." + target[3:]
            yield self.finding(
                mod, node,
                f"`{dotted_name(node.func)}` inside a jit-traced function "
                "forces a host-device sync (or a tracer leak)",
                _HOST_CALLS[canon])
            return
        # float(x) / int(x) on a traced value
        if isinstance(node.func, ast.Name) and node.func.id in ("float", "int"):
            if node.args and mod.mentions_traced(node.args[0], tainted):
                yield self.finding(
                    mod, node,
                    f"`{node.func.id}()` on a traced value inside a "
                    "jit-traced function raises ConcretizationTypeError "
                    "(or syncs every step via callbacks)",
                    "keep the value as a jax array; fetch with "
                    "float(jax.device_get(x)) after the jitted call returns")
            return
        # x.item() / x.tolist() / x.numpy()
        if isinstance(node.func, ast.Attribute) and not node.args \
                and node.func.attr in _HOST_METHODS:
            if mod.mentions_traced(node.func.value, tainted) \
                    or isinstance(node.func.value, ast.Call):
                yield self.finding(
                    mod, node,
                    f"`.{node.func.attr}()` inside a jit-traced function "
                    "concretizes the tracer (host-device sync point)",
                    _HOST_METHODS[node.func.attr])
