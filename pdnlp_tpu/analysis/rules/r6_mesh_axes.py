"""R6 — PartitionSpec axis names no mesh declares.

``with_sharding_constraint(x, P('modle'))`` with a typo'd axis doesn't
error loudly in every path — under ``jit`` with an ambient mesh it can
simply fail to constrain, silently degrading a sharded run to replicated
(all the HBM, none of the parallelism).  The repo's canonical axis
vocabulary lives in ``pdnlp_tpu/parallel/mesh.py`` (``KNOWN_AXES``); this
rule parses it from there — by AST, never importing — and flags every
string axis inside a ``PartitionSpec(...)`` / ``P(...)`` call that the
vocabulary doesn't contain.
"""
from __future__ import annotations

import ast
import os
from typing import Iterator, Optional, Set

from pdnlp_tpu.analysis.core import Finding, ModuleInfo, Rule, register

#: fallback when mesh.py cannot be parsed (e.g. analyzer vendored elsewhere)
_DEFAULT_AXES = {"data", "model", "expert", "seq", "stage"}

_MESH_PY = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "parallel", "mesh.py")


def declared_axes(mesh_path: str = _MESH_PY) -> Set[str]:
    """Axis names declared in mesh.py: every module-level UPPER_CASE
    assignment of a string constant (``DATA_AXIS = "data"``), tuple
    unpacking of string constants, and the ``KNOWN_AXES`` registry tuple."""
    try:
        with open(mesh_path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return set(_DEFAULT_AXES)
    axes: Set[str] = set()
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            names = [target] if isinstance(target, ast.Name) else (
                list(target.elts) if isinstance(target, (ast.Tuple, ast.List))
                else [])
            if not all(isinstance(n, ast.Name) and n.id.isupper()
                       for n in names) or not names:
                continue
            for v in ast.walk(node.value):
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    axes.add(v.value)
    return axes or set(_DEFAULT_AXES)


@register
class UnknownMeshAxis(Rule):
    rule_id = "R6"
    name = "unknown-partition-axis"
    hint = ("use an axis declared in pdnlp_tpu/parallel/mesh.py KNOWN_AXES "
            "(or add the new axis there so every subsystem agrees on it)")

    def __init__(self):
        self._axes: Optional[Set[str]] = None

    @property
    def axes(self) -> Set[str]:
        if self._axes is None:
            self._axes = declared_axes()
        return self._axes

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        # only meaningful in files that actually import PartitionSpec —
        # a random local helper named P() must not trip the rule
        spec_aliases = {alias for alias, origin in mod.aliases.items()
                        if origin.endswith("PartitionSpec")}
        if not spec_aliases:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Name)
                    and node.func.id in spec_aliases):
                resolved = mod.resolve(node.func) or ""
                if not resolved.endswith("PartitionSpec"):
                    continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                yield from self._check_spec_entry(mod, arg)

    def _check_spec_entry(self, mod: ModuleInfo, entry: ast.AST
                          ) -> Iterator[Finding]:
        values = entry.elts if isinstance(entry, (ast.Tuple, ast.List)) \
            else [entry]
        for v in values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str) \
                    and v.value not in self.axes:
                yield self.finding(
                    mod, v,
                    f"PartitionSpec axis '{v.value}' is not declared by any "
                    "mesh (pdnlp_tpu/parallel/mesh.py KNOWN_AXES) — a typo "
                    "here silently leaves the array unconstrained/replicated")
