"""R13 — control-plane knob writes outside the decision-recording path.

The serve control plane's contract (the controller PR) is that EVERY
actuation of a serving knob — ``hedge_ms``, ``max_wait_ms``, the admission
thresholds, the replica count — passes through
:meth:`ServeController._actuate`: the one choke point that enforces the
clamp range, cooldown, hysteresis and backoff hold, AND records the
hop-style decision chain (:mod:`pdnlp_tpu.obs.decision`) that lets
``trace_tpu.py decisions`` explain why capacity changed.  A knob write
that bypasses it is an *unrecorded actuation*: the system's behavior
changes with no decision record, no safety clamp, and no evaluation
window to auto-revert it — the unaccountable-autotuner bug class.

Heuristic, controller-scope modules only (a module that imports from
``pdnlp_tpu.serve.controller`` — or is it): flag

- assignments (plain or augmented) to an attribute named like a tuning
  knob (``x.hedge_ms = ...``, ``adm.backpressure_at *= 2``), and
- direct calls to the router's raw setter surface
  (``.apply_knob(...)``, ``.deactivate_replica(...)``,
  ``.activate_replica(...)``)

anywhere outside a function named ``_actuate`` or ``_apply`` (the
controller's applier that only ``_actuate`` calls).  Modules that never
touch the controller are out of scope — the router/batcher themselves own
these attributes (their ``__init__``/``apply_knob`` ARE the setter
surface), and test files are not on the lint surface.
"""
from __future__ import annotations

import ast
from typing import Iterator

from pdnlp_tpu.analysis.core import Finding, ModuleInfo, Rule, register

#: the attributes the control plane owns once a controller is in play
_TUNING_ATTRS = {"hedge_ms", "max_wait_ms", "backpressure_at", "shed_at",
                 "shed_slack_ms"}

#: the router's raw actuation surface — sanctioned only beneath _actuate
_ACTUATION_CALLS = {"apply_knob", "deactivate_replica", "activate_replica"}

#: functions that ARE the decision-record path
_SANCTIONED = {"_actuate", "_apply"}


@register
class UnrecordedActuation(Rule):
    rule_id = "R13"
    name = "unrecorded-actuation"
    hint = ("route the change through the controller's decision-recording "
            "choke point — `self._actuate(knob, value, cause)` (or "
            "`ServeController.inject` from test/chaos code) — so it is "
            "clamped, cooldown/hold-guarded, recorded as a decision chain "
            "(pdnlp_tpu.obs.decision) and auto-reverted if it regresses "
            "the SLO; raw `apply_knob`/attribute writes bypass all four")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not self._controller_module(mod):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and t.attr in _TUNING_ATTRS \
                            and not self._sanctioned(mod, node):
                        yield self.finding(
                            mod, node,
                            f"tuning attribute '{t.attr}' written outside "
                            "the _actuate decision-record path — an "
                            "unrecorded, unclamped, unevaluated actuation")
                        break
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _ACTUATION_CALLS \
                    and not self._sanctioned(mod, node):
                yield self.finding(
                    mod, node,
                    f"raw actuation call '{node.func.attr}()' outside the "
                    "_actuate decision-record path — the knob changes "
                    "with no decision record and no evaluation window")

    @staticmethod
    def _controller_module(mod: ModuleInfo) -> bool:
        if "pdnlp_tpu/serve/controller" in mod.path:
            return True
        return any(v.startswith("pdnlp_tpu.serve.controller")
                   or v.endswith(".ServeController")
                   for v in mod.aliases.values())

    @staticmethod
    def _sanctioned(mod: ModuleInfo, node: ast.AST) -> bool:
        fn = mod.enclosing_function(node)
        while fn is not None:
            if getattr(fn, "name", None) in _SANCTIONED:
                return True
            fn = mod.enclosing_function(fn)
        return False
