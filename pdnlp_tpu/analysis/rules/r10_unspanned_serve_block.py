"""R10 — serve dispatch paths that block on device results outside a span.

The serve engine's contract (PR 4, extended by the replica router) is that
every point where the serving path materializes device results lives inside
a tracer span: the ``forward``/``compile``/``queue_wait``/``swap``
vocabulary is what lets ``trace_tpu.py summarize`` build per-replica phase
tables and the trace-diff gate catch latency regressions.  A dispatch path
that calls ``jax.device_get``/``block_until_ready`` on a jitted forward's
output OUTSIDE any span silently swallows device wait — the router looks
fast while a replica's device stream is the bottleneck.

Heuristic, per scope: a *dispatch-shaped* value (assigned from a call whose
name contains ``jit`` or ``forward`` — the serve engine's ``_jit_forward``
idiom) reaching a blocking fetch (``jax.device_get``,
``jax.block_until_ready``, or an ``.block_until_ready()`` method) that is
not lexically inside a ``with <tracer>.span(...)`` block.  ``Tracer.block``
needs no exemption: it contains the barrier itself, so no raw fetch
appears.  Only modules that import from ``pdnlp_tpu.serve`` (or live under
``pdnlp_tpu/serve/``) are in scope — the bench/train layers have their own
timing rules (R4).
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from pdnlp_tpu.analysis.core import Finding, ModuleInfo, Rule, register

_BLOCK_CALLS = {"jax.device_get", "jax.block_until_ready"}
_BLOCK_METHODS = {"block_until_ready"}


def _dispatch_shaped(name: str) -> bool:
    last = name.split(".")[-1].lower()
    return "jit" in last or "forward" in last


@register
class UnspannedServeBlock(Rule):
    rule_id = "R10"
    name = "unspanned-serve-block"
    hint = ("wrap the fetch in a tracer span — `with engine.tracer.span("
            "'forward', ...): out = jax.device_get(logits)` — or use "
            "`Tracer.block(out)` so the device wait lands in its own "
            "device_block span (pdnlp_tpu.obs.trace); the serve/router "
            "dispatch path must never block on device results invisibly")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not self._serve_module(mod):
            return
        for _, scope_node, body in mod.scopes():
            yield from self._check_scope(mod, scope_node, body)

    @staticmethod
    def _serve_module(mod: ModuleInfo) -> bool:
        if "pdnlp_tpu/serve/" in mod.path:
            return True
        return any(v.startswith("pdnlp_tpu.serve")
                   for v in mod.aliases.values())

    def _check_scope(self, mod: ModuleInfo, scope_node, body
                     ) -> Iterator[Finding]:
        own = [n for stmt in body for n in ast.walk(stmt)
               if self._in_scope(mod, scope_node, n)]
        dispatch_vars: Set[str] = set()
        for node in own:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and self._is_dispatch_call(node.value):
                dispatch_vars.add(node.targets[0].id)
        for node in own:
            if not isinstance(node, ast.Call):
                continue
            if not self._is_block_call(mod, node):
                continue
            if not self._touches_dispatch(node, dispatch_vars):
                continue
            if self._inside_span(mod, node):
                continue
            yield self.finding(
                mod, node,
                "serve dispatch path blocks on device results outside any "
                "tracer span — the device wait is invisible to the "
                "per-replica phase tables and the trace-diff gate")

    @staticmethod
    def _is_dispatch_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        fn = node.func
        if isinstance(fn, ast.Attribute):
            return _dispatch_shaped(fn.attr)
        if isinstance(fn, ast.Name):
            return _dispatch_shaped(fn.id)
        return False

    def _is_block_call(self, mod: ModuleInfo, call: ast.Call) -> bool:
        if mod.resolves_to(call.func, _BLOCK_CALLS):
            return True
        return isinstance(call.func, ast.Attribute) \
            and call.func.attr in _BLOCK_METHODS

    def _touches_dispatch(self, call: ast.Call,
                          dispatch_vars: Set[str]) -> bool:
        """The fetch's operand IS (or mentions) a dispatch result — either
        a tracked variable or an inline jit/forward call."""
        targets = list(call.args)
        if isinstance(call.func, ast.Attribute):  # x.block_until_ready()
            targets.append(call.func.value)
        for arg in targets:
            for n in ast.walk(arg):
                if isinstance(n, ast.Name) and n.id in dispatch_vars:
                    return True
                if self._is_dispatch_call(n):
                    return True
        return False

    @staticmethod
    def _inside_span(mod: ModuleInfo, node: ast.AST) -> bool:
        p = mod.parents.get(node)
        while p is not None:
            if isinstance(p, ast.With):
                for item in p.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Call) \
                            and isinstance(ctx.func, ast.Attribute) \
                            and ctx.func.attr == "span":
                        return True
            p = mod.parents.get(p)
        return False

    def _in_scope(self, mod: ModuleInfo, scope_node, node) -> bool:
        fn = mod.enclosing_function(node)
        if isinstance(scope_node, ast.Module):
            return fn is None
        return fn is scope_node or node is scope_node
