"""pdnlp_tpu.analysis — jaxlint, the JAX/TPU tracing-hazard static analyzer.

Pure ``ast`` (no jax import anywhere in the package): the rules catch the
hazard classes that burned this repo before they burn TPU hours —

===  =============================  ==========================================
id   name                           hazard
===  =============================  ==========================================
R1   host-sync-in-jit               ``.item()``/``float()``/``np.asarray``/
                                    ``jax.device_get`` inside traced code
R2   traced-python-branch           ``if``/``while``/``assert`` on traced
                                    values (ConcretizationTypeError/retrace)
R3   prng-key-reuse                 same key consumed twice without a split
R4   unblocked-async-timing         timer deltas around dispatched work with
                                    no completion barrier
R5   train-step-missing-donate      train-step-shaped jit without
                                    ``donate_argnums`` (transient 2x HBM)
R6   unknown-partition-axis         ``PartitionSpec`` axis no mesh declares
R7   device-put-in-step-loop        per-step host->device upload inside a
                                    loop that dispatches a jitted step (the
                                    transport tax ``data.pipeline``'s
                                    resident/prefetch modes eliminate)
===  =============================  ==========================================

(R8-R16 extend the tracing suite to the serve/obs surfaces — see
README.md's rule table.)  The ``concurrency`` suite (T1-T3, *threadlint*)
is whole-program: guard inference / unguarded shared attributes,
lock-order cycles, and blocking calls under a lock — over a module graph
with alias-resolved call edges and class-level attribute type models
(:class:`~pdnlp_tpu.analysis.core.ProgramInfo`).

CLI: ``python lint_tpu.py`` (or ``python -m pdnlp_tpu.analysis``) with
``--suite {tracing,concurrency,all}`` and ``--format {text,json,sarif}``;
library: :func:`analyze_paths`.  Inline suppressions:
``# jaxlint: disable=R1[,T1]``.  The committed
``results/jaxlint_baseline.json`` ratchets tier-1 via
``tests/test_jaxlint.py``: only NEW violations fail.
"""
from pdnlp_tpu.analysis.core import (  # noqa: F401
    Finding, ModuleInfo, Rule, all_rules, parse_module, register, run_rules,
)
from pdnlp_tpu.analysis.cli import analyze_paths, default_paths, main  # noqa: F401
from pdnlp_tpu.analysis import baseline  # noqa: F401
