"""jaxlint baseline — the CI ratchet.

The committed ``results/jaxlint_baseline.json`` records the violations the
tree already carries; the lint (and its tier-1 pytest wrapper) fails only
when a (file, rule) bucket GROWS.  That makes adoption a ratchet, not a
flag day: existing debt is visible and enumerated, new debt is blocked, and
fixing old findings only ever loosens the gate (with a nudge to regenerate
so the ratchet tightens behind the fix).

Comparison is by per-(file, rule) COUNTS, not exact line numbers — editing
an unrelated part of a file shifts every line below it, and a ratchet that
cried wolf on every shift would be deleted within a week.  Recorded lines
are still kept (for humans, and to pick WHICH findings to blame when a
bucket grows).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from pdnlp_tpu.analysis.core import Finding

DEFAULT_BASELINE = os.path.join("results", "jaxlint_baseline.json")


def write(findings: List[Finding], path: str) -> None:
    # hand-written "reason" annotations (why a finding is grandfathered,
    # not fixed) survive regeneration for findings still at the same
    # (file, rule, line)
    reasons: Dict[Tuple[str, str, int], str] = {}
    if os.path.exists(path):
        for e in load(path):
            if "reason" in e:
                reasons[(e["file"], e["rule"], e["line"])] = e["reason"]
    entries = []
    for f in findings:
        d = f.to_dict()
        key = (d["file"], d["rule"], d["line"])
        if key in reasons:
            d["reason"] = reasons[key]
        entries.append(d)
    payload = {
        "version": 1,
        "tool": "lint_tpu.py",
        "note": ("per-(file,rule) violation counts ratchet tier-1; "
                 "regenerate with `python lint_tpu.py --write-baseline` "
                 "after fixing findings; hand-add \"reason\" keys to "
                 "grandfathered entries (kept across regeneration)"),
        "findings": entries,
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def load(path: str) -> List[Dict]:
    with open(path, "r", encoding="utf-8") as f:
        payload = json.load(f)
    return payload.get("findings", [])


def _counts(entries) -> Dict[Tuple[str, str], int]:
    out: Dict[Tuple[str, str], int] = {}
    for e in entries:
        key = (e["file"], e["rule"]) if isinstance(e, dict) \
            else (e.path, e.rule_id)
        out[key] = out.get(key, 0) + 1
    return out


def compare(findings: List[Finding], baseline_entries: List[Dict]
            ) -> Tuple[List[Finding], int]:
    """(new findings, fixed count) vs the baseline.

    A bucket that grew by d blames the d findings whose lines the baseline
    does not record (falling back to the tail of the bucket when lines
    shifted wholesale)."""
    base_counts = _counts(baseline_entries)
    base_lines: Dict[Tuple[str, str], set] = {}
    for e in baseline_entries:
        base_lines.setdefault((e["file"], e["rule"]), set()).add(e["line"])

    new: List[Finding] = []
    cur_counts = _counts(findings)
    for key, cur in sorted(cur_counts.items()):
        base = base_counts.get(key, 0)
        if cur <= base:
            continue
        group = sorted((f for f in findings
                        if (f.path, f.rule_id) == key), key=Finding.sort_key)
        unseen = [f for f in group if f.line not in base_lines.get(key, set())]
        d = cur - base
        blamed = unseen[:d]
        if len(blamed) < d:  # lines shifted wholesale: blame from the tail
            rest = [f for f in group if f not in blamed]
            blamed += rest[-(d - len(blamed)):]
        new.extend(blamed)

    fixed = sum(max(0, base - cur_counts.get(key, 0))
                for key, base in base_counts.items())
    return sorted(new, key=Finding.sort_key), fixed
