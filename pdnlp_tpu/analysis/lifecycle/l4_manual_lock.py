"""L4: a manual ``.acquire()`` must be released on every path.

The repo's locking idiom is ``with self._lock:`` — balanced by
construction, and what the concurrency suite (T1-T3) reasons about.
Manual ``.acquire()``/``.release()`` pairs re-introduce the exact class
of bug ``with`` exists to kill: an early ``return`` or an exception
between the pair leaves the lock held forever and the next acquirer
deadlocked.  L4 flags a manual acquire when ANY path — normal or
exception edge — reaches a function exit without the matching
``.release()`` on the same receiver.

Receivers are classified by the lifecycle model (constructor scan,
whole-program attribute types, then the ``lock``/``mutex``/``cond``
name hint), so bare helper parameters still match.  Conditional
acquires (``if lock.acquire(timeout=...):``) are out of scope — the
result-dependent release needs value tracking, and the repo has no
business writing that shape either.
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from pdnlp_tpu.analysis.cfg import RAISE_EXIT, RETURN_EXIT, _own_walk
from pdnlp_tpu.analysis.core import Finding, ProgramInfo, ProgramRule, register
from pdnlp_tpu.analysis.lifecycle.model import (
    FuncInfo, LifecycleModel, expr_text, get_lifecycle,
)


@register
class UnbalancedManualLock(ProgramRule):
    rule_id = "L4"
    name = "unbalanced-manual-lock"
    suite = "lifecycle"
    hint = ("use `with lock:` (balanced by construction), or release in "
            "a finally: block so exception edges unlock too")

    def check_program(self, prog: ProgramInfo) -> Iterator[Finding]:
        model = get_lifecycle(prog)
        for fi in model.funcs.values():
            if ".acquire(" not in fi.mod.source:
                continue
            yield from self._check_function(model, fi)

    def _check_function(self, model: LifecycleModel,
                        fi: FuncInfo) -> Iterator[Finding]:
        mod, fn = fi.mod, fi.fn
        nested = {n for n in ast.walk(fn)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)) and n is not fn}

        def in_nested(node: ast.AST) -> bool:
            p = mod.parents.get(node)
            while p is not None and p is not fn:
                if p in nested:
                    return True
                p = mod.parents.get(p)
            return False

        acquires = []
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"
                    and not in_nested(node)
                    and model.receiver_kind(mod, fi.owner, fn,
                                            node.func.value) == "lock"):
                acquires.append(node)
        if not acquires:
            return

        cfg = fi.cfg
        for call in acquires:
            stmt = self._nearest_stmt(mod, call, cfg)
            if stmt is None:
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                continue  # context-managed
            if isinstance(stmt, (ast.If, ast.While)) and any(
                    call in ast.walk(t) for t in [stmt.test]):
                continue  # conditional acquire: out of scope
            recv = expr_text(call.func.value)
            released: Set[int] = set()
            for nid, s in cfg.stmts.items():
                if not isinstance(s, ast.stmt):
                    continue
                for n in _own_walk(s):
                    if (isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Attribute)
                            and n.func.attr == "release"
                            and expr_text(n.func.value) == recv):
                        released.add(nid)
                        break
            nid = cfg.node_of(stmt)
            if nid is None:
                continue
            starts = cfg.step_successors(nid)
            exits = cfg.reachable_exits(starts, released)
            if not exits:
                continue
            via = ("an exception edge" if RAISE_EXIT in exits
                   else "a return path")
            path = cfg.path_to_exit(
                starts, released,
                RAISE_EXIT if RAISE_EXIT in exits else RETURN_EXIT)
            esc = cfg.last_line_before(path) if path else None
            where = f" (escape at line {esc})" if esc else ""
            yield self.finding(
                mod, call,
                f"manual `{recv}.acquire()` can reach a function exit "
                f"via {via} without `.release()`{where}")

    @staticmethod
    def _nearest_stmt(mod, node, cfg):
        p = node
        while p is not None:
            if isinstance(p, ast.stmt) and cfg.node_of(p) is not None:
                return p
            p = mod.parents.get(p)
        return None
