"""L1: an acquired resource can reach a function exit unreleased.

For every acquire event (see the registry in ``lifecycle.model``) the
rule asks the CFG: starting from the statement AFTER the acquire, is
there any path — normal or exception edge — that reaches a function
exit without passing a discharge?  Discharges are release calls on the
same receiver, owner-scoped releases (``release_owner``), stores into
``self``-rooted or parameter-rooted state (ownership transferred to a
ledger the runtime audits), returns of the resource (obligation handed
to the caller), and calls into helpers whose summaries release the
argument — the interprocedural inheritance the T1 lock analysis
established.

This is the static face of ``PageAllocator.leak_check()``: the runtime
audit only sees a leak after a drain actually leaks; L1 names the
acquire line whose exception window makes the leak possible.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from pdnlp_tpu.analysis.cfg import RAISE_EXIT, RETURN_EXIT, _own_walk
from pdnlp_tpu.analysis.core import Finding, ProgramInfo, ProgramRule, register
from pdnlp_tpu.analysis.lifecycle.model import (
    ACQUIRE_REGISTRY, AcquireEvent, FuncInfo, LifecycleModel, expr_text,
    get_lifecycle, mentions, root_name, simple_names, _STORE_METHODS,
)


def _spec_for_kind(kind: str):
    for spec in ACQUIRE_REGISTRY:
        if spec.kind == kind:
            return spec
    return None


def alias_closure(fi: FuncInfo, seed: Set[str]) -> Set[str]:
    """Fixpoint alias set: forward links (target assigned FROM a tracked
    value), reverse links through simple compositions (``pin = shared +
    [src]`` tracks ``shared`` too — same pages), and container links (a
    subscript store of a tracked value into a LOCAL container tracks
    the container, so committing the container commits the pages)."""
    names = set(seed)
    if not names:
        return names
    params = set(fi.param_names())
    grew = True
    while grew:
        grew = False
        for node in ast.walk(fi.fn):
            if not isinstance(node, ast.Assign):
                continue
            tgt_names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
            if mentions(node.value, names):
                for t in tgt_names:
                    if t not in names:
                        names.add(t)
                        grew = True
            if any(t in names for t in tgt_names):
                for n in simple_names(node.value):
                    if n not in names:
                        names.add(n)
                        grew = True
            for t in node.targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    rn = root_name(t)
                    if (rn and rn != "self" and rn not in params
                            and rn not in names
                            and mentions(node.value, names)):
                        names.add(rn)  # local container now carries it
                        grew = True
    return names


class _Discharges:
    """Classifies one statement (header only — nested blocks are their
    own CFG nodes) as discharging one event's obligation."""

    def __init__(self, model: LifecycleModel, fi: FuncInfo,
                 event: AcquireEvent, names: Set[str]):
        self.model = model
        self.fi = fi
        self.event = event
        self.names = names
        self.params = set(fi.param_names())

    def _recv_matches(self, recv: ast.AST) -> bool:
        spec = self.event.spec
        if not spec.recv_types and spec.recv_hint is None:
            return True
        text = expr_text(recv)
        if text and text == self.event.recv_text:
            return True
        return self.model.receiver_kind(
            self.fi.mod, self.fi.owner, self.fi.fn, recv) == spec.kind

    def _call_discharges(self, call: ast.Call) -> bool:
        spec = self.event.spec
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr in spec.releasers and self._recv_matches(f.value):
                return True
            # store into self-/param-rooted state via a mutator method
            if f.attr in _STORE_METHODS:
                rn = root_name(f.value)
                if (rn == "self" or rn in self.params) and any(
                        mentions(a, self.names) for a in call.args):
                    return True
        # helper summaries: the callee releases the argument / the kind
        callee = self.model.resolve_callee(self.fi.mod, self.fi.owner,
                                           self.fi.fn, call)
        if callee is not None:
            if spec.kind in callee.releases_kinds:
                return True
            if callee.released_params:
                pnames = callee.param_names()
                for i, a in enumerate(call.args):
                    if i < len(pnames) and pnames[i] in \
                            callee.released_params and \
                            mentions(a, self.names):
                        return True
                for kw in call.keywords:
                    if kw.arg in callee.released_params and \
                            mentions(kw.value, self.names):
                        return True
        return False

    def blocks(self, stmt: ast.AST) -> bool:
        if isinstance(stmt, ast.Return):
            if stmt.value is not None and mentions(stmt.value, self.names):
                return True  # ownership handed to the caller
        if isinstance(stmt, (ast.Assign, ast.AugAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    rn = root_name(t)
                    if (rn == "self" or rn in self.params) and \
                            mentions(stmt.value, self.names):
                        return True  # committed into tracked state
        for node in _own_walk(stmt) if isinstance(stmt, ast.stmt) \
                else iter(()):
            if isinstance(node, ast.Call) and self._call_discharges(node):
                return True
        return False


@register
class LeakedAcquire(ProgramRule):
    rule_id = "L1"
    name = "leaked-acquire"
    suite = "lifecycle"
    hint = ("release the resource on every exit (try/finally or a broad "
            "except that releases and re-raises), transfer it into a "
            "tracked ledger, or return it to the caller")

    def check_program(self, prog: ProgramInfo) -> Iterator[Finding]:
        model = get_lifecycle(prog)
        for fi in model.funcs.values():
            yield from self._check_function(model, fi)

    # ------------------------------------------------------------ helpers
    def _inherited_events(self, model: LifecycleModel,
                          fi: FuncInfo) -> List[AcquireEvent]:
        """Call sites of acquire-returning helpers inherit the
        obligation (``pages = self._reserve(...)`` is an acquire)."""
        out: List[AcquireEvent] = []
        for nid, stmt in list(fi.cfg.stmts.items()):
            if not isinstance(stmt, (ast.Assign, ast.Expr)):
                continue
            value = stmt.value
            if not isinstance(value, ast.Call):
                continue
            callee = model.resolve_callee(fi.mod, fi.owner, fi.fn, value)
            if callee is None or callee.returns_kind is None:
                continue
            spec = _spec_for_kind(callee.returns_kind)
            if spec is None:
                continue
            names: Set[str] = set()
            if isinstance(stmt, ast.Assign):
                names = {t.id for t in stmt.targets
                         if isinstance(t, ast.Name)}
            recv = (expr_text(value.func.value)
                    if isinstance(value.func, ast.Attribute) else "")
            out.append(AcquireEvent(spec, value, stmt, names, recv))
        return out

    def _check_function(self, model: LifecycleModel,
                        fi: FuncInfo) -> Iterator[Finding]:
        events = list(model.events_of(fi))
        events += self._inherited_events(model, fi)
        if not events:
            return
        cfg = fi.cfg
        for event in events:
            spec = event.spec
            names = alias_closure(fi, event.names)
            judge = _Discharges(model, fi, event, names)
            blocked = {nid for nid, stmt in cfg.stmts.items()
                       if judge.blocks(stmt)}
            acq_node = cfg.node_of(event.stmt)
            if acq_node is None or acq_node in blocked:
                continue  # acquired-and-committed in one statement
            starts = cfg.step_successors(acq_node)
            exits = cfg.reachable_exits(starts, blocked)
            via_exc = RAISE_EXIT in exits
            via_ret = RETURN_EXIT in exits and not spec.exc_only
            if not (via_exc or via_ret):
                continue
            exit_id = RAISE_EXIT if via_exc else RETURN_EXIT
            path = cfg.path_to_exit(starts, blocked, exit_id)
            esc = cfg.last_line_before(path) if path else None
            how = ("an exception edge" if via_exc else "a return path")
            where = f" (escape at line {esc})" if esc else ""
            meth = (event.call.func.attr
                    if isinstance(event.call.func, ast.Attribute)
                    else expr_text(event.call.func))
            yield self.finding(
                fi.mod, event.call,
                f"{spec.kind} acquired by `{meth}(...)` can reach a "
                f"function exit via {how} without "
                f"release/transfer{where}",
                spec.hint or None)
