"""L2: hop-chain terminal coverage, statically.

``obs.request.validate_chains`` audits request hop chains at runtime:
every admitted request must record exactly one terminal hop.  It only
sees traffic that ran.  L2 checks the two shapes that produce invalid
chains at the source:

- **orphaned admit**: a function records an ``admit`` hop and can then
  escape on an exception edge with no terminal hop for the same request
  — the caller sees a raise, the chain stays open forever.  (A normal
  return after ``admit`` is the architecture working: the worker thread
  owns the terminal.)
- **double terminal**: two distinct terminal ``record_hop`` sites for
  the same request id where one is reachable from the other.  Terminals
  guarded by the first-wins ``stream._finish(...)`` idiom are exempt —
  that guard is exactly how the runtime enforces at-most-once.

Request identity is matched by the rid argument's expression text
(``stream.rid`` vs ``s.rid`` are different requests), which keeps the
rule honest inside loops over other streams.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from pdnlp_tpu.analysis.cfg import CFG, RAISE_EXIT, build_cfg
from pdnlp_tpu.analysis.core import Finding, ModuleInfo, Rule, register
from pdnlp_tpu.analysis.lifecycle.model import expr_text

#: keep in sync with ``pdnlp_tpu.obs.request.TERMINAL_HOPS`` — the
#: analyzer never imports the modules it scans, so the contract is
#: duplicated here and pinned equal by a test.
TERMINAL_HOPS = ("complete", "deadline", "shed", "rejected", "failed")


class _Hop:
    __slots__ = ("call", "stmt", "hop", "rid", "guarded")

    def __init__(self, call: ast.Call, stmt: ast.stmt, hop: str,
                 rid: str, guarded: bool):
        self.call = call
        self.stmt = stmt
        self.hop = hop
        self.rid = rid
        self.guarded = guarded


def _hop_of(call: ast.Call) -> Optional[Tuple[str, ast.AST]]:
    """(hop name, rid expr) when this is ``record_hop(tracer, rid,
    "<constant>", ...)``; variable hop names are out of scope."""
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if name != "record_hop" or len(call.args) < 3:
        return None
    hop = call.args[2]
    if not (isinstance(hop, ast.Constant) and isinstance(hop.value, str)):
        return None
    return hop.value, call.args[1]


#: the first-wins completion guards: ``DecodeStream._finish`` and the
#: batcher/fleet request's ``_complete`` both return True exactly once
_FIRST_WINS_GUARDS = ("_finish", "_complete")


def _finish_guarded(mod: ModuleInfo, node: ast.AST, fn: ast.AST) -> bool:
    """Is ``node`` under an ``if X._finish(...):`` /
    ``if X._complete(...):`` first-wins guard?"""
    p = mod.parents.get(node)
    while p is not None and p is not fn:
        if isinstance(p, ast.If):
            for n in ast.walk(p.test):
                if isinstance(n, ast.Call) and isinstance(
                        n.func, ast.Attribute) \
                        and n.func.attr in _FIRST_WINS_GUARDS:
                    return True
        p = mod.parents.get(p)
    return False


@register
class TerminalCoverage(Rule):
    rule_id = "L2"
    name = "terminal-coverage"
    suite = "lifecycle"
    hint = ("an admitted request must reach exactly one terminal hop "
            "(complete/deadline/shed/rejected/failed): record a terminal "
            "before re-raising, and guard terminals with the first-wins "
            "stream._finish(...) idiom")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if "record_hop" not in mod.source:
            return
        for name, fn, body in mod.scopes():
            if name == "<module>" or isinstance(fn, ast.Lambda):
                continue
            yield from self._check_function(mod, fn)

    def _check_function(self, mod: ModuleInfo,
                        fn: ast.AST) -> Iterator[Finding]:
        nested = {n for n in ast.walk(fn)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)) and n is not fn}

        def in_nested(node: ast.AST) -> bool:
            p = mod.parents.get(node)
            while p is not None and p is not fn:
                if p in nested:
                    return True
                p = mod.parents.get(p)
            return False

        hops: List[_Hop] = []
        cfg: Optional[CFG] = None
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or in_nested(node):
                continue
            parsed = _hop_of(node)
            if parsed is None:
                continue
            if cfg is None:
                cfg = build_cfg(fn)
            hop_name, rid_expr = parsed
            stmt = self._nearest_stmt(mod, node, cfg)
            if stmt is None:
                continue
            hops.append(_Hop(node, stmt, hop_name, expr_text(rid_expr),
                             _finish_guarded(mod, node, fn)))
        if cfg is None:
            return

        terminals = [h for h in hops if h.hop in TERMINAL_HOPS]

        # ---- orphaned admit: exception escape with no terminal
        for h in hops:
            if h.hop != "admit":
                continue
            blocked = {cfg.node_of(t.stmt) for t in terminals
                       if t.rid == h.rid}
            blocked.discard(None)
            nid = cfg.node_of(h.stmt)
            if nid is None:
                continue
            starts = cfg.step_successors(nid)
            if RAISE_EXIT in cfg.reachable_exits(starts, blocked):
                path = cfg.path_to_exit(starts, blocked, RAISE_EXIT)
                esc = cfg.last_line_before(path) if path else None
                where = f" (escape at line {esc})" if esc else ""
                yield self.finding(
                    mod, h.call,
                    f"request {h.rid!r} is admitted here but an exception "
                    f"path can escape with no terminal hop{where}")

        # ---- double terminal: one unguarded terminal reaches another
        seen_pairs = set()
        unguarded = [t for t in terminals if not t.guarded]
        for t1 in unguarded:
            n1 = cfg.node_of(t1.stmt)
            if n1 is None:
                continue
            starts = cfg.step_successors(n1)
            for t2 in unguarded:
                if t2 is t1 or t2.rid != t1.rid:
                    continue
                n2 = cfg.node_of(t2.stmt)
                if n2 is None:
                    continue
                key = frozenset((n1, n2))
                if key in seen_pairs:
                    continue
                if self._reaches(cfg, starts, n2):
                    seen_pairs.add(key)
                    yield self.finding(
                        mod, t2.call,
                        f"request {t2.rid!r} can record a second terminal "
                        f"hop {t2.hop!r} here (first terminal "
                        f"{t1.hop!r} at line {t1.call.lineno}); guard "
                        "terminals with the first-wins _finish() idiom")

    @staticmethod
    def _nearest_stmt(mod: ModuleInfo, node: ast.AST,
                      cfg: CFG) -> Optional[ast.AST]:
        p = node
        while p is not None:
            if isinstance(p, ast.stmt) and cfg.node_of(p) is not None:
                return p
            p = mod.parents.get(p)
        return None

    @staticmethod
    def _reaches(cfg: CFG, starts, target: int) -> bool:
        seen = set()
        stack = list(starts)
        while stack:
            nid = stack.pop()
            if nid == target:
                return True
            if nid in seen or nid in (RAISE_EXIT,):
                continue
            seen.add(nid)
            stack += [t for t, _k in cfg.succ.get(nid, [])]
        return False
