"""L3: checkpoint/manifest writes must use the atomic publish protocol.

``train.checkpoint`` publishes every durable artifact the same way:
write to ``path + ".tmp"``, flush, fsync, ``os.replace`` into place
(``_atomic_write_bytes`` / ``write_json_atomic`` / ``publish``).  A
reader — the fleet watcher, a resume, a human — can then never observe
a torn file.  L3 flags direct writes that bypass the protocol on paths
that look like watched publish artifacts.

Scope is deliberately conservative (this rule must not bury the repo's
plain results/log writers in noise): a write is only judged when its
path expression *textually* looks watched — mentions ``ckpt`` /
``checkpoint`` / ``manifest`` / ``.msgpack`` / ``best.json`` /
``publish`` — and is only sanctioned when the SAME function hands the
written path to ``os.replace``/``os.rename`` (the tmp half of the
protocol) or delegates to one of the sanctioned writers.  Extend
:data:`WATCHED_PATH_RE` to put more artifacts under the contract.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set

from pdnlp_tpu.analysis.core import Finding, ModuleInfo, Rule, register
from pdnlp_tpu.analysis.lifecycle.model import expr_text

#: path expressions that are "watched": publish artifacts someone else
#: reads concurrently.  The extension point for new artifact families.
WATCHED_PATH_RE = re.compile(
    r"ckpt|checkpoint|manifest|\.msgpack|best\.json|best_json|publish",
    re.IGNORECASE)

#: callables that already implement (or ride) the atomic protocol
_SANCTIONED_WRITERS = {
    "write_json_atomic", "_atomic_write_bytes", "publish", "submit_json",
}

_WRITE_MODES = ("w", "a", "x")


def _open_write_path(call: ast.Call) -> Optional[ast.AST]:
    """The path argument when ``call`` is ``open(path, "w"/"wb"/...)``."""
    f = call.func
    if not (isinstance(f, ast.Name) and f.id == "open"):
        return None
    if not call.args:
        return None
    mode: Optional[str] = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            mode = kw.value.value
    if mode is None or not mode.startswith(_WRITE_MODES):
        return None
    return call.args[0]


@register
class NonAtomicPublish(Rule):
    rule_id = "L3"
    name = "non-atomic-publish"
    suite = "lifecycle"
    hint = ("publish watched artifacts crash-atomically: "
            "checkpoint.write_json_atomic(path, obj), or write to "
            "path+'.tmp', flush+fsync, then os.replace(tmp, path)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if "open(" not in mod.source:
            return
        for name, fn, body in mod.scopes():
            if isinstance(fn, ast.Lambda):
                continue
            yield from self._check_scope(mod, fn, body)

    def _check_scope(self, mod: ModuleInfo, fn: ast.AST,
                     body: List[ast.stmt]) -> Iterator[Finding]:
        nested = {n for stmt in body for n in ast.walk(stmt)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)) and n is not fn}

        def in_nested(node: ast.AST) -> bool:
            p = mod.parents.get(node)
            while p is not None and p is not fn:
                if p in nested:
                    return True
                p = mod.parents.get(p)
            return False

        calls = [n for stmt in body for n in ast.walk(stmt)
                 if isinstance(n, ast.Call) and not in_nested(n)]

        # the tmp half of the protocol: paths handed to os.replace/rename
        replaced: Set[str] = set()
        for c in calls:
            if mod.resolve(c.func) in ("os.replace", "os.rename") and c.args:
                replaced.add(expr_text(c.args[0]))

        # local name -> the expression it was assigned from (one hop),
        # so `p = dir + "/ckpt.msgpack"; open(p, "w")` is judged by the
        # RHS text too
        assigned: dict = {}
        for stmt in body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name):
                    assigned[n.targets[0].id] = n.value

        def path_text(path_expr: ast.AST) -> str:
            text = expr_text(path_expr)
            if isinstance(path_expr, ast.Name) and \
                    path_expr.id in assigned:
                text += " " + expr_text(assigned[path_expr.id])
            return text

        for c in calls:
            path_expr = _open_write_path(c)
            if path_expr is None:
                continue
            text = path_text(path_expr)
            if not WATCHED_PATH_RE.search(text):
                continue
            if expr_text(path_expr) in replaced:
                continue  # tmp file later os.replace'd: the protocol
            if isinstance(path_expr, ast.Name) and \
                    path_expr.id in assigned and \
                    expr_text(assigned[path_expr.id]) in replaced:
                continue
            fname = getattr(fn, "name", "<module>")
            if fname in _SANCTIONED_WRITERS:
                continue
            yield self.finding(
                mod, c,
                f"watched artifact written non-atomically "
                f"(open({expr_text(path_expr)!r}, write mode) with no "
                "os.replace of that path in this function)")
