"""leaklint — the lifecycle suite (rules L1-L4).

Path-sensitive must-release / exactly-one-terminal analyses over the
:mod:`pdnlp_tpu.analysis.cfg` control-flow graphs, with the same
interprocedural spine as the concurrency suite: helper functions
inherit acquire/release obligations from their call sites.

Importing this package registers the rules (the same side-effect
contract as ``analysis.rules`` and ``analysis.concurrency``):

- **L1 leaked-acquire** — an acquire (``PageAllocator.alloc``/``share``,
  semaphore ``.acquire()``, standby ``deactivate_replica``, tmp-file
  creation) whose resource can reach a function exit — including
  exception edges — without release/``release_owner``/ownership
  transfer (a store into a tracked ledger/table counts as transfer).
- **L2 terminal-coverage** — a path that records an ``admit`` hop but
  can escape on an exception with no terminal hop, or that can record
  two unguarded terminals (the static face of
  ``obs.request.validate_chains``).
- **L3 non-atomic-publish** — a checkpoint/manifest write that bypasses
  the ``write_json_atomic`` / tmp+fsync+``os.replace`` protocol.
- **L4 unbalanced-manual-lock** — a manual ``.acquire()`` that can exit
  without its ``.release()`` on some path (use ``with`` or
  try/finally).
"""
from pdnlp_tpu.analysis.lifecycle import (  # noqa: F401
    l1_leaked_acquire,
    l2_terminal_coverage,
    l3_atomic_publish,
    l4_manual_lock,
)
