"""Shared machinery for the lifecycle suite: the acquire registry,
receiver classification, per-function acquire events with alias
closure, and the interprocedural obligation summaries.

The acquire registry is the extension point: each :class:`AcquireSpec`
names the calls that create an obligation, what counts as discharging
it, and how strictly the receiver must be identified.  Receivers are
classified three ways, best evidence first: a constructor the model saw
(``self._sem = threading.Semaphore(...)``), the program-wide type
inference (:meth:`ProgramInfo.expr_type` resolving to ``PageAllocator``),
then a conservative name hint (``alloc`` / ``sem`` / ``lock`` in the
receiver's dotted text) so un-annotated helper parameters still match.

Discharge is deliberately broader than release: returning the resource
hands the obligation to the caller; storing it into ``self``-rooted
state (a page table, a pending-COW list, an LRU ledger) transfers
ownership to the object; and a call into a helper whose summary says
"releases this parameter" (or "releases everything of this kind")
discharges at the call site — the same inheritance direction T1 uses
for lock facts.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from pdnlp_tpu.analysis.cfg import CFG, build_cfg
from pdnlp_tpu.analysis.core import (
    ClassModel, ModuleInfo, ProgramInfo, dotted_name,
)

# ------------------------------------------------------------------ registry

@dataclasses.dataclass(frozen=True)
class AcquireSpec:
    """One acquire/release protocol the L1 analysis enforces."""

    kind: str                       # short id used in messages/summaries
    methods: FrozenSet[str]         # method names that acquire
    releasers: FrozenSet[str]       # method names that discharge
    funcs: FrozenSet[str] = frozenset()   # dotted callables that acquire
    #: methods whose FIRST ARGUMENT is the resource (``share(pages,
    #: owner)``); all other acquires bind their resource to the result
    arg_methods: FrozenSet[str] = frozenset()
    recv_types: FrozenSet[str] = frozenset()  # class simple names / dotted
    recv_hint: Optional[str] = None  # substring of receiver text (lowered)
    #: True: a leak is only a leak when the escape is an exception edge
    #: (the normal-path "release" lives in another function by design —
    #: e.g. standby deactivation is re-activated by a later control law)
    exc_only: bool = False
    hint: str = ""


def _fs(*items: str) -> FrozenSet[str]:
    return frozenset(items)


#: the default registry.  Extend by appending an :class:`AcquireSpec`
#: (tests monkeypatch this; downstream repos can too).
ACQUIRE_REGISTRY: Tuple[AcquireSpec, ...] = (
    AcquireSpec(
        kind="kv-pages",
        methods=_fs("alloc", "share"),
        arg_methods=_fs("share"),
        # ``transfer`` discharges the SENDER side of a custody move; the
        # disaggregation staging wrapper ``stage_handoff`` (which calls
        # transfer onto the staged owner and returns that key) CREATES
        # the receiver-side obligation — its result owes a
        # release_owner on every dispatch outcome
        funcs=_fs("pdnlp_tpu.serve.kvpage.stage_handoff"),
        releasers=_fs("release", "release_owner", "release_if_idle",
                      "transfer"),
        recv_types=_fs("PageAllocator"),
        recv_hint="alloc",
        hint="release/release_owner the pages on every exit (wrap the "
             "post-acquire tail in try/except BaseException), or commit "
             "them into the page table / a ledger before anything can "
             "raise",
    ),
    AcquireSpec(
        kind="handoff-conn",
        methods=_fs(),
        funcs=_fs("pdnlp_tpu.serve.handoff.HandoffChannel",
                  "socket.create_connection"),
        releasers=_fs("close"),
        hint="close the handoff channel/socket on every path "
             "(try/finally or use it as a context manager), or commit "
             "it into the router's channel table before anything can "
             "raise",
    ),
    AcquireSpec(
        kind="semaphore",
        methods=_fs("acquire"),
        releasers=_fs("release"),
        recv_types=_fs("threading.Semaphore", "threading.BoundedSemaphore"),
        recv_hint="sem",
        hint="pair .acquire() with .release() in a finally, or use "
             "`with sem:`",
    ),
    AcquireSpec(
        kind="standby",
        methods=_fs("deactivate_replica"),
        releasers=_fs("activate_replica"),
        exc_only=True,
        hint="an exception between deactivate_replica and the state "
             "commit strands the replica in standby — reactivate on "
             "failure or record the index first",
    ),
    AcquireSpec(
        kind="tmpfile",
        methods=_fs(),
        funcs=_fs("tempfile.mkstemp", "tempfile.mkdtemp",
                  "tempfile.NamedTemporaryFile"),
        releasers=_fs("remove", "unlink", "replace", "rename", "rmtree",
                      "move", "cleanup", "close"),
        hint="remove/os.replace the temp artifact on every path "
             "(try/finally), or use it as a context manager",
    ),
)

#: constructor dotted names -> receiver kind, for receivers the
#: whole-program type inference cannot see (stdlib primitives)
CTOR_KINDS: Dict[str, str] = {
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "threading.Condition": "lock",
    "multiprocessing.Lock": "lock",
    "multiprocessing.RLock": "lock",
}

#: scanned resource classes -> receiver kind
RESOURCE_CLASSES: Dict[str, str] = {
    "PageAllocator": "kv-pages",
}

#: mutating container methods that, on a ``self``-rooted receiver,
#: count as storing the resource into tracked object state
_STORE_METHODS = _fs("append", "appendleft", "add", "insert", "extend",
                     "update", "setdefault", "put", "put_nowait")


def expr_text(node: ast.AST) -> str:
    dn = dotted_name(node)
    if dn is not None:
        return dn
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - very old ast shapes
        return ""


def _hint_kind(text: str) -> Optional[str]:
    low = text.lower()
    if "alloc" in low:
        return "kv-pages"
    if "sem" in low:
        return "semaphore"
    if "lock" in low or "mutex" in low or "cond" in low:
        return "lock"
    return None


def simple_names(expr: ast.AST) -> Set[str]:
    """Names composing a *simple* value expression (names, containers of
    names, concatenations) — what reverse alias linking accepts.  A call
    result is a new value, so calls contribute nothing here."""
    out: Set[str] = set()

    def walk(e: ast.AST) -> None:
        if isinstance(e, ast.Name):
            out.add(e.id)
        elif isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            for v in e.elts:
                walk(v)
        elif isinstance(e, ast.BinOp):
            walk(e.left)
            walk(e.right)
        elif isinstance(e, ast.Starred):
            walk(e.value)
        elif isinstance(e, ast.IfExp):
            walk(e.body)
            walk(e.orelse)

    walk(expr)
    return out


def mentions(expr: ast.AST, names: Set[str]) -> bool:
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(expr))


def root_name(target: ast.AST) -> Optional[str]:
    """The base Name of a Subscript/Attribute chain (``self`` for
    ``self._table[slot]``), or None."""
    node = target
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


# ------------------------------------------------------------------- events

class AcquireEvent:
    """One acquire call inside one function: the spec it matched, the
    statement it lives in, the resource names it binds, and its
    receiver text (release calls on the same receiver discharge it)."""

    __slots__ = ("spec", "call", "stmt", "names", "recv_text")

    def __init__(self, spec: AcquireSpec, call: ast.Call, stmt: ast.stmt,
                 names: Set[str], recv_text: str):
        self.spec = spec
        self.call = call
        self.stmt = stmt
        self.names = names
        self.recv_text = recv_text


class FuncInfo:
    """Per-function lifecycle facts, computed lazily and cached."""

    __slots__ = ("key", "mod", "fn", "owner", "events", "returns_kind",
                 "released_params", "releases_kinds", "_cfg")

    def __init__(self, key: str, mod: ModuleInfo, fn: ast.AST,
                 owner: Optional[ClassModel]):
        self.key = key
        self.mod = mod
        self.fn = fn
        self.owner = owner
        self.events: List[AcquireEvent] = []
        #: spec kind when this function acquires and RETURNS the
        #: resource — its call sites inherit the obligation
        self.returns_kind: Optional[str] = None
        #: parameter names this function releases (caller-side discharge
        #: of arguments passed in those positions)
        self.released_params: Set[str] = set()
        #: kinds for which this function calls an owner-scoped releaser
        #: (``release_owner`` and friends) — a call discharges every
        #: event of that kind at the call site
        self.releases_kinds: Set[str] = set()
        self._cfg: Optional[CFG] = None

    @property
    def cfg(self) -> CFG:
        if self._cfg is None:
            self._cfg = build_cfg(self.fn)
        return self._cfg

    def param_names(self) -> List[str]:
        args = getattr(self.fn, "args", None)
        if args is None:
            return []
        names = [a.arg for a in list(args.posonlyargs) + list(args.args)]
        if self.owner is not None and names and names[0] in ("self", "cls"):
            names = names[1:]
        return names


def func_key(owner: Optional[ClassModel], mod: ModuleInfo,
             fn: ast.AST) -> str:
    name = getattr(fn, "name", "<lambda>")
    if owner is not None:
        return f"m:{owner.qualname}.{name}"
    return f"f:{mod.path}:{name}:{getattr(fn, 'lineno', 0)}"


# -------------------------------------------------------------------- model

class LifecycleModel:
    """Whole-program lifecycle facts: ctor-classified receivers, per-
    function acquire events, and the helper summaries the interprocedural
    discharge matching reads.  Built once per :class:`ProgramInfo` and
    cached on it (:func:`get_lifecycle`)."""

    def __init__(self, prog: ProgramInfo):
        self.prog = prog
        #: (class qualname, attr) -> receiver kind, from ctor scans
        self._attr_kinds: Dict[Tuple[str, str], str] = {}
        #: id(fn) -> {local name -> receiver kind}
        self._local_kinds: Dict[int, Dict[str, str]] = {}
        self._env_cache: Dict[int, Dict[str, str]] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        self._by_node: Dict[int, FuncInfo] = {}
        self._scan_ctors()
        self._scan_functions()
        self._summarize()

    # ------------------------------------------------------------ ctor scan
    def _ctor_kind(self, mod: ModuleInfo, value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        resolved = mod.resolve(value.func)
        if resolved in CTOR_KINDS:
            return CTOR_KINDS[resolved]
        cm = self.prog.resolve_class(mod, value.func)
        if cm is not None and cm.name in RESOURCE_CLASSES:
            return RESOURCE_CLASSES[cm.name]
        return None

    def _scan_ctors(self) -> None:
        for mod in self.prog.modules.values():
            for cm in [c for c in self.prog.classes.values()
                       if c.mod is mod]:
                for meth in cm.methods.values():
                    for node in ast.walk(meth):
                        if not (isinstance(node, ast.Assign)
                                and len(node.targets) == 1):
                            continue
                        t = node.targets[0]
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            kind = self._ctor_kind(mod, node.value)
                            if kind is not None:
                                self._attr_kinds[(cm.qualname, t.attr)] = kind

    def _locals_of(self, mod: ModuleInfo, fn: ast.AST) -> Dict[str, str]:
        cached = self._local_kinds.get(id(fn))
        if cached is None:
            cached = {}
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    kind = self._ctor_kind(mod, node.value)
                    if kind is not None:
                        cached[node.targets[0].id] = kind
            self._local_kinds[id(fn)] = cached
        return cached

    def _env_of(self, mod: ModuleInfo, fn: ast.AST) -> Dict[str, str]:
        env = self._env_cache.get(id(fn))
        if env is None:
            env = self.prog.local_env(mod, fn)
            self._env_cache[id(fn)] = env
        return env

    # -------------------------------------------------- receiver classify
    def receiver_kind(self, mod: ModuleInfo, owner: Optional[ClassModel],
                      fn: ast.AST, recv: ast.AST) -> Optional[str]:
        if (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self" and owner is not None):
            kind = self._attr_kinds.get((owner.qualname, recv.attr))
            if kind is not None:
                return kind
        if isinstance(recv, ast.Name):
            kind = self._locals_of(mod, fn).get(recv.id)
            if kind is not None:
                return kind
        t = self.prog.expr_type(mod, owner, self._env_of(mod, fn), recv)
        if t is not None:
            if t in CTOR_KINDS:
                return CTOR_KINDS[t]
            simple = t.split(".")[-1]
            if simple in RESOURCE_CLASSES:
                return RESOURCE_CLASSES[simple]
        return _hint_kind(expr_text(recv))

    def _spec_matches_recv(self, spec: AcquireSpec, mod: ModuleInfo,
                           owner: Optional[ClassModel], fn: ast.AST,
                           recv: ast.AST) -> bool:
        if not spec.recv_types and spec.recv_hint is None:
            return True  # method name alone identifies the protocol
        kind = self.receiver_kind(mod, owner, fn, recv)
        return kind == spec.kind

    def match_acquire(self, mod: ModuleInfo, owner: Optional[ClassModel],
                      fn: ast.AST, call: ast.Call) -> Optional[AcquireSpec]:
        if isinstance(call.func, ast.Attribute):
            meth = call.func.attr
            for spec in ACQUIRE_REGISTRY:
                if meth in spec.methods and self._spec_matches_recv(
                        spec, mod, owner, fn, call.func.value):
                    return spec
        resolved = mod.resolve(call.func)
        if resolved is not None:
            for spec in ACQUIRE_REGISTRY:
                if resolved in spec.funcs:
                    return spec
        return None

    # ------------------------------------------------------ function scan
    def _scan_functions(self) -> None:
        for mod in self.prog.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                owner = self.prog.owner_class(mod, node)
                fi = FuncInfo(func_key(owner, mod, node), mod, node, owner)
                self.funcs.setdefault(fi.key, fi)
                self._by_node[id(node)] = fi

    def info_for(self, fn: ast.AST) -> Optional[FuncInfo]:
        return self._by_node.get(id(fn))

    def resolve_callee(self, mod: ModuleInfo, owner: Optional[ClassModel],
                       fn: ast.AST, call: ast.Call) -> Optional[FuncInfo]:
        f = call.func
        if isinstance(f, ast.Attribute):
            recv_cm: Optional[ClassModel] = None
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and owner is not None:
                recv_cm = owner
            else:
                t = self.prog.expr_type(mod, owner, self._env_of(mod, fn),
                                        f.value)
                if t is not None:
                    recv_cm = self.prog.classes.get(t)
            if recv_cm is not None:
                target = recv_cm.methods.get(f.attr)
                if target is not None:
                    return self._by_node.get(id(target))
            return None
        qual = self.prog.resolve_function(mod, f)
        if qual is not None:
            found = self.prog.function_named(qual)
            if found is not None:
                return self._by_node.get(id(found[1]))
        return None

    # -------------------------------------------------------- event layer
    def _nearest_stmt(self, mod: ModuleInfo, node: ast.AST,
                      cfg: CFG) -> Optional[ast.AST]:
        p: Optional[ast.AST] = node
        while p is not None:
            if isinstance(p, ast.stmt) and cfg.node_of(p) is not None:
                return p
            p = mod.parents.get(p)
        return None

    def events_of(self, fi: FuncInfo) -> List[AcquireEvent]:
        """Acquire events in ``fi`` (cached).  ``with``-managed acquires
        and acquires whose result is immediately returned (obligation
        handed to the caller) are excluded — the latter instead marks
        the function as acquire-returning for its call sites."""
        if fi.events:
            return fi.events
        mod, fn, owner = fi.mod, fi.fn, fi.owner
        nested = {n for n in ast.walk(fn)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)) and n is not fn}

        def in_nested(node: ast.AST) -> bool:
            p = mod.parents.get(node)
            while p is not None and p is not fn:
                if p in nested:
                    return True
                p = mod.parents.get(p)
            return False

        events: List[AcquireEvent] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or in_nested(node):
                continue
            spec = self.match_acquire(mod, owner, fn, node)
            if spec is None:
                continue
            stmt = self._nearest_stmt(mod, node, fi.cfg)
            if stmt is None:
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)) and any(
                    node in ast.walk(item.context_expr)
                    for item in stmt.items):
                continue  # context-managed: released by construction
            names: Set[str] = set()
            recv_text = (expr_text(node.func.value)
                         if isinstance(node.func, ast.Attribute) else "")
            meth = (node.func.attr
                    if isinstance(node.func, ast.Attribute) else None)
            committed = False
            if isinstance(stmt, ast.Assign) and node in ast.walk(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, (ast.Attribute, ast.Subscript)) \
                            and root_name(t) == "self":
                        committed = True  # stored into object state at birth
            if committed and not names:
                continue
            if not names and meth in spec.arg_methods and node.args:
                names |= simple_names(node.args[0])
            if isinstance(stmt, ast.Return):
                fi.returns_kind = spec.kind
                continue
            events.append(AcquireEvent(spec, node, stmt, names, recv_text))
        fi.events = events
        return events

    # --------------------------------------------------------- summaries
    def _summarize(self) -> None:
        for fi in self.funcs.values():
            params = set(fi.param_names())
            self.events_of(fi)  # populates returns_kind as a side effect
            for node in ast.walk(fi.fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                meth = node.func.attr
                for spec in ACQUIRE_REGISTRY:
                    if meth not in spec.releasers:
                        continue
                    if not self._spec_matches_recv(spec, fi.mod, fi.owner,
                                                   fi.fn, node.func.value):
                        continue
                    arg_names = {n for a in node.args
                                 for n in simple_names(a)}
                    hit = arg_names & params
                    if hit:
                        fi.released_params |= hit
                    else:
                        # owner-scoped release (release_owner et al):
                        # discharges every same-kind obligation around
                        # the call site
                        fi.releases_kinds.add(spec.kind)


def get_lifecycle(prog: ProgramInfo) -> LifecycleModel:
    model = getattr(prog, "_lifecycle_model", None)
    if model is None:
        model = LifecycleModel(prog)
        prog._lifecycle_model = model
    return model
