"""Intraprocedural control-flow graphs with exception edges — the
path-sensitivity layer under the lifecycle suite (L1-L4).

The tracing rules (R*) judge single statements; the concurrency rules
(T*) judge the whole thread mesh; the lifecycle rules ask a question
neither can answer: *can this statement's effect reach a function exit
along SOME path without a matching counter-effect?*  That needs a CFG —
including the paths the interpreter takes when a statement raises.

Design (deliberately small — this is a linter, not a compiler):

- **statement granularity**: every ``ast.stmt`` (and every
  ``ast.ExceptHandler``) is one node; basic blocks would only compress
  what reachability walks anyway at this scale.
- **two edge kinds**: ``"step"`` (normal completion) and ``"exc"`` (the
  statement raised).  A statement gets exception edges when it plausibly
  raises: ``raise``/``assert``, or any call not in
  :data:`NO_RAISE_CALLS` (attribute loads, arithmetic and subscript
  stores are treated as non-raising — modelling MemoryError-grade
  failure would drown every rule in noise).
- **synthetic exits**: :data:`RETURN_EXIT` (fell off the end /
  ``return``) and :data:`RAISE_EXIT` (an exception escaped the
  function).  These are the targets lifecycle rules test reachability
  against.
- **try/except**: a raising statement in the body gets an ``exc`` edge
  to EVERY handler, plus an escape edge past the handlers unless one of
  them is broad (bare ``except``, ``Exception``, ``BaseException``) —
  that is exactly how ``except KVPagesExhausted:`` fails to cover an
  ``AssertionError`` between an alloc and its table commit.
- **try/finally**: every way out of the protected region (normal,
  exception, ``return``/``break``/``continue``) routes through the
  ``finally`` body, whose exit then fans out to every continuation the
  region could have taken.  The fan-out over-approximates (a path may
  "return" and then also continue) — safe for must-release analysis,
  where extra paths can only make the rule MORE demanding, and the
  release-in-finally idiom dominates the fan-out either way.
- **with**: the body runs with the same exception context (we assume
  context managers do not swallow exceptions); the acquire-site rules
  treat ``with``-managed resources as released by construction.

Loops keep their back edge, so reachability naturally covers the
leak-on-second-iteration shapes without any special casing.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: synthetic exit reached by ``return`` statements and by falling off
#: the end of the function body
RETURN_EXIT = -1
#: synthetic exit reached when an exception escapes the function
RAISE_EXIT = -2

EXITS = (RETURN_EXIT, RAISE_EXIT)

#: calls assumed not to raise in practice — the containment keeps
#: exception edges meaningful instead of universal.  Matched against the
#: LAST segment of the callee's dotted name, so both ``x.append`` and
#: ``collections.deque.append`` hit.
NO_RAISE_CALLS = frozenset({
    # containers / queues / sets
    "append", "appendleft", "extend", "add", "discard", "update",
    "setdefault", "get", "items", "keys", "values", "copy", "clear",
    # threading signalling (never raises once constructed)
    "notify", "notify_all", "set", "is_set", "release_owner_hint",
    # metrics / tracing (designed to be fail-safe on the hot path)
    "inc", "dec", "observe", "record", "record_hop", "labels",
    # string ops
    "join", "split", "strip", "lstrip", "rstrip", "format", "lower",
    "upper", "startswith", "endswith", "replace_text",
    # clocks
    "monotonic", "perf_counter", "time",
    # benign builtins
    "len", "isinstance", "hasattr", "getattr", "id", "repr", "str",
    "bool", "abs", "min", "max", "sum", "sorted", "range", "enumerate",
    "zip", "print", "callable", "type", "int", "float", "tuple",
    "list", "dict", "frozenset",
})

_BROAD_EXC = {"Exception", "BaseException"}


def stmt_can_raise(stmt: ast.stmt) -> bool:
    """Does executing ``stmt``'s own code (not its nested block bodies)
    plausibly raise?  Drives where ``exc`` edges are drawn."""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    for node in _own_walk(stmt):
        if isinstance(node, ast.Call):
            name = _callee_tail(node)
            if name is None or name not in NO_RAISE_CALLS:
                return True
        elif isinstance(node, (ast.Await, ast.Yield, ast.YieldFrom)):
            return True
    return False


def _callee_tail(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


_BLOCK_FIELDS = ("body", "orelse", "finalbody", "handlers")


def _own_walk(stmt: ast.stmt):
    """Walk ``stmt``'s header expressions only — the nested statement
    blocks (``body``/``orelse``/...) are separate CFG nodes."""
    todo: List[ast.AST] = []
    for field, value in ast.iter_fields(stmt):
        if field in _BLOCK_FIELDS:
            continue
        if isinstance(value, ast.AST):
            todo.append(value)
        elif isinstance(value, list):
            todo += [v for v in value if isinstance(v, ast.AST)]
    while todo:
        node = todo.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # nested defs don't run here
        todo += list(ast.iter_child_nodes(node))
    return


class CFG:
    """One function's control-flow graph.  ``stmts`` maps node id ->
    the ``ast.stmt`` / ``ast.ExceptHandler`` it models; ``succ`` maps
    node id -> ``[(successor id, "step"|"exc"), ...]``; ``entry`` is
    the first node (or :data:`RETURN_EXIT` for an empty body)."""

    def __init__(self) -> None:
        self.stmts: Dict[int, ast.AST] = {}
        self.succ: Dict[int, List[Tuple[int, str]]] = {}
        self.entry: int = RETURN_EXIT

    # ------------------------------------------------------------ queries
    def nodes_for(self, stmt: ast.AST) -> List[int]:
        return [nid for nid, s in self.stmts.items() if s is stmt]

    def node_of(self, stmt: ast.AST) -> Optional[int]:
        for nid, s in self.stmts.items():
            if s is stmt:
                return nid
        return None

    def step_successors(self, nid: int) -> List[int]:
        return [t for t, kind in self.succ.get(nid, []) if kind == "step"]

    def reachable_exits(self, starts: Sequence[int],
                        blocked: Set[int]) -> Set[int]:
        """Which synthetic exits are reachable from ``starts`` without
        entering a ``blocked`` node — the core must-release query."""
        seen: Set[int] = set()
        stack = [s for s in starts if s not in blocked]
        exits: Set[int] = set()
        while stack:
            nid = stack.pop()
            if nid in seen:
                continue
            seen.add(nid)
            if nid in EXITS:
                exits.add(nid)
                continue
            for t, _kind in self.succ.get(nid, []):
                if t not in blocked and t not in seen:
                    stack.append(t)
        return exits

    def path_to_exit(self, starts: Sequence[int], blocked: Set[int],
                     exit_id: int) -> Optional[List[int]]:
        """One concrete blocked-avoiding path (list of node ids) from
        ``starts`` to ``exit_id`` — for human-readable findings.  BFS,
        so the reported path is a shortest one."""
        from collections import deque
        prev: Dict[int, int] = {}
        q = deque(s for s in starts if s not in blocked)
        seen = set(q)
        while q:
            nid = q.popleft()
            if nid == exit_id:
                path = [nid]
                while path[-1] in prev:
                    path.append(prev[path[-1]])
                return list(reversed(path))
            if nid in EXITS:
                continue
            for t, _kind in self.succ.get(nid, []):
                if t not in blocked and t not in seen:
                    seen.add(t)
                    prev[t] = nid
                    q.append(t)
        return None

    def last_line_before(self, path: List[int]) -> Optional[int]:
        """Line of the last real statement on ``path`` (the escape
        site a finding names)."""
        for nid in reversed(path):
            stmt = self.stmts.get(nid)
            if stmt is not None and hasattr(stmt, "lineno"):
                return stmt.lineno
        return None


class _Ctx:
    """Continuation targets while building: where an exception goes
    (possibly several handlers), where return/break/continue go."""

    __slots__ = ("exc", "return_to", "break_to", "continue_to")

    def __init__(self, exc: Tuple[int, ...], return_to: int,
                 break_to: Optional[int], continue_to: Optional[int]):
        self.exc = exc
        self.return_to = return_to
        self.break_to = break_to
        self.continue_to = continue_to

    def with_(self, **kw) -> "_Ctx":
        vals = {"exc": self.exc, "return_to": self.return_to,
                "break_to": self.break_to, "continue_to": self.continue_to}
        vals.update(kw)
        return _Ctx(**vals)


class _Builder:
    def __init__(self) -> None:
        self.cfg = CFG()
        self._next = 0

    def _node(self, stmt: ast.AST) -> int:
        nid = self._next
        self._next += 1
        self.cfg.stmts[nid] = stmt
        self.cfg.succ[nid] = []
        return nid

    def _edge(self, src: int, dst: int, kind: str = "step") -> None:
        if (dst, kind) not in self.cfg.succ[src]:
            self.cfg.succ[src].append((dst, kind))

    # --------------------------------------------------------------- build
    def build(self, fn: ast.AST) -> CFG:
        body = list(fn.body) if isinstance(fn.body, list) else [fn.body]
        ctx = _Ctx(exc=(RAISE_EXIT,), return_to=RETURN_EXIT,
                   break_to=None, continue_to=None)
        self.cfg.entry = self._seq(body, RETURN_EXIT, ctx)
        return self.cfg

    def _seq(self, stmts: List[ast.stmt], nxt: int, ctx: _Ctx) -> int:
        entry = nxt
        for stmt in reversed(stmts):
            entry = self._stmt(stmt, entry, ctx)
        return entry

    def _exc_edges(self, nid: int, stmt: ast.stmt, ctx: _Ctx) -> None:
        if stmt_can_raise(stmt):
            for target in ctx.exc:
                self._edge(nid, target, "exc")

    def _stmt(self, stmt: ast.stmt, nxt: int, ctx: _Ctx) -> int:
        nid = self._node(stmt)

        if isinstance(stmt, ast.Return):
            self._edge(nid, ctx.return_to)
            self._exc_edges(nid, stmt, ctx)
        elif isinstance(stmt, ast.Raise):
            for target in ctx.exc:
                self._edge(nid, target, "exc")
        elif isinstance(stmt, ast.Break) and ctx.break_to is not None:
            self._edge(nid, ctx.break_to)
        elif isinstance(stmt, ast.Continue) and ctx.continue_to is not None:
            self._edge(nid, ctx.continue_to)
        elif isinstance(stmt, ast.If):
            body = self._seq(stmt.body, nxt, ctx)
            orelse = self._seq(stmt.orelse, nxt, ctx)
            self._edge(nid, body)
            self._edge(nid, orelse)
            self._exc_edges(nid, stmt, ctx)
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            after = self._seq(list(stmt.orelse), nxt, ctx)
            loop_ctx = ctx.with_(break_to=nxt, continue_to=nid)
            body = self._seq(stmt.body, nid, loop_ctx)
            self._edge(nid, body)    # iterate
            self._edge(nid, after)   # loop exits (or test false)
            self._exc_edges(nid, stmt, ctx)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            body = self._seq(stmt.body, nxt, ctx)
            self._edge(nid, body)
            self._exc_edges(nid, stmt, ctx)
        elif isinstance(stmt, ast.Try):
            self._try(nid, stmt, nxt, ctx)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            self._edge(nid, nxt)  # a def is just a binding here
        else:
            self._edge(nid, nxt)
            self._exc_edges(nid, stmt, ctx)
        return nid

    def _try(self, nid: int, stmt: ast.Try, nxt: int, ctx: _Ctx) -> None:
        # ---- finally: everything routes through it, then fans out
        if stmt.finalbody:
            fan = self._node(stmt)  # synthetic fan-out point after finally
            fin_entry = self._seq(stmt.finalbody, fan, ctx)
            for target in {nxt, ctx.return_to, *ctx.exc} | (
                    {ctx.break_to} if ctx.break_to is not None else set()) | (
                    {ctx.continue_to} if ctx.continue_to is not None
                    else set()):
                self._edge(fan, target)
            inner_ctx = ctx.with_(exc=(fin_entry,), return_to=fin_entry,
                                  break_to=fin_entry
                                  if ctx.break_to is not None else None,
                                  continue_to=fin_entry
                                  if ctx.continue_to is not None else None)
            after_body = fin_entry
        else:
            inner_ctx = ctx
            after_body = nxt

        # ---- handlers
        handler_entries: List[int] = []
        broad = False
        for h in stmt.handlers:
            h_node = self._node(h)
            h_body = self._seq(h.body, after_body, inner_ctx)
            self._edge(h_node, h_body)
            handler_entries.append(h_node)
            if h.type is None:
                broad = True
            else:
                names = [h.type] if not isinstance(h.type, ast.Tuple) \
                    else list(h.type.elts)
                for t in names:
                    tail = t.attr if isinstance(t, ast.Attribute) else (
                        t.id if isinstance(t, ast.Name) else None)
                    if tail in _BROAD_EXC:
                        broad = True

        body_exc: Tuple[int, ...] = tuple(handler_entries)
        if not broad:
            body_exc = body_exc + inner_ctx.exc  # escapes past handlers
        if not body_exc:
            body_exc = inner_ctx.exc

        body_ctx = inner_ctx.with_(exc=body_exc)
        orelse_entry = self._seq(list(stmt.orelse), after_body, inner_ctx)
        body_entry = self._seq(stmt.body, orelse_entry, body_ctx)
        self._edge(nid, body_entry)


def build_cfg(fn: ast.AST) -> CFG:
    """CFG for one FunctionDef / AsyncFunctionDef / Lambda body."""
    return _Builder().build(fn)
