"""threadlint — jaxlint's whole-program concurrency suite (T1-T3).

Importing this package registers the three analyses:

===  ==========================  =========================================
id   name                        hazard
===  ==========================  =========================================
T1   unguarded-shared-attr       lock-guarded attribute read/written on a
                                 thread-reachable path outside the lock
T2   lock-order-cycle            A-then-B here, B-then-A there: deadlock
                                 waiting for the interleaving
T3   blocking-call-under-lock    queue/join/result/jit-dispatch/file I/O
                                 inside a pool-level critical section
===  ==========================  =========================================

Unlike the per-file tracing rules, these run over a
:class:`~pdnlp_tpu.analysis.core.ProgramInfo` — module graph, import-alias
resolved call edges, class-level attribute type models — built once per
lint (``pdnlp_tpu.analysis.concurrency.model``).  Select with
``lint_tpu.py --suite concurrency`` (``--suite all`` is the default).
"""
from pdnlp_tpu.analysis.concurrency import (  # noqa: F401
    t1_unguarded_attr,
    t2_lock_order,
    t3_blocking_under_lock,
)
