"""T3 — blocking call while holding a lock.

A pool-level lock is the serving tier's convoy point: every submitter,
worker and monitor wake funnels through it.  Anything that can park the
holder — an unbounded ``queue.put``/``get``, ``Future.result()``, a
``Thread.join()``, ``jax.block_until_ready`` / a jit dispatch, file or
socket I/O, a bare ``sleep`` — extends the critical section by the full
wait and serializes the pool against it (the PR-9 "packing under the one
lock serialized every worker" bug class).

Checked both directly (a blocking call lexically inside ``with self._lock``)
and interprocedurally: a call made under the lock to a function/method
whose body (transitively, through resolvable call edges) performs blocking
work — the finding lands on the call site, citing the blocking operation's
own ``file:line``, because the call site is where the lock scope is wrong.

Sanctioned shapes that do NOT flag:

- ``cond.wait(...)`` on a Condition wrapping a lock you hold — that is
  the one blocking call DESIGNED to run under its lock (it releases it);
- any wait/join/get/put given a ``timeout`` (bounded stall, a latency
  bug at worst — not a wedge);
- ``put_nowait``/``get_nowait``;
- blocking work after the ``with`` block closed (the snapshot-then-work
  pattern the repo's batch formation uses).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from pdnlp_tpu.analysis.core import (
    Finding, ProgramInfo, ProgramRule, dotted_name, is_step_call, register,
)
from pdnlp_tpu.analysis.concurrency.model import (
    CallFact, ConcurrencyModel, FuncKey, FunctionFacts, get_model,
    token_display,
)

_SLEEPERS = {"time.sleep"}
_SUBPROCESS = {"subprocess.run", "subprocess.call", "subprocess.check_call",
               "subprocess.check_output"}
_FILE_IO = {"os.replace", "os.rename", "os.fsync", "os.makedirs",
            "shutil.copyfile", "shutil.copy", "shutil.move",
            "json.dump", "pickle.dump", "numpy.save"}
_DEVICE_SYNC = {"jax.block_until_ready", "jax.device_get"}
_QUEUE_TYPES = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
                "queue.SimpleQueue"}
_SOCKET_BLOCKING_METHODS = {"recv", "send", "sendall", "accept", "connect"}
#: jit-dispatch naming: the repo's step convention plus jit-prefixed
#: callables and the engine forward surface
_JIT_NAME_RE = re.compile(r"(^|_)jit(_|$)")
_ENGINE_DISPATCH = {"infer_ids", "infer_packed", "prefill_ids",
                    "decode_batch", "warmup_packed"}

_MAX_DEPTH = 3


def _has_timeout(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" and not (
        isinstance(kw.value, ast.Constant) and kw.value.value is None)
        for kw in call.keywords)


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def classify_blocking(facts: FunctionFacts, c: CallFact,
                      resolved_in_program: bool = False
                      ) -> Optional[Tuple[str, str]]:
    """``(kind, detail)`` when this call can block unboundedly, else None.
    Receiver-sensitive checks use the type model (``self._q`` known to be
    a ``queue.Queue``); the Condition-wait exemption uses the held-set at
    the call.  The jit-dispatch NAME heuristics only apply when the
    callee does NOT resolve to a scanned function — a resolvable callee
    is judged by what its body actually does (the interprocedural
    summary), not by what it is called (``_close_step`` is an obs
    helper, not a jitted step)."""
    call = c.node
    mod = facts.mod
    resolved = mod.resolve(call.func)
    if resolved in _SLEEPERS:
        return ("sleep", "time.sleep holds the lock for the full nap")
    if resolved in _SUBPROCESS:
        return ("subprocess", f"{resolved} blocks on the child process")
    if resolved == "open" or resolved in _FILE_IO:
        return ("file I/O", f"{resolved} touches the filesystem")
    if resolved in _DEVICE_SYNC:
        return ("device sync", f"{resolved} waits for the device stream")
    if is_step_call(call) and not resolved_in_program:
        name = dotted_name(call.func) or "<step>"
        # the repo's callback convention (`on_step`, `on_death`) shares
        # the *step suffix but names a handler, not a dispatch
        if not name.split(".")[-1].startswith("on_"):
            return ("jit dispatch",
                    f"{name} dispatches compiled device work")
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        recv = c.recv_type
        if not resolved_in_program and (
                _JIT_NAME_RE.search(attr) or attr in _ENGINE_DISPATCH):
            return ("jit dispatch",
                    f".{attr}() dispatches compiled device work")
        if attr == "block_until_ready":
            return ("device sync",
                    ".block_until_ready() waits for the device stream")
        if attr == "result" and not call.args and not _has_timeout(call):
            return ("future wait", ".result() with no timeout")
        if attr == "join" and not call.args and not _has_timeout(call):
            if recv == "threading.Thread" or _thread_named(call.func.value):
                return ("thread join", ".join() with no timeout")
        if attr in ("put", "get") and recv in _QUEUE_TYPES:
            block_arg = call.args[1] if attr == "put" and len(call.args) > 1 \
                else (call.args[0] if attr == "get" and call.args else None)
            blocking_false = (isinstance(block_arg, ast.Constant)
                              and block_arg.value is False) or (
                isinstance(_kw(call, "block"), ast.Constant)
                and _kw(call, "block").value is False)
            if not blocking_false and not _has_timeout(call):
                return ("queue wait", f".{attr}() with no timeout")
        if attr == "wait":
            if recv == "threading.Event" and not call.args \
                    and not _has_timeout(call):
                return ("event wait", "Event.wait() with no timeout")
            # Condition.wait on a lock you HOLD is the sanctioned shape
            # (it releases the lock); on one you don't, an unbounded
            # wait extends whatever you DO hold
            if c.recv_token is not None \
                    and c.recv_token not in c.held_tokens() \
                    and not call.args and not _has_timeout(call):
                return ("condition wait",
                        f"waiting {token_display(c.recv_token)} "
                        "with no timeout")
        if attr in _SOCKET_BLOCKING_METHODS and recv == "socket.socket":
            return ("socket I/O", f".{attr}() blocks on the peer")
    return None


def _thread_named(recv: ast.AST) -> bool:
    dn = dotted_name(recv) or ""
    last = dn.split(".")[-1].lower()
    return any(s in last for s in ("thread", "worker", "harvester",
                                   "monitor"))


@register
class BlockingCallUnderLock(ProgramRule):
    rule_id = "T3"
    name = "blocking-call-under-lock"
    suite = "concurrency"
    hint = ("move the blocking work outside the `with` block — snapshot "
            "what you need under the lock, release, then block (the "
            "_PackIntent pattern); for waits, pass a timeout so a wedge "
            "is a latency blip, not a deadlock")

    def check_program(self, prog: ProgramInfo) -> Iterator[Finding]:
        model = get_model(prog)
        summaries: Dict[FuncKey, List[Tuple[str, str, str]]] = {}
        for key in sorted(model.facts):
            facts = model.facts[key]
            for c in facts.calls:
                if not c.held:
                    continue
                verdict = classify_blocking(
                    facts, c, c.callee is not None and c.callee in model.facts)
                lock_tok, lock_site = c.held[0]
                where = (f"{token_display(lock_tok)} (acquired "
                         f"{facts.mod.path}:"
                         f"{getattr(lock_site, 'lineno', '?')})")
                if verdict is not None:
                    kind, detail = verdict
                    yield self.finding(
                        facts.mod, c.node,
                        f"{kind} while holding {where} — {detail}")
                    continue
                if c.callee is None or c.callee not in model.facts:
                    continue
                inner = self._blocking_summary(model, c.callee, summaries,
                                               _MAX_DEPTH)
                if inner:
                    kind, detail, site = inner[0]
                    callee_name = c.callee.split(".")[-1]
                    yield self.finding(
                        facts.mod, c.node,
                        f"call to {callee_name}() performs {kind} "
                        f"({site}: {detail}) while holding {where}")

    def _blocking_summary(self, model: ConcurrencyModel, key: FuncKey,
                          memo: Dict, depth: int
                          ) -> List[Tuple[str, str, str]]:
        """(kind, detail, file:line) blocking operations reachable inside
        ``key`` — what calling it under a lock drags into the critical
        section."""
        if key in memo:
            return memo[key]
        memo[key] = []  # cycle guard
        if depth <= 0:
            return memo[key]
        facts = model.facts.get(key)
        if facts is None:
            return memo[key]
        out: List[Tuple[str, str, str]] = []
        for c in facts.calls:
            verdict = classify_blocking(
                facts, c, c.callee is not None and c.callee in model.facts)
            if verdict is not None:
                kind, detail = verdict
                out.append((kind, detail,
                            f"{facts.mod.path}:"
                            f"{getattr(c.node, 'lineno', '?')}"))
            elif c.callee is not None and c.callee in model.facts:
                out.extend(self._blocking_summary(model, c.callee, memo,
                                                  depth - 1))
        memo[key] = out
        return out
