"""T1 — lock-guarded attribute touched outside the lock.

For each class that owns a ``threading.Lock``/``RLock`` (Conditions alias
the lock they wrap), infer the guarded attribute set: attributes whose
accesses occur at least :data:`MIN_GUARDED` times while the lock is held
— counting helper methods that inherit the lock interprocedurally
(``_finish_locked`` is guarded because every call site holds the lock) —
and that are WRITTEN somewhere outside ``__init__`` (a reference assigned
once at construction cannot race, however often it is read).

Then flag every read/write of a guarded attribute on a thread-reachable
path that does not hold the lock.  Thread reachability is seeded from
``threading.Thread(target=...)`` / ``threading.Timer`` spawns and
``Thread`` subclass ``run`` methods — the serving stack's worker/monitor/
harvester entry points — and closed over the program call graph, so an
unlocked touch buried two helpers deep under a worker loop still lands an
exact ``file:line``.

Two finding shapes:

- a direct access in a thread-reachable non-helper method;
- a CALL to a same-class helper from a site that does not hold the lock,
  when the helper's body (transitively) touches guarded attributes that
  its own ``with`` blocks do not cover — the finding cites the call site
  (that is where the lock is missing), naming the helper and attribute.

``__init__`` is exempt (construction is single-threaded by convention),
and so are methods that are not thread-reachable: a lifecycle method only
the owning thread calls cannot race the worker it has not started yet.
"""
from __future__ import annotations

from typing import Dict, FrozenSet, Iterator, Set, Tuple

from pdnlp_tpu.analysis.core import Finding, ProgramInfo, ProgramRule, register
from pdnlp_tpu.analysis.concurrency.model import (
    ConcurrencyModel, LockToken, get_model, method_key, token_display,
)

#: guarded-set inference threshold: accesses under the lock before an
#: attribute counts as lock-guarded
MIN_GUARDED = 2


@register
class UnguardedSharedAttr(ProgramRule):
    rule_id = "T1"
    name = "unguarded-shared-attr"
    suite = "concurrency"
    hint = ("take the owning lock around the access (`with self._lock:`) "
            "— or, when the invariant is upheld by construction (e.g. the "
            "write happens-before Thread.start()), suppress with "
            "`# jaxlint: disable=T1` and a written reason")

    def check_program(self, prog: ProgramInfo) -> Iterator[Finding]:
        model = get_model(prog)
        for cls_qual in sorted(model.class_locks):
            yield from self._check_class(model, cls_qual)

    # ------------------------------------------------------------ per-class
    def _check_class(self, model: ConcurrencyModel,
                     cls_qual: str) -> Iterator[Finding]:
        if not model.class_is_threaded(cls_qual):
            return
        cm = model.prog.classes[cls_qual]
        entry = model.entry_held(cls_qual)
        lock_attrs = model.lock_attrs(cls_qual)
        own_tokens = model.class_tokens(cls_qual)

        counts: Dict[Tuple[LockToken, str], int] = {}
        written: Set[str] = set()
        for mname, facts in model.methods_of(cls_qual):
            if mname == "__init__":
                continue
            ent = entry.get(mname, frozenset())
            for a in facts.accesses:
                if a.attr in lock_attrs or a.attr in cm.methods:
                    continue
                if a.write:
                    written.add(a.attr)
                for tok in (a.held | ent) & own_tokens:
                    counts[(tok, a.attr)] = counts.get((tok, a.attr), 0) + 1
        guarded: Dict[LockToken, Set[str]] = {}
        for (tok, attr), n in counts.items():
            if n >= MIN_GUARDED and attr in written:
                guarded.setdefault(tok, set()).add(attr)
        if not guarded:
            return

        callsites = model.intraclass_callsite_counts(cls_qual)

        def is_helper(mname: str) -> bool:
            return (mname.startswith("_") and not mname.startswith("__")
                    and callsites.get(mname, 0) > 0
                    and method_key(cls_qual, mname)
                    not in model.thread_entries)

        exposed_memo: Dict[str, Set[Tuple[str, FrozenSet[LockToken]]]] = {}
        for mname, facts in sorted(model.methods_of(cls_qual)):
            if mname == "__init__" or \
                    method_key(cls_qual, mname) not in model.thread_reachable:
                continue
            ent = entry.get(mname, frozenset())
            if not is_helper(mname):  # helpers are judged at call sites
                for a in facts.accesses:
                    eff = a.held | ent
                    for tok in sorted(guarded):
                        if a.attr in guarded[tok] and tok not in eff:
                            yield self.finding(
                                facts.mod, a.node,
                                f"{'write to' if a.write else 'read of'} "
                                f"'{a.attr}' outside {token_display(tok)} "
                                f"— the attribute is lock-guarded "
                                f"({counts[(tok, a.attr)]} guarded "
                                f"accesses) and `{mname}` runs on a "
                                f"thread-reachable path")
                            break
            for c in facts.calls:
                prefix = f"m:{cls_qual}."
                if c.callee is None or not c.callee.startswith(prefix):
                    continue
                callee_name = c.callee[len(prefix):]
                if not is_helper(callee_name):
                    continue
                eff = c.held_tokens() | ent
                flagged: Set[str] = set()
                for attr, hs in sorted(
                        self._exposed(model, cls_qual, callee_name,
                                      exposed_memo),
                        key=lambda p: p[0]):
                    for tok in sorted(guarded):
                        if attr in guarded[tok] and tok not in hs \
                                and tok not in eff and attr not in flagged:
                            flagged.add(attr)
                            yield self.finding(
                                facts.mod, c.node,
                                f"call to {cm.name}.{callee_name}() "
                                f"without holding {token_display(tok)} — "
                                f"the helper touches lock-guarded "
                                f"'{attr}' and `{mname}` runs on a "
                                f"thread-reachable path")

    # ------------------------------------------------------------- exposure
    def _exposed(self, model: ConcurrencyModel, cls_qual: str, mname: str,
                 memo: Dict[str, Set[Tuple[str, FrozenSet[LockToken]]]],
                 ) -> Set[Tuple[str, FrozenSet[LockToken]]]:
        """(attr, locks-held-locally) pairs a helper's body touches,
        transitively through same-class calls (each nested call adds the
        locks held AT that call) — what a call site must cover itself."""
        if mname in memo:
            return memo[mname]
        memo[mname] = set()  # cycle guard
        facts = model.facts.get(method_key(cls_qual, mname))
        if facts is None:
            return memo[mname]
        cm = model.prog.classes[cls_qual]
        lock_attrs = model.lock_attrs(cls_qual)
        out: Set[Tuple[str, FrozenSet[LockToken]]] = set()
        for a in facts.accesses:
            if a.attr not in lock_attrs and a.attr not in cm.methods:
                out.add((a.attr, a.held))
        prefix = f"m:{cls_qual}."
        for c in facts.calls:
            if c.callee is None or not c.callee.startswith(prefix):
                continue
            sub = self._exposed(model, cls_qual, c.callee[len(prefix):],
                                memo)
            out |= {(attr, hs | c.held_tokens()) for attr, hs in sub}
        memo[mname] = out
        return out
