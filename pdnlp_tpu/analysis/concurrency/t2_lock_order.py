"""T2 — lock-order cycles (potential deadlock).

Build the acquired-while-holding graph over every lock the program owns
(class locks by qualified name, module-level locks by file): an edge
``A -> B`` means some code path acquires ``B`` while already holding
``A`` — from a lexically nested ``with``, or interprocedurally: a call
made under ``A`` to a function/method that (transitively) acquires ``B``.
A cycle in that graph is a deadlock waiting for the right interleaving:
thread 1 parks inside ``A`` waiting for ``B`` exactly as thread 2 parks
inside ``B`` waiting for ``A``.

One finding per cycle, placed on an acquisition site of the first edge,
with EVERY edge's two sites cited (where the outer lock was held, where
the inner was acquired) so the fix — pick one global order, or drop work
out of the outer region — can be made with the whole loop in view.

Re-acquiring the SAME lock is not an edge (RLock re-entry is legal, and a
plain-Lock self-deadlock is a different bug class T3's unbounded-wait
checks approximate).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from pdnlp_tpu.analysis.core import (
    Finding, ModuleInfo, ProgramInfo, ProgramRule, register,
)
from pdnlp_tpu.analysis.concurrency.model import (
    ConcurrencyModel, FuncKey, LockToken, get_model, token_display,
)

#: interprocedural acquisition summaries stop here — deeper chains exist
#: but three hops covers every idiom this repo has grown
_MAX_DEPTH = 4


class _Edge:
    __slots__ = ("a", "b", "mod", "site_a", "site_b", "via")

    def __init__(self, a: LockToken, b: LockToken, mod: ModuleInfo,
                 site_a: ast.AST, site_b: ast.AST, via: str):
        self.a, self.b = a, b
        self.mod = mod
        self.site_a = site_a      # where A was held (its acquisition)
        self.site_b = site_b      # where B is acquired (or the call site)
        self.via = via            # "" or "via <callee>"

    def cite(self) -> str:
        held = f"{self.mod.path}:{getattr(self.site_a, 'lineno', '?')}"
        acq = f"{self.mod.path}:{getattr(self.site_b, 'lineno', '?')}"
        via = f" {self.via}" if self.via else ""
        return (f"holding {token_display(self.a)} (acquired {held}) "
                f"acquires {token_display(self.b)} ({acq}{via})")


@register
class LockOrderCycle(ProgramRule):
    rule_id = "T2"
    name = "lock-order-cycle"
    suite = "concurrency"
    hint = ("pick ONE global acquisition order for the locks in the cycle "
            "and restructure the minority path to follow it (usually: "
            "snapshot what you need under the first lock, release, then "
            "take the second)")

    def check_program(self, prog: ProgramInfo) -> Iterator[Finding]:
        model = get_model(prog)
        edges: Dict[Tuple[LockToken, LockToken], _Edge] = {}
        acq_memo: Dict[FuncKey, Set[Tuple[LockToken, str, int]]] = {}

        for key, facts in model.facts.items():
            for acq in facts.acquires:
                for a, site_a in acq.held_before:
                    if a != acq.token:
                        edges.setdefault((a, acq.token), _Edge(
                            a, acq.token, facts.mod, site_a, acq.node, ""))
            for c in facts.calls:
                if not c.held or c.callee is None \
                        or c.callee not in model.facts:
                    continue
                for b, where in self._acquired_by(model, c.callee,
                                                  acq_memo, _MAX_DEPTH):
                    for a, site_a in c.held:
                        if a != b:
                            edges.setdefault((a, b), _Edge(
                                a, b, facts.mod, site_a, c.node,
                                f"via {self._callee_name(c.callee)} "
                                f"at {where}"))

        adj: Dict[LockToken, Set[LockToken]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        seen_cycles: Set[frozenset] = set()
        for cycle in self._cycles(adj):
            key = frozenset(cycle)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            cycle_edges = [edges[(cycle[i], cycle[(i + 1) % len(cycle)])]
                           for i in range(len(cycle))]
            first = cycle_edges[0]
            order = " -> ".join(token_display(t) for t in cycle
                                ) + f" -> {token_display(cycle[0])}"
            yield self.finding(
                first.mod, first.site_b,
                f"lock-order cycle {order} — potential deadlock: "
                + "; ".join(e.cite() for e in cycle_edges))

    # ----------------------------------------------------------- summaries
    def _acquired_by(self, model: ConcurrencyModel, key: FuncKey,
                     memo: Dict, depth: int
                     ) -> Set[Tuple[LockToken, str]]:
        """Locks ``key`` (transitively) acquires, each with a ``file:line``
        of the acquisition for the citation."""
        if key in memo:
            return memo[key]
        memo[key] = set()  # cycle guard
        if depth <= 0:
            return memo[key]
        facts = model.facts.get(key)
        if facts is None:
            return memo[key]
        out: Set[Tuple[LockToken, str]] = set()
        for acq in facts.acquires:
            out.add((acq.token,
                     f"{facts.mod.path}:"
                     f"{getattr(acq.node, 'lineno', '?')}"))
        for c in facts.calls:
            if c.callee is not None and c.callee in model.facts:
                out |= self._acquired_by(model, c.callee, memo, depth - 1)
        memo[key] = out
        return out

    @staticmethod
    def _callee_name(key: FuncKey) -> str:
        return key.split(":", 1)[1].split(".")[-1] + "()"

    # --------------------------------------------------------------- cycles
    @staticmethod
    def _cycles(adj: Dict[LockToken, Set[LockToken]]
                ) -> List[List[LockToken]]:
        """One simple cycle per strongly connected component of size >= 2
        (enumerating every rotation/ordering would re-report the same
        deadlock shape)."""
        index: Dict[LockToken, int] = {}
        low: Dict[LockToken, int] = {}
        on_stack: Set[LockToken] = set()
        stack: List[LockToken] = []
        sccs: List[List[LockToken]] = []
        counter = [0]

        def strongconnect(v: LockToken) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in sorted(adj.get(v, ()), key=str):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) >= 2:
                    sccs.append(comp)

        for v in sorted(adj, key=str):
            if v not in index:
                strongconnect(v)

        cycles: List[List[LockToken]] = []
        for comp in sccs:
            comp_set = set(comp)
            start = sorted(comp, key=str)[0]
            # DFS inside the SCC for one path start -> ... -> start
            path: List[LockToken] = [start]
            found: List[Optional[List[LockToken]]] = [None]

            def dfs(v: LockToken) -> None:
                if found[0] is not None:
                    return
                for w in sorted(adj.get(v, ()), key=str):
                    if w == start and len(path) >= 2:
                        found[0] = list(path)
                        return
                    if w in comp_set and w not in path:
                        path.append(w)
                        dfs(w)
                        path.pop()

            dfs(start)
            if found[0] is not None:
                cycles.append(found[0])
        return cycles
