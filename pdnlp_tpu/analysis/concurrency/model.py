"""threadlint shared machinery — locks, held-sets, call facts, threads.

Everything the three concurrency analyses (T1/T2/T3) share lives here,
computed ONCE per program:

- **lock discovery** per class (``self._lock = threading.Lock()``) with
  Condition aliasing (``self._wake = threading.Condition(self._lock)``
  guards the same lock — ``with self._wake`` IS ``with self._lock``) and
  module-level locks (``_COMPLETE_LOCK = threading.Lock()``);
- **function facts**: a structural walk of every function body tracking
  the set of locks held at each point — every ``self.<attr>`` access,
  every call site (resolved to a method / module function through the
  program's class-attribute type models), every lock acquisition, and
  every ``threading.Thread(target=...)`` spawn, each stamped with the
  held-set at that point;
- **thread reachability**: the closure of the program call graph from
  spawned-thread entry points (``Thread(target=...)``, ``Timer``,
  ``Thread`` subclass ``run``) — the worker/monitor/harvester entry
  points of the serving stack seed this by construction;
- **must-hold entries** per class: a helper method called only under the
  lock (``_finish_locked`` and friends) inherits that context, so its
  body accesses count as guarded interprocedurally.

Pure ``ast`` like the rest of jaxlint: nothing here imports threading's
runtime — the names are matched through each module's import-alias map.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from pdnlp_tpu.analysis.core import ClassModel, ModuleInfo, ProgramInfo

#: a lock identity: ("C", class_qualname, group) for a class-owned lock
#: (group = the canonical attribute name after Condition aliasing) or
#: ("M", module_path, name) for a module-level lock
LockToken = Tuple[str, str, str]

_LOCK_CTORS = {"threading.Lock", "threading.RLock"}
_COND_CTOR = "threading.Condition"

#: thread-entry idioms: a callable handed to one of these runs on its own
#: thread (first arg position / keyword per ctor)
_THREAD_CTORS = {"threading.Thread": "target", "threading.Timer": "function"}


def token_display(tok: LockToken) -> str:
    kind, scope, name = tok
    if kind == "C":
        return f"{scope.split('.')[-1]}.{name}"
    return name


@dataclasses.dataclass(frozen=True)
class Access:
    """One ``self.<attr>`` touch, with the locks held around it."""
    attr: str
    write: bool
    node: ast.AST
    held: FrozenSet[LockToken]


@dataclasses.dataclass(frozen=True)
class CallFact:
    """One call site: resolved callee (or None), receiver type (for the
    ``.get()``/``.join()``/``.wait()`` judgements), the receiver's lock
    token when it IS a lock/condition attribute (the ``cond.wait()``
    exemption), and the held-set with the acquisition node per token (so
    findings can cite WHERE the lock was taken)."""
    node: ast.Call
    callee: Optional[str]              # function-key, see FunctionFacts
    recv_type: Optional[str]           # qualified type of `x` in x.m(...)
    recv_token: Optional[LockToken]    # set when `x` is a known lock/cond
    held: Tuple[Tuple[LockToken, ast.AST], ...]

    def held_tokens(self) -> FrozenSet[LockToken]:
        return frozenset(t for t, _ in self.held)


@dataclasses.dataclass(frozen=True)
class Acquire:
    """One ``with <lock>`` acquisition and what was already held."""
    token: LockToken
    node: ast.AST
    held_before: Tuple[Tuple[LockToken, ast.AST], ...]


#: function key: "m:<class_qualname>.<method>" | "f:<func_qualname>"
FuncKey = str


def method_key(cls_qual: str, name: str) -> FuncKey:
    return f"m:{cls_qual}.{name}"


class FunctionFacts:
    def __init__(self, key: FuncKey, mod: ModuleInfo, fn: ast.AST,
                 owner: Optional[ClassModel]):
        self.key = key
        self.mod = mod
        self.fn = fn
        self.owner = owner
        self.accesses: List[Access] = []
        self.calls: List[CallFact] = []
        self.acquires: List[Acquire] = []
        self.spawn_targets: List[FuncKey] = []


def get_model(prog: ProgramInfo) -> "ConcurrencyModel":
    """The (cached) :class:`ConcurrencyModel` for one program — T1/T2/T3
    share one build per lint run.  Cached ON the program object so the
    model's lifetime is exactly the program's (a global map keyed on
    programs would pin every scanned AST for process lifetime)."""
    model = getattr(prog, "_concurrency_model", None)
    if model is None:
        model = ConcurrencyModel(prog)
        prog._concurrency_model = model
    return model


class ConcurrencyModel:
    """All shared facts for one program (built once, used by T1/T2/T3)."""

    def __init__(self, prog: ProgramInfo):
        self.prog = prog
        #: class qualname -> {lock attr -> group}; Conditions alias their
        #: wrapped lock's group
        self.class_locks: Dict[str, Dict[str, str]] = {}
        #: module path -> module-level lock names
        self.module_locks: Dict[str, Set[str]] = {}
        self.facts: Dict[FuncKey, FunctionFacts] = {}
        self.thread_entries: Set[FuncKey] = set()
        self._discover_locks()
        self._build_facts()
        self.thread_reachable = self._reach_closure()
        self._entry_held: Dict[str, Dict[str, FrozenSet[LockToken]]] = {}

    # --------------------------------------------------------------- locks
    def _discover_locks(self) -> None:
        for cm in self.prog.classes.values():
            groups: Dict[str, str] = {}
            conds: List[Tuple[str, ast.Call]] = []
            for meth in cm.methods.values():
                for node in ast.walk(meth):
                    if not (isinstance(node, ast.Assign)
                            and len(node.targets) == 1
                            and isinstance(node.targets[0], ast.Attribute)
                            and isinstance(node.targets[0].value, ast.Name)
                            and node.targets[0].value.id == "self"
                            and isinstance(node.value, ast.Call)):
                        continue
                    attr = node.targets[0].attr
                    resolved = cm.mod.resolve(node.value.func)
                    if resolved in _LOCK_CTORS:
                        groups[attr] = attr
                    elif resolved == _COND_CTOR:
                        conds.append((attr, node.value))
            for attr, call in conds:  # second pass: alias wrapped locks
                wrapped = None
                if call.args:
                    a0 = call.args[0]
                    if isinstance(a0, ast.Attribute) \
                            and isinstance(a0.value, ast.Name) \
                            and a0.value.id == "self":
                        wrapped = groups.get(a0.attr)
                groups[attr] = wrapped if wrapped is not None else attr
            if groups:
                self.class_locks[cm.qualname] = groups
        for mod in self.prog.modules.values():
            names: Set[str] = set()
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call) \
                        and mod.resolve(node.value.func) in (
                            _LOCK_CTORS | {_COND_CTOR}):
                    names.add(node.targets[0].id)
            if names:
                self.module_locks[mod.path] = names

    def lock_groups(self, cls_qual: str) -> Dict[str, str]:
        return self.class_locks.get(cls_qual, {})

    def class_tokens(self, cls_qual: str) -> Set[LockToken]:
        return {("C", cls_qual, g)
                for g in set(self.lock_groups(cls_qual).values())}

    #: attr names that ARE locks/conditions for a class (never "guarded
    #: data" themselves)
    def lock_attrs(self, cls_qual: str) -> Set[str]:
        return set(self.lock_groups(cls_qual))

    # --------------------------------------------------------------- facts
    def _build_facts(self) -> None:
        for mod in self.prog.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                owner = self.prog.owner_class(mod, node)
                if owner is not None:
                    key = method_key(owner.qualname, node.name)
                elif node in [n for n in mod.tree.body]:
                    fq = self.prog.resolve_function(
                        mod, ast.Name(id=node.name))
                    key = f"f:{fq}" if fq else \
                        f"f:{mod.path}::{node.name}"
                else:
                    continue  # defs nested in defs run in their own scope
                facts = FunctionFacts(key, mod, node, owner)
                _FactsWalker(self, facts).run()
                self.facts[key] = facts
        # Thread subclass `run` methods are entries too
        for cm in self.prog.classes.values():
            for base in cm.node.bases:
                if cm.mod.resolve(base) == "threading.Thread" \
                        and "run" in cm.methods:
                    self.thread_entries.add(method_key(cm.qualname, "run"))

    # --------------------------------------------------------- reachability
    def _reach_closure(self) -> Set[FuncKey]:
        edges: Dict[FuncKey, Set[FuncKey]] = {}
        for key, facts in self.facts.items():
            outs = edges.setdefault(key, set())
            for c in facts.calls:
                if c.callee is not None and c.callee in self.facts:
                    outs.add(c.callee)
            self.thread_entries.update(
                t for t in facts.spawn_targets if t in self.facts)
        seen: Set[FuncKey] = set()
        frontier = list(self.thread_entries & set(self.facts))
        while frontier:
            k = frontier.pop()
            if k in seen:
                continue
            seen.add(k)
            frontier.extend(edges.get(k, ()))
        return seen

    def class_is_threaded(self, cls_qual: str) -> bool:
        """True when some method of the class runs on a spawned thread —
        the precondition for any cross-thread attribute race."""
        prefix = f"m:{cls_qual}."
        return any(k.startswith(prefix) for k in self.thread_reachable)

    # --------------------------------------------------------- entry-held
    def entry_held(self, cls_qual: str) -> Dict[str, FrozenSet[LockToken]]:
        """Must-hold lock set at entry per method of ``cls_qual``.

        A leading-underscore helper called ONLY from same-class sites that
        hold the lock inherits it (``_finish_locked``); public methods and
        thread entries start with nothing held.  Computed as a decreasing
        fixpoint (init: all own-class tokens for eligible helpers)."""
        if cls_qual in self._entry_held:
            return self._entry_held[cls_qual]
        cm = self.prog.classes.get(cls_qual)
        tokens = frozenset(self.class_tokens(cls_qual))
        methods = list(cm.methods) if cm is not None else []
        sites: Dict[str, List[Tuple[str, FrozenSet[LockToken]]]] = \
            {m: [] for m in methods}
        for m in methods:
            facts = self.facts.get(method_key(cls_qual, m))
            if facts is None:
                continue
            for c in facts.calls:
                if c.callee is None or not c.callee.startswith(
                        f"m:{cls_qual}."):
                    continue
                callee_name = c.callee[len(f"m:{cls_qual}."):]
                if callee_name in sites:
                    sites[callee_name].append((m, c.held_tokens()))

        def eligible(m: str) -> bool:
            return (m.startswith("_") and not m.startswith("__")
                    and bool(sites[m])
                    and method_key(cls_qual, m) not in self.thread_entries)

        entry: Dict[str, FrozenSet[LockToken]] = {
            m: (tokens if eligible(m) else frozenset()) for m in methods}
        changed = True
        while changed:
            changed = False
            for m in methods:
                if not eligible(m):
                    continue
                new = None
                for caller, held in sites[m]:
                    eff = held | entry.get(caller, frozenset())
                    new = eff if new is None else (new & eff)
                new = new if new is not None else frozenset()
                if new != entry[m]:
                    entry[m] = new
                    changed = True
        self._entry_held[cls_qual] = entry
        return entry

    # ------------------------------------------------------------- queries
    def methods_of(self, cls_qual: str) -> Iterator[Tuple[str, FunctionFacts]]:
        prefix = f"m:{cls_qual}."
        for key, facts in self.facts.items():
            if key.startswith(prefix):
                yield key[len(prefix):], facts

    def intraclass_callsite_counts(self, cls_qual: str) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for _m, facts in self.methods_of(cls_qual):
            for c in facts.calls:
                if c.callee is not None and c.callee.startswith(
                        f"m:{cls_qual}."):
                    name = c.callee[len(f"m:{cls_qual}."):]
                    counts[name] = counts.get(name, 0) + 1
        return counts


class _FactsWalker:
    """Structural walk of one function body with a held-lock environment.

    Nested defs/lambdas/classes are skipped (their bodies run in their own
    scope and are analyzed separately); ``with`` statements stack and
    un-stack lock tokens; everything else is visited expression-wise at
    the current held-set.
    """

    def __init__(self, model: ConcurrencyModel, facts: FunctionFacts):
        self.model = model
        self.prog = model.prog
        self.facts = facts
        self.mod = facts.mod
        self.owner = facts.owner
        self.env = self.prog.local_env(self.mod, facts.fn)

    def run(self) -> None:
        self._stmts(self.facts.fn.body, {})

    # ------------------------------------------------------------ held env
    def _lock_token(self, expr: ast.AST) -> Optional[LockToken]:
        if isinstance(expr, ast.Attribute):
            base_t = self.prog.expr_type(self.mod, self.owner, self.env,
                                         expr.value)
            if base_t is not None:
                groups = self.model.lock_groups(base_t)
                if expr.attr in groups:
                    return ("C", base_t, groups[expr.attr])
        elif isinstance(expr, ast.Name):
            if expr.id in self.model.module_locks.get(self.mod.path, ()):
                return ("M", self.mod.path, expr.id)
        return None

    # ------------------------------------------------------------- walking
    def _stmts(self, body: List[ast.stmt],
               held: Dict[LockToken, ast.AST]) -> None:
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, s: ast.stmt, held: Dict[LockToken, ast.AST]) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            acquired: List[LockToken] = []
            for item in s.items:
                self._expr(item.context_expr, held)
                tok = self._lock_token(item.context_expr)
                if tok is not None:
                    self.facts.acquires.append(Acquire(
                        tok, item.context_expr,
                        tuple(sorted(held.items(), key=str))))
                    if tok not in held:
                        held[tok] = item.context_expr
                        acquired.append(tok)
            self._stmts(s.body, held)
            for tok in acquired:
                del held[tok]
            return
        if isinstance(s, ast.If):
            self._expr(s.test, held)
            self._stmts(s.body, held)
            self._stmts(s.orelse, held)
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._expr(s.iter, held)
            self._expr(s.target, held)
            self._stmts(s.body, held)
            self._stmts(s.orelse, held)
            return
        if isinstance(s, ast.While):
            self._expr(s.test, held)
            self._stmts(s.body, held)
            self._stmts(s.orelse, held)
            return
        if isinstance(s, ast.Try):
            self._stmts(s.body, held)
            for h in s.handlers:
                self._stmts(h.body, held)
            self._stmts(s.orelse, held)
            self._stmts(s.finalbody, held)
            return
        if hasattr(ast, "Match") and isinstance(s, ast.Match):
            self._expr(s.subject, held)
            for case in s.cases:
                if case.guard is not None:
                    self._expr(case.guard, held)
                self._stmts(case.body, held)
            return
        # simple statement: visit every expression it holds
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._expr(child, held)

    def _expr(self, e: ast.AST, held: Dict[LockToken, ast.AST]) -> None:
        if isinstance(e, (ast.Lambda, ast.FunctionDef,
                          ast.AsyncFunctionDef)):
            return  # deferred bodies don't run here
        if isinstance(e, ast.Attribute):
            self._record_access(e, held)
        elif isinstance(e, ast.Call):
            self._record_call(e, held)
        for child in ast.iter_child_nodes(e):
            self._expr(child, held)

    def _record_access(self, node: ast.Attribute,
                       held: Dict[LockToken, ast.AST]) -> None:
        if not (isinstance(node.value, ast.Name)
                and node.value.id == "self" and self.owner is not None):
            return
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        self.facts.accesses.append(Access(
            node.attr, write, node,
            frozenset(held)))

    def _record_call(self, node: ast.Call,
                     held: Dict[LockToken, ast.AST]) -> None:
        callee: Optional[FuncKey] = None
        recv_type: Optional[str] = None
        recv_token: Optional[LockToken] = None
        func = node.func
        if isinstance(func, ast.Attribute):
            recv_type = self.prog.expr_type(self.mod, self.owner, self.env,
                                            func.value)
            recv_token = self._lock_token(func.value)
            if recv_type is not None:
                cm = self.prog.classes.get(recv_type)
                if cm is not None and func.attr in cm.methods:
                    callee = method_key(recv_type, func.attr)
        else:
            cm = self.prog.resolve_class(self.mod, func)
            if cm is not None and "__init__" in cm.methods:
                callee = method_key(cm.qualname, "__init__")
            else:
                fq = self.prog.resolve_function(self.mod, func)
                if fq is not None:
                    callee = f"f:{fq}"
        self.facts.calls.append(CallFact(
            node, callee, recv_type, recv_token,
            tuple(sorted(held.items(), key=str))))
        # thread spawn? resolve the target callable
        resolved = self.mod.resolve(func)
        kw_name = _THREAD_CTORS.get(resolved or "")
        if kw_name is not None:
            target = None
            for kw in node.keywords:
                if kw.arg == kw_name:
                    target = kw.value
            if target is None and resolved == "threading.Timer" \
                    and len(node.args) >= 2:
                target = node.args[1]
            if target is not None:
                tkey = self._callable_key(target)
                if tkey is not None:
                    self.facts.spawn_targets.append(tkey)

    def _callable_key(self, expr: ast.AST) -> Optional[FuncKey]:
        if isinstance(expr, ast.Attribute):
            base_t = self.prog.expr_type(self.mod, self.owner, self.env,
                                         expr.value)
            if base_t is not None:
                return method_key(base_t, expr.attr)
        elif isinstance(expr, ast.Name):
            fq = self.prog.resolve_function(self.mod, expr)
            if fq is not None:
                return f"f:{fq}"
        return None
