"""Pipeline parallelism — GPipe-style stages over a ``stage`` mesh axis.

No reference twin exists (``SURVEY.md`` §2.3: the reference's only
model-state sharding is ZeRO-3); this is a capability the TPU framework
adds, completing the parallelism quartet (data / tensor / sequence /
pipeline).  The design is TPU-idiomatic SPMD, not a multi-controller
scheduler:

- the stacked layer tree ``params['layers']`` (leading dim ``L``) shards
  its leading dim across ``stage`` — each device physically holds ``L/S``
  contiguous layers (plus replicated embeddings/head, which are small);
- one ``shard_map`` program runs the classic pipelined loop: the batch
  splits into ``M`` microbatches, and for ``M + S - 1`` ticks every stage
  runs its layer slice and ``ppermute``s activations to the next stage —
  the same single-program pipeline loop TPU pod frameworks use, with the
  (S-1)/(M+S-1) GPipe bubble;
- backward is ``jax.grad`` straight through the tick scan and the
  ``ppermute`` (whose transpose is the reverse permutation), i.e. the
  reversed pipeline, with gradients for each stage's layers landing on
  that stage and gradients for the replicated trees ``psum``-combined.

Cost note: embeddings and the pooler/classifier head are replicated, so
EVERY stage computes the full-batch embedding pass and the head (the
results are discarded on all but the first/last stage via the masked-psum
selects).  At BERT scale this is deliberate — embed+head are <2% of layer
FLOPs and replicating them keeps the tick loop free of extra collectives —
but it grows linearly with stage count; a deep-pipeline deployment would
gate them on ``axis_index`` at the price of a divergent program per stage.

Dropout note: per-layer streams key on *global* layer indices
(``bert.run_layers``), so each layer's stream is stage-placement-invariant;
the microbatch split makes the batch-level stream differ from the
single-device run, so exact-parity tests run dropout=0 (as the other
strategy-parity tests do).
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pdnlp_tpu.parallel.compat import shard_map
from pdnlp_tpu.models import bert
from pdnlp_tpu.models.config import BertConfig
from pdnlp_tpu.parallel.mesh import DATA_AXIS
from pdnlp_tpu.train.precision import resolve_dtype
from pdnlp_tpu.train.steps import init_state, weighted_ce

STAGE = "stage"
State = Dict[str, object]


def _is_layer_path(path) -> bool:
    return any(isinstance(k, jax.tree_util.DictKey) and k.key == "layers"
               for k in path)


def pp_specs(tree):
    """PartitionSpec pytree for ``shard_map``: layer-stack leaves split
    their leading (layer) dim over ``stage``; everything else replicates.
    The Adam moments inherit the rule through their mirrored tree paths."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: P(STAGE) if _is_layer_path(path) else P(), tree)


def pp_shardings(tree, mesh: Mesh):
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), pp_specs(tree))


def setup_pp_model(args, vocab_size: int, mesh: Mesh, total_steps: int = None
                   ) -> Tuple[BertConfig, optax.GradientTransformation, State, object]:
    """(cfg, tx, state, shardings) with the layer stack sharded over
    ``stage`` from init — the pipeline twin of ``setup_sharded_model``."""
    from pdnlp_tpu.models import get_config
    from pdnlp_tpu.models.config import args_overrides
    from pdnlp_tpu.train.optim import build_optimizer, make_schedule
    from pdnlp_tpu.utils.seeding import set_seed, train_key

    if STAGE not in mesh.shape:
        raise ValueError(
            f"pp needs a {STAGE!r} mesh axis; got {dict(mesh.shape)} — "
            'pass --mesh_shape \'{"stage": S}\'')
    if getattr(args, "ema_decay", 0.0) > 0:
        raise ValueError("--ema_decay runs on the jit strategies (dp/zero/"
                         "tp/ep) — the pipeline step does not maintain the "
                         "EMA tree")
    n_stages = mesh.shape[STAGE]
    cfg = get_config(args.model, vocab_size=vocab_size, num_labels=args.num_labels,
                     dropout=args.dropout, attn_dropout=args.attn_dropout,
                     **args_overrides(args))
    if cfg.num_layers % n_stages:
        raise ValueError(f"pipeline degree {n_stages} must divide num_layers "
                         f"({cfg.num_layers}) — stages hold contiguous "
                         "layer slices")
    # MoE composes with pp: expert stacks [L, E, in, out] split their
    # leading layer dim like every other layer weight, and the tick loop
    # accumulates each stage's share of the load-balancing aux (gated to
    # real ticks; psum'd over stages in the train step)
    root = set_seed(args.seed)
    init_key, _ = jax.random.split(root)
    train_rng = train_key(args.seed, getattr(args, "rng_impl", "rbg"))
    param_shapes = jax.eval_shape(lambda k: bert.init_params(k, cfg), init_key)
    tx = build_optimizer(param_shapes, args,
                         schedule=make_schedule(args, total_steps))

    def init_fn(key, rng):
        return init_state(key, cfg, tx, rng=rng, params=bert.init_params(key, cfg))

    state_shapes = jax.eval_shape(init_fn, init_key, train_rng)
    shardings = pp_shardings(state_shapes, mesh)
    state = jax.jit(init_fn, out_shardings=shardings)(init_key, train_rng)
    if getattr(args, "init_from", None):
        from pdnlp_tpu.train.pretrain import load_encoder

        params = load_encoder(args.init_from, state["params"],
                              head=getattr(args, "init_head", False))
        state["params"] = jax.device_put(params, shardings["params"])
    return cfg, tx, state, shardings


def _pp_logits(params, batch, cfg, *, n_stages: int, n_micro: int, dtype,
               deterministic: bool, rng, remat: bool, attn_impl: str,
               unroll):
    """The pipelined forward, INSIDE ``shard_map``: returns ``(logits,
    aux)`` where logits [B, num_labels] are only meaningful on the LAST
    stage (callers ``psum``-select) and ``aux`` is this STAGE's share of
    the MoE load-balancing loss (0 for dense models; callers ``psum`` over
    ``stage``).  ``params['layers']`` leaves arrive with leading dim
    ``L/S`` (this stage's slice)."""
    s = jax.lax.axis_index(STAGE)
    B = batch["label"].shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by {n_micro} microbatches")
    b = B // n_micro
    local_layers = params["layers"]
    lk = jax.tree_util.tree_leaves(local_layers)[0].shape[0]
    seq = batch["input_ids"].shape[1]
    if rng is None:
        rng = jax.random.key(0)

    # embeddings depend only on the batch, not the pipeline carry: one pass
    # over the full batch before the loop, dynamic-indexed per tick
    x_emb, rng = bert.embed(params, cfg, batch["input_ids"],
                            batch["token_type_ids"], dtype=dtype,
                            deterministic=deterministic, rng=rng)
    x_emb = x_emb.reshape(n_micro, b, seq, cfg.hidden_size)
    masks = batch["attention_mask"].reshape(n_micro, b, seq)

    def tick(carry, t):
        h_in, outs, aux_sum = carry
        # stage 0 ingests microbatch t; this stage holds microbatch t - s
        # (both clipped during fill/drain bubble ticks)
        t_in = jnp.clip(t, 0, n_micro - 1)
        x0 = jax.lax.dynamic_index_in_dim(x_emb, t_in, 0, keepdims=False)
        x = jnp.where(s == 0, x0, h_in)
        m_here = jnp.clip(t - s, 0, n_micro - 1)
        mask = jax.lax.dynamic_index_in_dim(masks, m_here, 0, keepdims=False)
        x, aux = bert.run_layers(
            local_layers, cfg, x, li=s * lk + jnp.arange(lk),
            bias=bert.mask_bias(mask, dtype), dtype=dtype,
            deterministic=deterministic,
            rng=jax.random.fold_in(rng, m_here), remat=remat,
            attn_impl=attn_impl, unroll=unroll, with_aux=True,
            token_mask=mask)
        # bubble ticks recompute a clipped microbatch whose result is
        # discarded — its aux must not count (it would double-weight the
        # edge microbatches); a real tick on this stage is 0 <= t-s < M
        real = ((t - s >= 0) & (t - s < n_micro)).astype(aux.dtype)
        aux_sum = aux_sum + aux * real
        # the last stage finishes microbatch t - (S-1) this tick; only its
        # [CLS] row feeds the head, so that is all the loop accumulates
        done = t - (n_stages - 1)
        d_idx = jnp.clip(done, 0, n_micro - 1)
        write = (s == n_stages - 1) & (done >= 0) & (done < n_micro)
        cur = jax.lax.dynamic_index_in_dim(outs, d_idx, 0, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(write, x[:, 0, :], cur), d_idx, 0)
        h_out = jax.lax.ppermute(
            x, STAGE, [(i, (i + 1) % n_stages) for i in range(n_stages)])
        return (h_out, outs, aux_sum), None

    h0 = jnp.zeros((b, seq, cfg.hidden_size), dtype)
    outs0 = jnp.zeros((n_micro, b, cfg.hidden_size), dtype)
    (_, outs, aux_sum), _ = jax.lax.scan(
        tick, (h0, outs0, jnp.zeros((), jnp.float32)),
        jnp.arange(n_micro + n_stages - 1))

    logits = bert.pooled_logits(
        params, cfg, outs.reshape(B, cfg.hidden_size), dtype=dtype,
        drop_rng=None if deterministic else jax.random.fold_in(rng, 10_000))
    # mean over microbatches: each real tick added this stage's layer-slice
    # aux for one microbatch, so the per-microbatch mean matches the dense-
    # dispatch convention (sum over layers of batch-statistic aux) up to
    # the estimator (per-microbatch vs full-batch statistics)
    return logits, aux_sum / n_micro


def _select_last(x, n_stages: int):
    """Zero out every stage's value but the last's, then ``psum`` — the
    SPMD way to read a value that only the final pipeline stage owns."""
    s = jax.lax.axis_index(STAGE)
    on_last = (s == n_stages - 1).astype(x.dtype)
    return jax.lax.psum(x * on_last, STAGE)


def _lazy_jit(make):
    """Defer jit+shard_map construction to the first call so ``in_specs``
    can be derived from the caller's actual pytree (optax wrappers vary
    with the configured schedule)."""
    compiled = {}

    def call(first, *rest):
        if "fn" not in compiled:
            compiled["fn"] = make(first)
        return compiled["fn"](first, *rest)

    return call


def make_pp_train_step(cfg: BertConfig, tx, args, mesh: Mesh,
                       n_micro: int = 4):
    """Compile the pipelined train step.  Gradients of each stage's layer
    slice stay on that stage; gradients of the replicated trees are
    ``psum``-combined (they receive nonzero cotangents only on the stages
    that use them — embeddings on stage 0, the head on the last).

    Composes with data parallelism: on a ``(data x stage)`` mesh the batch
    arrives split along ``data``, each data shard runs its own pipeline,
    and gradients weight-combine across shards exactly as the shard_map
    (Horovod-analog) path does — the global-mean gradient stays exact even
    when filler rows make shards uneven."""
    n_stages = mesh.shape[STAGE]
    has_data = DATA_AXIS in mesh.shape
    dtype = resolve_dtype(args.dtype)
    remat = bool(args.remat)
    attn_impl = args.attention_impl  # ops.attention routes "auto" per trace
    from pdnlp_tpu.train.steps import _unroll

    unroll = _unroll(args)
    smoothing = args.label_smoothing
    batch_spec = P(DATA_AXIS) if has_data else P()

    def loss_fn(params, batch, rng):
        logits, aux = _pp_logits(params, batch, cfg, n_stages=n_stages,
                                 n_micro=n_micro, dtype=dtype,
                                 deterministic=False, rng=rng, remat=remat,
                                 attn_impl=attn_impl, unroll=unroll)
        loss, correct, objective = weighted_ce(
            logits, batch["label"], batch["example_weight"],
            smoothing=smoothing)
        # objective (smoothed + MoE aux, each stage contributing its layer
        # slice's share) is differentiated; bare CE is reported
        objective = (_select_last(objective, n_stages)
                     + cfg.moe_aux_coef * jax.lax.psum(aux, STAGE))
        return objective, (
            _select_last(loss, n_stages), _select_last(correct, n_stages))

    def per_device(state: State, batch):
        rng = jax.random.fold_in(state["rng"], state["step"])
        if has_data:  # distinct dropout stream per data shard (cf. shardmap)
            rng = jax.random.fold_in(rng, jax.lax.axis_index(DATA_AXIS))
        (_, (loss, correct)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"], batch, rng)
        if has_data:
            # local grads are weighted means over the local shard; combine
            # them weighted by local weight mass -> exact global mean
            from pdnlp_tpu.parallel.collectives import weighted_shard_scale

            scale, gw = weighted_shard_scale(
                batch["example_weight"].sum(), DATA_AXIS)
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            loss = jax.lax.psum(loss * scale, DATA_AXIS)
            correct = jax.lax.psum(correct, DATA_AXIS)
        else:
            gw = jnp.maximum(batch["example_weight"].sum(), 1.0)

        def reduce_g(g, with_stage):
            axes = ((DATA_AXIS,) if has_data else ()) + \
                   ((STAGE,) if with_stage else ())
            return jax.lax.psum(g, axes) if axes else g

        grads = {k: jax.tree_util.tree_map(
                     lambda g: reduce_g(g, with_stage=(k != "layers")), v)
                 for k, v in grads.items()}
        updates, opt_state = tx.update(grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        new_state = {"params": params, "opt_state": opt_state,
                     "step": state["step"] + 1, "rng": state["rng"]}
        return new_state, {"loss": loss, "accuracy": correct / gw}

    return _lazy_jit(lambda state: jax.jit(
        shard_map(
            per_device, mesh=mesh,
            in_specs=(pp_specs(state), batch_spec),
            out_specs=(pp_specs(state), P()),
            check_vma=False,
        ),
        donate_argnums=0,
    ))


def make_pp_eval_step(cfg: BertConfig, args, mesh: Mesh, n_micro: int = 4):
    """Deterministic pipelined eval step with ``build_eval_step``'s metric
    contract: global scalar sums (replicated), per-row preds/labels left
    sharded along ``data`` (the host fetch is the all-gather)."""
    n_stages = mesh.shape[STAGE]
    has_data = DATA_AXIS in mesh.shape
    dtype = resolve_dtype(args.dtype)
    attn_impl = args.attention_impl  # ops.attention routes "auto" per trace
    from pdnlp_tpu.train.steps import _unroll

    unroll = _unroll(args)
    batch_spec = P(DATA_AXIS) if has_data else P()

    def data_sum(x):
        return jax.lax.psum(x, DATA_AXIS) if has_data else x

    def per_device(params, batch):
        logits, _ = _pp_logits(params, batch, cfg, n_stages=n_stages,
                               n_micro=n_micro, dtype=dtype,
                               deterministic=True, rng=None, remat=False,
                               attn_impl=attn_impl, unroll=unroll)
        w = batch["example_weight"]
        loss, correct, _ = weighted_ce(logits, batch["label"], w)
        return {
            "loss_sum": data_sum(
                _select_last(loss * jnp.maximum(w.sum(), 1.0), n_stages)),
            "weight": data_sum(w.sum()),
            "correct": data_sum(_select_last(correct, n_stages)),
            "pred": _select_last(jnp.argmax(logits, -1), n_stages),
            "label": batch["label"],
            "ew": w,
        }

    out_specs = {"loss_sum": P(), "weight": P(), "correct": P(),
                 "pred": batch_spec, "label": batch_spec, "ew": batch_spec}
    return _lazy_jit(lambda params: jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(pp_specs(params), batch_spec),
        out_specs=out_specs,
        check_vma=False,
    )))


def make_pp_batch(mesh: Mesh):
    """Host batch -> global arrays on the pipeline mesh: split along
    ``data`` when that axis exists (each shard runs its own pipeline),
    replicated across ``stage`` (activations, not data, flow stage to
    stage).  ``make_array_from_process_local_data`` covers both the
    single-process mesh and a mesh whose axes span processes (each host
    contributes its data shard / its replica of the full batch)."""
    spec = P(DATA_AXIS) if DATA_AXIS in mesh.shape else P()
    sh = NamedSharding(mesh, spec)

    def put(batch):
        return jax.tree_util.tree_map(
            lambda a: jax.make_array_from_process_local_data(
                sh, np.asarray(a)), batch)

    return put
