"""Collective helpers — the NCCL ``all_reduce`` / ``all_gather`` twins.

The reference reduces the step loss and gathers eval outputs explicitly
(``loss_reduce`` / ``output_reduce``, ``/root/reference/multi-gpu-distributed-
cls.py:139-155``) and syncs ranks with ``dist.barrier()`` (``:171``).  On TPU
these become ``lax`` collectives compiled onto ICI — used *explicitly* only
inside ``shard_map`` bodies (the Horovod-style path); the jit/NamedSharding
path gets the same collectives inserted by XLA from sharding annotations.

``make_global_batch`` is the ``DistributedSampler`` + host->device half: each
process feeds its local shard of the batch and the result is ONE global
``jax.Array`` laid out along the mesh's data axis.
"""
from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pdnlp_tpu.parallel.mesh import DATA_AXIS


def loss_reduce(loss: jax.Array, axis: str = DATA_AXIS) -> jax.Array:
    """Mean over data-parallel shards (``dist.all_reduce(SUM)/world_size``,
    ``multi-gpu-distributed-cls.py:139-143``)."""
    return lax.pmean(loss, axis)


def weighted_shard_scale(local_weight: jax.Array, axis: str = DATA_AXIS):
    """``(scale, global_weight)`` for combining per-shard weighted means
    into the exact global weighted mean: each shard's grad/loss (a mean
    over its local weight mass) is multiplied by ``scale = lw/gw`` and
    ``psum``'d.  Exact even when filler rows make shards uneven, and the
    zero guard keeps an all-filler global batch at 0 instead of 0/0 NaN
    (the guard ``steps.weighted_ce`` applies locally, applied globally).
    Shared by the shard_map (Horovod-analog) and pipeline train steps."""
    gw = jnp.maximum(lax.psum(local_weight, axis), 1.0)
    return local_weight / gw, gw


def grad_reduce(grads, axis: str = DATA_AXIS, compress_dtype=None):
    """Mean-reduce a gradient pytree across the data axis.

    ``compress_dtype=jnp.bfloat16`` reduces in bf16 — the wire-compression
    analog of Horovod's ``hvd.Compression.fp16``
    (``/root/reference/multi-gpu-horovod-cls.py:344-349``)."""

    def red(g):
        if compress_dtype is not None:
            return lax.pmean(g.astype(compress_dtype), axis).astype(g.dtype)
        return lax.pmean(g, axis)

    return jax.tree_util.tree_map(red, grads)


def output_reduce(outputs: jax.Array, targets: jax.Array, axis: str = DATA_AXIS):
    """All-gather per-shard eval outputs into global arrays
    (``dist.all_gather``, ``multi-gpu-distributed-cls.py:145-155``)."""
    return (lax.all_gather(outputs, axis, tiled=True),
            lax.all_gather(targets, axis, tiled=True))


def barrier() -> None:
    """Host-level sync across processes (the ``dist.barrier()`` analog).
    Device-side ordering needs no barrier — XLA program order provides it."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("pdnlp_tpu.barrier")


def make_global_batch(mesh: Mesh, axis: str = DATA_AXIS,
                      leading_stack: bool = False
                      ) -> Callable[[Dict], Dict[str, jax.Array]]:
    """Returns ``put(batch)``: host-local numpy batch -> global ``jax.Array``
    dict sharded along the data axis.  Single-process: the full batch is
    scattered over local devices.  Multi-process: each host contributes its
    shard (built by ``DistributedShardSampler``) and the global array spans
    hosts — no gather ever materializes on one device.

    ``leading_stack=True`` is the fused-multi-step layout: arrays carry a
    leading ``[K]`` step axis that stays unsharded; the batch axis (dim 1)
    shards over ``data``."""
    spec = P(None, axis) if leading_stack else P(axis)
    sharding = NamedSharding(mesh, spec)

    def put(batch: Dict) -> Dict[str, jax.Array]:
        return {
            k: jax.make_array_from_process_local_data(sharding, v)
            for k, v in batch.items()
        }

    return put
