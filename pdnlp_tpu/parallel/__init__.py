"""Parallelism layer: mesh construction, sharding rules, collectives, and
multi-process runtime — the TPU-native replacement for the reference's
NCCL/DDP/Horovod/DeepSpeed machinery (``SURVEY.md`` §2.3-2.4)."""
from pdnlp_tpu.parallel.collectives import (
    barrier, grad_reduce, loss_reduce, make_global_batch, output_reduce,
)
from pdnlp_tpu.parallel.execution import (
    make_parallel_eval_step, make_parallel_train_step, make_shardmap_train_step,
    setup_sharded_model,
)
from pdnlp_tpu.parallel.mesh import DATA_AXIS, local_batch_mult, make_mesh
from pdnlp_tpu.parallel.runtime import init_runtime
from pdnlp_tpu.parallel.sharding import (
    batch_sharding, replicated, shard_fraction, state_shardings,
)

__all__ = [
    "DATA_AXIS", "barrier", "batch_sharding", "grad_reduce", "init_runtime",
    "local_batch_mult", "loss_reduce", "make_global_batch", "make_mesh",
    "make_parallel_eval_step", "make_parallel_train_step",
    "make_shardmap_train_step", "output_reduce", "replicated",
    "setup_sharded_model", "shard_fraction", "state_shardings",
]
