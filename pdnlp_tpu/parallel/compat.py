"""Version-portable ``shard_map`` — one import site for the whole tree.

``jax.shard_map`` (with ``check_vma``) landed after jax 0.4.x; the 0.4.37
this image ships only has ``jax.experimental.shard_map.shard_map`` (with the
older ``check_rep`` spelling of the same knob).  Every shard_map call in the
repo (``parallel.sp``/``execution``/``pp``, the sp tests, the longcontext
smoke) routes through :func:`shard_map` here, so the sequence-parallel and
explicit-collectives paths run on BOTH jax generations instead of dying with
``AttributeError: module 'jax' has no attribute 'shard_map'`` on this image
(the seed's test_sp/test_parallel failure mode).
"""
from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

else:  # jax <= 0.4.x: the experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)


def axis_size(axis_name: str) -> int:
    """Static size of a mapped mesh axis, from inside ``shard_map``.

    ``jax.lax.axis_size`` post-dates this image's jax; the 0.4.x spelling
    is the core axis frame (same static int, resolved at trace time —
    0.4.37's ``axis_frame`` returns the size directly, earlier cores a
    frame object carrying it)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return getattr(frame, "size", frame)
