"""Sharded experiment assembly + compiled parallel steps.

This is where strategy becomes *placement*: the same step functions from
``train.steps`` get compiled with explicit mesh shardings.

- ``setup_sharded_model``: init the train state **already sharded** — the
  shardings are computed from ``jax.eval_shape`` (no memory), then the init
  runs under ``jit`` with ``out_shardings``, so a ZeRO run never materializes
  a full replica (the analog of DeepSpeed partitioning params at init,
  ``/root/reference/multi-gpu-deepspeed-cls.py:296-302``).
- ``make_parallel_train_step`` / ``make_parallel_eval_step``: ``jit`` with
  in/out shardings — XLA inserts the gradient all-reduce (DDP's NCCL hooks)
  or all-gather/reduce-scatter (ZeRO-3) on ICI.
- ``make_shardmap_train_step``: the explicit-collectives flavor (Horovod
  analog, ``/root/reference/multi-gpu-horovod-cls.py:338-350``): per-device
  code with hand-written ``psum`` of bf16-compressed gradients.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from pdnlp_tpu.models import BertConfig, bert, get_config
from pdnlp_tpu.models.config import args_overrides
from pdnlp_tpu.parallel import collectives
from pdnlp_tpu.parallel.compat import shard_map
from pdnlp_tpu.parallel.mesh import DATA_AXIS
from pdnlp_tpu.parallel.sharding import batch_sharding, replicated, state_shardings
from pdnlp_tpu.train.optim import build_optimizer
from pdnlp_tpu.train.precision import resolve_dtype
from pdnlp_tpu.train.steps import (
    State, build_eval_step, build_train_step, init_state, weighted_ce,
)
from pdnlp_tpu.utils.seeding import set_seed


def setup_sharded_model(args, vocab_size: int, mesh: Mesh, mode: str = "dp",
                        total_steps: int = None
                        ) -> Tuple[BertConfig, optax.GradientTransformation, State, Any]:
    """(cfg, tx, state, shardings) — state lives on the mesh from birth.

    ``total_steps`` sizes the optional LR schedule (``--lr_schedule``);
    required when one is configured."""
    from pdnlp_tpu.train.optim import make_schedule
    from pdnlp_tpu.utils.seeding import train_key

    cfg = get_config(args.model, vocab_size=vocab_size, num_labels=args.num_labels,
                     dropout=args.dropout, attn_dropout=args.attn_dropout,
                     **args_overrides(args))
    if mode == "tp":
        from pdnlp_tpu.parallel.sharding import MODEL_AXIS

        if cfg.moe_experts:
            raise ValueError("tp does not support MoE models (the expert "
                             "dim needs the ep mode's placement)")
        m = mesh.shape.get(MODEL_AXIS, 1)
        if cfg.num_heads % m or cfg.intermediate_size % m:
            raise ValueError(
                f"tensor-parallel degree {m} must divide num_heads "
                f"({cfg.num_heads}) and intermediate_size "
                f"({cfg.intermediate_size}) — heads and MLP features split "
                "across the model axis")
    if mode == "ep":
        from pdnlp_tpu.parallel.sharding import EXPERT_AXIS

        e = mesh.shape.get(EXPERT_AXIS, 1)
        if not cfg.moe_experts:
            raise ValueError(f"ep needs an MoE model ({args.model} is "
                             "dense) — use bert-base-moe / bert-tiny-moe "
                             "or set moe_experts")
        if cfg.moe_experts % e:
            raise ValueError(f"expert-parallel degree {e} must divide "
                             f"moe_experts ({cfg.moe_experts})")
    root = set_seed(args.seed)
    init_key, _ = jax.random.split(root)
    train_rng = train_key(args.seed, getattr(args, "rng_impl", "rbg"))

    # tx needs a params *structure* for the weight-decay mask — shapes only.
    param_shapes = jax.eval_shape(lambda k: bert.init_params(k, cfg), init_key)
    tx = build_optimizer(param_shapes, args,
                         schedule=make_schedule(args, total_steps))

    def init_fn(key, rng):
        params = bert.init_params(key, cfg)
        return init_state(key, cfg, tx, rng=rng, params=params,
                          ema=getattr(args, "ema_decay", 0.0) > 0)

    state_shapes = jax.eval_shape(init_fn, init_key, train_rng)
    shardings = state_shardings(state_shapes, mesh, mode)
    offload = getattr(args, "offload_opt_state", False)
    state = jax.jit(init_fn, out_shardings=shardings)(init_key, train_rng)
    if offload:
        # Adam moments move to host RAM (DeepSpeed offload_optimizer
        # analog); the train step stages them explicitly.  The move happens
        # EAGERLY after init — memory-kind annotations inside the init jit
        # would spread to its integer outputs, which XLA's SPMD partitioner
        # rejects ("Side-effect HLO must have sharding" on s32 scalars).
        from pdnlp_tpu.parallel.sharding import with_memory_kind

        shardings = dict(shardings)
        shardings["opt_state"] = with_memory_kind(
            shardings["opt_state"], "pinned_host",
            shape_tree=state_shapes["opt_state"])
        state["opt_state"] = jax.device_put(state["opt_state"],
                                            shardings["opt_state"])
    if getattr(args, "init_from", None):
        # warm-start the encoder from an in-repo pretrain checkpoint (the
        # from_pretrained analog); head stays fresh, placement is preserved
        # (ZeRO leaves go straight to their shards)
        from pdnlp_tpu.train.pretrain import load_encoder

        params = load_encoder(args.init_from, state["params"],
                              head=getattr(args, "init_head", False))
        state["params"] = jax.device_put(params, shardings["params"])
        if "ema" in state:  # the EMA tracks the WARM-STARTED weights
            state["ema"] = jax.device_put(params, shardings["ema"])
    if "ema" in state:
        # force DISTINCT buffers: the init jit (and device_put's cache) may
        # alias the identical params/ema values to one buffer — the first
        # donated train step would then invalidate both references
        # (observed as "TPU backend error (InvalidArgument)" at eval fetch)
        state["ema"] = jax.tree_util.tree_map(jnp.copy, state["ema"])
    return cfg, tx, state, shardings


def make_parallel_train_step(cfg: BertConfig, tx, args, mesh: Mesh, shardings):
    """Compile the fused train step over the mesh.  DP vs ZeRO is entirely
    encoded in ``shardings`` — the step function is identical."""
    opt_staging = None
    if getattr(args, "offload_opt_state", False):
        from jax.sharding import NamedSharding

        # host-kind leaves (the float moments) stage to device and back;
        # everything else keeps its original sharding — explicit memory-kind
        # annotations on replicated integer scalars break SPMD partitioning
        def to_device(s):
            if getattr(s, "memory_kind", None) == "pinned_host":
                return NamedSharding(s.mesh, s.spec, memory_kind="device")
            return s

        opt_staging = (jax.tree_util.tree_map(to_device, shardings["opt_state"]),
                       shardings["opt_state"])
    fn = build_train_step(cfg, tx, args, opt_staging=opt_staging)
    return jax.jit(
        fn,
        donate_argnums=0,
        in_shardings=(shardings, batch_sharding(mesh)),
        out_shardings=(shardings, replicated(mesh)),
    )


def make_parallel_multi_step(cfg: BertConfig, tx, args, mesh: Mesh, shardings):
    """K-step fused variant of ``make_parallel_train_step`` (batches carry a
    leading unsharded ``[K]`` axis; batch dim shards over ``data``)."""
    from jax.sharding import NamedSharding
    from pdnlp_tpu.train.steps import build_multi_step

    fn = build_multi_step(build_train_step(cfg, tx, args))
    batch_sh = NamedSharding(mesh, P(None, DATA_AXIS))
    metrics_sh = replicated(mesh)
    return jax.jit(
        fn,
        donate_argnums=0,
        in_shardings=(shardings, batch_sh),
        out_shardings=(shardings, metrics_sh),
    )


def make_parallel_eval_step(cfg: BertConfig, args, mesh: Mesh, param_shardings):
    """Eval step over the mesh; outputs replicated so every host can read
    them (the ``output_reduce`` all-gather, ``multi-gpu-distributed-cls.py:
    145-155``, inserted by XLA)."""
    fn = build_eval_step(cfg, args)
    return jax.jit(
        fn,
        in_shardings=(param_shardings, batch_sharding(mesh)),
        out_shardings=replicated(mesh),
    )


def make_shardmap_train_step(cfg: BertConfig, tx, args, mesh: Mesh,
                             compress_grads: bool = True):
    """Explicit-collectives train step (Horovod analog).

    Per-device body: local forward/backward on the batch shard, then a
    hand-written weighted ``psum`` of gradients — optionally compressed to
    bf16 on the wire (``hvd.Compression.fp16``,
    ``/root/reference/multi-gpu-horovod-cls.py:344-349``) — then an identical
    replicated optimizer update on every device.

    Exactness: the global loss is sum(w*ce)/sum(w) over the *global* batch.
    Each shard computes its local weighted-mean grad; shards are then
    combined weighted by their local weight mass, which reproduces the
    global-mean gradient exactly even when filler rows make shards uneven.
    """
    from pdnlp_tpu.train.steps import _unroll

    if getattr(args, "ema_decay", 0.0) > 0:
        raise ValueError("--ema_decay runs on the jit strategies (dp/zero/"
                         "tp/ep) — the shard_map step does not maintain the "
                         "EMA tree and would silently evaluate stale "
                         "weights")
    dtype = resolve_dtype(args.dtype)
    remat = bool(args.remat)
    attn_impl = args.attention_impl  # ops.attention routes "auto" per trace
    compress = jnp.bfloat16 if compress_grads else None
    unroll = _unroll(args)
    smoothing = args.label_smoothing

    def local_loss(params, batch, rng):
        # MoE aux (0 for dense): computed over the LOCAL shard's batch and
        # weight-averaged across shards with the loss below — a per-shard
        # estimator of the balancing statistics, vs the jit paths' global-
        # batch one (the standard per-device formulation; both pressure the
        # router identically in expectation).  It joins the optimized
        # objective only — the reported loss stays bare CE.
        logits, aux = bert.classify(params, cfg, batch, dtype=dtype,
                                    deterministic=False, rng=rng, remat=remat,
                                    attn_impl=attn_impl, unroll=unroll,
                                    return_aux=True)
        loss, correct, objective = weighted_ce(
            logits, batch["label"], batch["example_weight"],
            smoothing=smoothing)
        return objective + cfg.moe_aux_coef * aux, (
            loss, correct, batch["example_weight"].sum())

    def per_device(state: State, batch) -> Tuple[State, Dict[str, jax.Array]]:
        # distinct dropout stream per shard, common stream per step
        rng = jax.random.fold_in(state["rng"], state["step"])
        rng = jax.random.fold_in(rng, jax.lax.axis_index(DATA_AXIS))
        (_, (loss, correct, lw)), grads = jax.value_and_grad(
            local_loss, has_aux=True)(state["params"], batch, rng)
        from pdnlp_tpu.parallel.collectives import weighted_shard_scale

        scale, gw = weighted_shard_scale(lw, DATA_AXIS)
        grads = jax.tree_util.tree_map(
            lambda g: (jax.lax.psum((g * scale).astype(compress), DATA_AXIS)
                       .astype(g.dtype)) if compress is not None
            else jax.lax.psum(g * scale, DATA_AXIS),
            grads,
        )
        loss = jax.lax.psum(loss * scale, DATA_AXIS)
        acc = jax.lax.psum(correct, DATA_AXIS) / gw
        updates, opt_state = tx.update(grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        new_state = {"params": params, "opt_state": opt_state,
                     "step": state["step"] + 1, "rng": state["rng"]}
        return new_state, {"loss": loss, "accuracy": acc}

    batch_specs = P(DATA_AXIS)
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), batch_specs),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=0)
