"""Sequence parallelism — training over a ``('data', 'seq')`` mesh.

The long-context path: activations are sharded along the *sequence* inside
each data shard, attention runs as ring attention (``ops.ring``), and the
classifier head is computed from the psum-broadcast [CLS] vector.  The
gradient-correctness subtlety is the redundant head compute: every seq
shard produces identical logits, so the loss is *gated to seq-shard 0* —
its backward broadcasts the pooled cotangent to all shards through the
psum, each shard backpropagates exactly its own sequence slice, and the
plain ``psum`` of gradients over ``seq`` counts head parameters once.

This capability has no reference twin (``SURVEY.md`` §5: long-context
"absent"); it exists so the framework scales past single-device sequence
lengths.  The full dropout recipe applies — hidden-state dropout per
shard and attention-probability dropout per ring block (``ops.ring``) —
so sp trains the same model as every other strategy.  Measured on the chip at the lengths it exists for: 7.0 steps/s
training ``bert-base-long`` at seq 1024 (57k tokens/s,
``results/longcontext.json``); multi-shard parity is pinned by
``tests/test_sp.py``, the multichip dryrun, and a seq axis spanning two
real OS processes in ``tests/test_spawn.py``.
"""
from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pdnlp_tpu.models import BertConfig, bert
from pdnlp_tpu.parallel.compat import shard_map
from pdnlp_tpu.train.precision import resolve_dtype
from pdnlp_tpu.train.steps import State, weighted_ce

DATA, SEQ = "data", "seq"

#: [B, S] per-TOKEN channels shard along the sequence axis; everything
#: else — flat [B] labels and the packed rows' per-SEGMENT [B, M] channels
#: (``cls_positions``/``label``/``example_weight``, whose second dim is
#: the segment slot count, not the sequence) — shards over data only.
#: One definition shared by batch placement and the step in_specs, so a
#: packed channel can never be sharded one way on upload and another in
#: the program.
TOKEN_KEYS = ("input_ids", "attention_mask", "token_type_ids",
              "segment_ids", "position_ids")


def sp_spec(key: str, val) -> P:
    """The PartitionSpec for one batch channel on the (data, seq) mesh."""
    return P(DATA, SEQ) if (getattr(val, "ndim", 0) == 2
                            and key in TOKEN_KEYS) else P(DATA)


def _flat_ce(logits, labels, weights, smoothing: float = 0.0):
    """``weighted_ce`` over packed ([B, M, C] / [B, M]) or flat inputs —
    per-segment outputs flatten to the per-example stream exactly as
    ``train.steps.build_train_step`` does, so sp's packed loss IS the
    single-device packed loss."""
    if logits.ndim == 3:
        logits = logits.reshape(-1, logits.shape[-1])
        labels = labels.reshape(-1)
        weights = weights.reshape(-1)
    return weighted_ce(logits, labels, weights, smoothing=smoothing), weights


def make_sp_batch(mesh: Mesh) -> Callable[[Dict], Dict[str, jax.Array]]:
    """Batch placement: token arrays [B, S] shard over (data, seq); label
    vectors [B] shard over data only.

    When the ``seq`` axis spans OS processes (spawn ``--mode sp``), each
    process holds the full [B, S] host batch (the data axis is then
    process-local — ``run.build_sp_trainer`` feeds accordingly) and
    ``make_array_from_callback`` hands every device exactly its sequence
    slice; ``make_array_from_process_local_data`` would instead interpret
    the full batch as this process's *shard* and mis-assemble."""
    from pdnlp_tpu.parallel.mesh import local_data_extent

    seq_spans_processes = (jax.process_count() > 1
                           and SEQ in mesh.shape
                           and local_data_extent(mesh, SEQ)[0] > 1)

    def put(batch: Dict) -> Dict[str, jax.Array]:
        out = {}
        for key, val in batch.items():
            sh = NamedSharding(mesh, sp_spec(key, val))
            if seq_spans_processes:
                out[key] = jax.make_array_from_callback(
                    val.shape, sh, lambda idx, v=val: v[idx])
            else:
                out[key] = jax.make_array_from_process_local_data(sh, val)
        return out

    return put


def make_sp_train_step(cfg: BertConfig, tx, args, mesh: Mesh):
    """Fused sequence-parallel train step (state replicated, batch sharded
    over (data, seq)); same Trainer contract as every other strategy."""
    from pdnlp_tpu.train.steps import _unroll

    dtype = resolve_dtype(args.dtype)
    remat = bool(args.remat)
    unroll = _unroll(args)
    smoothing = args.label_smoothing
    if getattr(args, "ema_decay", 0.0) > 0:
        raise ValueError("--ema_decay runs on the jit strategies (dp/zero/"
                         "tp/ep) — the sequence-parallel step does not "
                         "maintain the EMA tree")

    def local_loss(params, batch, rng):
        logits = bert.classify(params, cfg, batch, dtype=dtype,
                               deterministic=False, rng=rng, remat=remat,
                               seq_axis=SEQ, unroll=unroll)
        (loss, correct, objective), w = _flat_ce(
            logits, batch["label"], batch["example_weight"],
            smoothing=smoothing)
        # gate to seq-shard 0: head grads counted once; encoder grads flow
        # to every shard through the psum backward (see module docstring).
        # objective (smoothed) is differentiated; bare CE is reported.
        on0 = (jax.lax.axis_index(SEQ) == 0).astype(loss.dtype)
        return objective * on0, (loss * on0, correct * on0,
                                 w.sum() * on0)

    def per_device(state: State, batch) -> Tuple[State, Dict[str, jax.Array]]:
        rng = jax.random.fold_in(state["rng"], state["step"])
        rng = jax.random.fold_in(rng, jax.lax.axis_index(DATA))
        rng = jax.random.fold_in(rng, jax.lax.axis_index(SEQ))
        (_, (loss, correct, lw)), grads = jax.value_and_grad(
            local_loss, has_aux=True)(state["params"], batch, rng)
        # seq axis: plain sum (loss gated to one shard; each shard owns its
        # slice of encoder grads).  data axis: weight-mass average, exactly
        # as the explicit-collectives DP step.
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, SEQ), grads)
        loss = jax.lax.psum(loss, SEQ)
        correct = jax.lax.psum(correct, SEQ)
        lw = jax.lax.psum(lw, SEQ)
        # max(·, 1) guard matches steps.build_train_step: an all-filler
        # global batch must yield 0 loss/grads, not 0/0 NaN.
        gw = jnp.maximum(jax.lax.psum(lw, DATA), 1.0)
        scale = lw / gw
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g * scale, DATA), grads)
        loss = jax.lax.psum(loss * scale, DATA)
        acc = jax.lax.psum(correct, DATA) / gw
        updates, opt_state = tx.update(grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        new_state = {"params": params, "opt_state": opt_state,
                     "step": state["step"] + 1, "rng": state["rng"]}
        return new_state, {"loss": loss, "accuracy": acc}

    def specs_for(batch):
        return {k: sp_spec(k, v) for k, v in batch.items()}

    def compile_step(example_batch):
        mapped = shard_map(
            per_device, mesh=mesh,
            in_specs=(P(), specs_for(example_batch)),
            out_specs=(P(), P()),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=0)

    return compile_step


def make_sp_eval_step(cfg: BertConfig, args, mesh: Mesh):
    """Deterministic sequence-parallel eval step (same metric contract as
    ``train.steps.build_eval_step``)."""
    from pdnlp_tpu.train.steps import _unroll

    dtype = resolve_dtype(args.dtype)
    unroll = _unroll(args)

    def per_device(params, batch):
        logits = bert.classify(params, cfg, batch, dtype=dtype,
                               deterministic=True, seq_axis=SEQ,
                               unroll=unroll)
        (loss, correct, _), w = _flat_ce(logits, batch["label"],
                                         batch["example_weight"])
        wsum = w.sum()
        out = {
            "loss_sum": jax.lax.psum(loss * wsum, DATA),
            "weight": jax.lax.psum(wsum, DATA),
            "correct": jax.lax.psum(correct, DATA),
            "pred": jax.lax.all_gather(jnp.argmax(logits, -1), DATA, tiled=True),
            "label": jax.lax.all_gather(batch["label"], DATA, tiled=True),
            "ew": jax.lax.all_gather(w, DATA, tiled=True),
        }
        return out

    def specs_for(batch):
        return {k: sp_spec(k, v) for k, v in batch.items()}

    def compile_step(example_batch):
        mapped = shard_map(
            per_device, mesh=mesh,
            in_specs=(P(), specs_for(example_batch)),
            out_specs=P(),
            check_vma=False,
        )
        return jax.jit(mapped)

    return compile_step
