"""Multi-process runtime init — the rendezvous layer.

Twin of the reference's two rendezvous modes: env-var
``dist.init_process_group`` (``/root/reference/multi-gpu-distributed-cls.py:
275-284``) and explicit TCP (``multi-gpu-distributed-mp-cls.py:265-266``).
JAX collapses both into ``jax.distributed.initialize(coordinator, n, id)``;
afterwards every process sees the global device set and ``jit`` programs are
single-program-multiple-data across hosts (DCN for cross-host, ICI within).
"""
from __future__ import annotations

import os
from typing import Tuple


def init_runtime(args) -> Tuple[int, int]:
    """Initialize multi-process JAX if configured; returns
    ``(process_index, process_count)``.

    Config precedence: explicit ``Args`` fields, then the standard env vars
    (``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/``PROCESS_ID`` — the
    MASTER_ADDR/WORLD_SIZE/RANK analog), else single-process.

    Idempotent: entrypoints may call it early (e.g. to resolve a default
    mesh from the device count) and again inside the shared runner —
    ``jax.distributed.initialize`` itself raises on a second call.
    """
    import jax

    _honor_platform_env()
    coord = args.coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    nproc = args.num_processes or _int_env("NUM_PROCESSES")
    pid = args.process_id if args.process_id is not None else _int_env("PROCESS_ID")

    if coord and nproc and nproc > 1 and not _distributed_initialized():
        # NOTE: checked via the distributed client, not process_count() —
        # the latter would initialize the backend, which must not happen
        # before the distributed client is up
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(nproc),
            process_id=int(pid or 0),
        )
    return jax.process_index(), jax.process_count()


def _distributed_initialized() -> bool:
    """Is the distributed client up?  ``jax.distributed.is_initialized``
    where it exists; on older jax (this image's 0.4.37 has no such
    attribute — every spawn worker died on it and the whole elastic suite
    failed at init) probe the client object the same module keeps."""
    import jax

    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def _int_env(name: str):
    v = os.environ.get(name)
    return int(v) if v else None


def _honor_platform_env() -> None:
    """Re-apply ``JAX_PLATFORMS=cpu`` + the XLA virtual-device-count flag via
    ``jax.config``.  This image's sitecustomize force-registers the TPU
    plugin at interpreter start, which silently overrides the standard env
    vars — so CPU-mesh runs (CI, spawn-launcher workers) would land on the
    single TPU chip instead of N virtual devices.  No-op once the backend
    is initialized."""
    import re

    import jax

    if "cpu" not in os.environ.get("JAX_PLATFORMS", "").lower():
        return
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    try:
        m = re.search(r"xla_force_host_platform_device_count=(\d+)",
                      os.environ.get("XLA_FLAGS", ""))
        if m:
            jax.config.update("jax_num_cpu_devices", int(m.group(1)))
    except (RuntimeError, AttributeError):
        # jax < 0.5 has no jax_num_cpu_devices option; the XLA_FLAGS
        # host-platform override above already forces the virtual devices
        # (same guard as tests/conftest.py) — without this, every spawn
        # worker on such a jax died at init and the whole elastic suite
        # failed before a single gang ever launched
        pass
