"""Sharding rules: how state and batches are laid out over the mesh.

Two modes mirror the reference's two model-state strategies, and a third
goes beyond it:

- ``"dp"`` — replicated params/optimizer, batch split along ``data``: the
  DDP analog (``/root/reference/multi-gpu-distributed-cls.py:340-341``).
  XLA inserts the gradient all-reduce DDP does via NCCL hooks.
- ``"zero"`` — every weight *and* optimizer moment sharded along ``data``
  too: the ZeRO-3 analog (``/root/reference/multi-gpu-deepspeed-cls.py:
  232-239`` — ``allgather_partitions`` / ``reduce_scatter`` become XLA's
  all-gather-before-use / reduce-scatter-of-grads, chosen by the compiler
  from the same one-line sharding annotation).
- ``"tp"`` — Megatron-style tensor parallelism over a second ``model``
  mesh axis (no reference twin: ``SURVEY.md`` §2.3 notes the reference has
  no tensor parallelism).  Attention q/k/v and the MLP up-projection shard
  their *output* features (heads split across devices); the o/down
  projections shard their *input* features, so each device contracts its
  local features and XLA inserts the block all-reduce exactly where
  Megatron puts its NCCL call.  Composes with ``data``: grads all-reduce
  over ``data``, activations stay sharded over ``model`` inside a block.

The leaf rule for ``zero`` is shape-only — shard the largest dimension
divisible by the axis size — so it applies uniformly to params, Adam moments,
and anything else in the state pytree without a name registry.  ``tp`` is
necessarily name-aware (which feature dim shards is semantic, not a shape
property); its rule keys on the trailing dict path (``layers/<sub>/<leaf>``),
which the Adam moments share with the params they mirror.
"""
from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pdnlp_tpu.parallel.mesh import DATA_AXIS

MODEL_AXIS = "model"
EXPERT_AXIS = "expert"
MODES = ("dp", "zero", "tp", "ep")


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Leading (batch) dim split along the data axis."""
    return NamedSharding(mesh, P(axis))


def _zero_spec(shape, axis_size: int, axis: str) -> P:
    """Largest dim divisible by the axis size gets sharded; else replicate."""
    if not shape or axis_size <= 1:
        return P()
    order = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    for i in order:
        if shape[i] % axis_size == 0 and shape[i] >= axis_size:
            spec = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return P()


def _ep_spec(names, shape, axis: str) -> P:
    """Expert-parallel placement: MoE expert weights ``[L, E, in, out]``
    (and biases ``[L, E, out]``) split their expert dim; the gate and all
    attention weights replicate.  The gate-weighted combine contracts the
    expert dim, so XLA inserts the expert all-reduce there.  Rank-checked:
    a dense model's rank-3 ``up``/``down`` stacks replicate (only MoE
    models grow the expert dim)."""
    if len(names) >= 3 and names[-3] == "layers":
        sub, leaf = names[-2], names[-1]
        if sub in ("up", "down"):
            if leaf == "kernel" and len(shape) == 4:
                return P(None, axis, None, None)
            if leaf == "bias" and len(shape) == 3:
                return P(None, axis, None)
    return P()


def _tp_spec(names, axis: str) -> P:
    """Megatron placement by trailing dict path ``(..., 'layers', sub, leaf)``.

    Stacked layer weights are ``[L, in, out]`` (``models/bert.py``):
    q/k/v/up shard ``out`` (column-parallel — heads / mlp features split),
    o/down shard ``in`` (row-parallel — XLA all-reduces the partial
    contraction).  Everything else (LN, embeddings, pooler, classifier,
    biases of row-parallel layers) replicates."""
    if len(names) >= 3 and names[-3] == "layers":
        sub, leaf = names[-2], names[-1]
        if sub in ("q", "k", "v", "up"):
            return P(None, None, axis) if leaf == "kernel" else P(None, axis)
        if sub in ("o", "down") and leaf == "kernel":
            return P(None, axis, None)
    return P()


def state_shardings(state_shapes: Any, mesh: Mesh, mode: str = "dp",
                    axis: str = DATA_AXIS) -> Any:
    """Pytree of ``NamedSharding`` matching ``state_shapes`` (arrays or
    ``jax.eval_shape`` structs).  ``dp`` replicates everything; ``zero``
    shards every floating leaf by the shape rule; ``tp`` shards layer
    weights over the ``model`` axis by the Megatron name rule."""
    if mode not in MODES:
        raise ValueError(f"unknown sharding mode {mode!r}; use one of {MODES}")
    if mode == "tp" and MODEL_AXIS not in mesh.shape:
        raise ValueError(
            f"tp needs a {MODEL_AXIS!r} mesh axis; got {dict(mesh.shape)} — "
            'pass --mesh_shape \'{"data": D, "model": M}\'')
    if mode == "ep" and EXPERT_AXIS not in mesh.shape:
        raise ValueError(
            f"ep needs an {EXPERT_AXIS!r} mesh axis; got {dict(mesh.shape)} "
            '— pass --mesh_shape \'{"data": D, "expert": E}\'')
    if mode in ("tp", "ep") and axis not in mesh.shape:
        # the batch still feeds along the data axis; a pure {"model": M}
        # mesh would die later with a raw KeyError in the batch plumbing
        raise ValueError(
            f"{mode} also needs a {axis!r} mesh axis for the batch (size 1 "
            f"is fine); got {dict(mesh.shape)} — pass --mesh_shape "
            f'\'{{"{axis}": 1, "{MODEL_AXIS if mode == "tp" else EXPERT_AXIS}'
            f'": N}}\'')

    def _is_float(leaf) -> bool:
        import jax.numpy as jnp

        dtype = getattr(leaf, "dtype", None)
        try:
            return dtype is not None and jnp.issubdtype(dtype, jnp.floating)
        except TypeError:  # extended dtypes (PRNG keys)
            return False

    if mode in ("tp", "ep"):
        def name_rule(path, leaf):
            if not _is_float(leaf):
                return replicated(mesh)
            names = [k.key for k in path
                     if isinstance(k, jax.tree_util.DictKey)]
            spec = (_tp_spec(names, MODEL_AXIS) if mode == "tp"
                    else _ep_spec(names, leaf.shape, EXPERT_AXIS))
            return NamedSharding(mesh, spec)

        return jax.tree_util.tree_map_with_path(name_rule, state_shapes)

    size = mesh.shape[axis]  # zero's shard axis; dp/tp never read it

    def rule(leaf):
        if mode == "dp":
            return replicated(mesh)
        if not _is_float(leaf):
            # ints, PRNG keys, counters: tiny — replicate
            return replicated(mesh)
        return NamedSharding(mesh, _zero_spec(leaf.shape, size, axis))

    return jax.tree_util.tree_map(rule, state_shapes)


def with_memory_kind(sharding_tree: Any, kind: str,
                     shape_tree: Any = None) -> Any:
    """Same placement, different memory space — ``"pinned_host"`` moves a
    subtree (e.g. optimizer moments) to host RAM, the DeepSpeed
    ``offload_optimizer`` analog.  XLA stages host<->device copies around
    any compute that touches the leaves (see ``train.steps``).

    When ``shape_tree`` (eval_shape structs) is given, only FLOATING leaves
    move: the bytes are all in the fp32 moments anyway, and XLA's SPMD
    partitioner rejects host-placement annotations on replicated integer
    scalars (optax's step count) over a multi-device mesh."""
    if shape_tree is None:
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(s.mesh, s.spec, memory_kind=kind),
            sharding_tree)
    import jax.numpy as jnp

    def rule(s, shp):
        try:
            is_float = jnp.issubdtype(shp.dtype, jnp.floating)
        except TypeError:
            is_float = False
        if not is_float:
            return s
        return NamedSharding(s.mesh, s.spec, memory_kind=kind)

    return jax.tree_util.tree_map(rule, sharding_tree, shape_tree)


def shard_fraction(state, mesh) -> float:
    """Measured per-device fraction of total state bytes (tests/diagnostics:
    ~1/axis_size under ``zero``, 1.0 under ``dp``)."""
    total = on_device = 0
    ndev = mesh.size
    for leaf in jax.tree_util.tree_leaves(state):
        if isinstance(leaf, jax.Array):
            total += leaf.nbytes
            shard = leaf.addressable_shards[0] if leaf.addressable_shards else None
            if shard is not None:
                on_device += shard.data.nbytes
    return on_device / total if total else 1.0
