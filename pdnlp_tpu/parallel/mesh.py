"""Device-mesh construction — the process-group / communicator analog.

The reference's runtime layer is ``dist.init_process_group("nccl")`` plus an
implicit all-device communicator (``/root/reference/multi-gpu-distributed-cls.py:284``).
The TPU-native twin is a ``jax.sharding.Mesh``: a named, possibly
multi-dimensional arrangement of devices over which ``jit`` lays out arrays
and inserts ICI collectives.  One ``('data',)`` axis reproduces the
reference's pure data-parallel world; extra axes (``model``/``seq``) are how
the same machinery extends beyond it.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"

#: Every axis name any mesh in this repo may declare — the canonical
#: vocabulary: "data" (all strategies), "model" (tensor parallel,
#: sharding.MODEL_AXIS), "expert" (MoE expert parallel,
#: sharding.EXPERT_AXIS), "seq" (sequence parallel, sp.SEQ), "stage"
#: (pipeline parallel, pp.STAGE).  jaxlint rule R6 parses this tuple (by
#: AST, never importing) and flags any PartitionSpec axis string outside
#: it — a typo'd axis silently leaves an array unconstrained.  Add new
#: axes HERE first.
KNOWN_AXES = ("data", "model", "expert", "seq", "stage")


def make_mesh(
    num_devices: Optional[int] = None,
    shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over the local view of devices.

    ``shape`` maps axis name -> size (one ``-1`` entry = inferred), defaulting
    to a 1-D ``('data',)`` mesh over every visible device — the TPU twin of
    "one NCCL rank per GPU".  ``num_devices`` caps the device count (the
    ``--nproc_per_node`` analog, ``/root/reference/README.md:81-86``).
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(f"asked for {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    if not shape:
        shape = {DATA_AXIS: len(devices)}

    names = tuple(shape)
    dims = [int(shape[n]) for n in names]
    if dims.count(-1) > 1:
        raise ValueError(f"at most one inferred (-1) axis: {shape}")
    if -1 in dims:
        known = int(np.prod([d for d in dims if d != -1])) or 1
        if len(devices) % known:
            raise ValueError(f"{len(devices)} devices not divisible by {shape}")
        dims[dims.index(-1)] = len(devices) // known
    total = int(np.prod(dims)) if dims else 1
    if total > len(devices):
        raise ValueError(f"mesh {dict(zip(names, dims))} needs {total} devices, "
                         f"have {len(devices)}")

    try:
        # topology-aware layout (rides ICI neighbours on real TPU slices)
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(tuple(dims), devices=devices[:total])
    except Exception:
        dev_array = np.asarray(devices[:total]).reshape(dims)
    return Mesh(dev_array, names)


def local_data_extent(mesh: Mesh, axis: str = DATA_AXIS):
    """``(num_shards, shard_id, mult)`` for the data loader: which slice of
    the global batch THIS process's devices address along ``axis``.

    Generalizes ``local_batch_mult`` to meshes where the data axis may be
    replicated across processes (e.g. a stage-major ``{"stage": 2, "data":
    2}`` pipeline mesh: each process holds one stage of EVERY data shard, so
    every process must feed the full global batch).  ``mult`` scales the
    per-host batch; ``num_shards``/``shard_id`` select the host's slice of
    the seeded global permutation."""
    if axis not in mesh.shape:
        return 1, 0, 1
    axis_num = list(mesh.axis_names).index(axis)
    arr = np.asarray(mesh.devices)
    pid = jax.process_index()
    local = {idx[axis_num] for idx in np.ndindex(arr.shape)
             if arr[idx].process_index == pid}
    if not local:
        raise ValueError(f"process {pid} owns no devices of mesh "
                         f"{dict(mesh.shape)}")
    mult = len(local)
    lo, hi = min(local), max(local)
    if hi - lo + 1 != mult or mesh.shape[axis] % mult:
        raise ValueError(
            f"process {pid}'s data-axis indices {sorted(local)} are not a "
            f"contiguous even slice of the {axis} axis (size "
            f"{mesh.shape[axis]}) — reorder the mesh axes so each process's "
            "devices cover a contiguous data-axis block")
    return mesh.shape[axis] // mult, lo // mult, mult


def local_batch_mult(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    """How many data-axis shards this *process* feeds — scales the per-host
    batch so global batch = per-device batch x axis size (the step-count math
    of ``DistributedSampler``: 288 single / 144 at 2-way, ``SURVEY.md`` §6).
    Assumes the data axis divides evenly across processes, which holds for
    standard pod topologies (one process per host, hosts x chips = mesh)."""
    nproc = jax.process_count()
    if axis not in mesh.shape:
        raise ValueError(
            f"mesh {dict(mesh.shape)} has no {axis!r} axis — every strategy "
            f"feeds its batch along one; include it even at size 1, e.g. "
            f'--mesh_shape \'{{"{axis}": 1, ...}}\'')
    size = mesh.shape[axis]
    if size % nproc:
        raise ValueError(f"data axis {size} not divisible by {nproc} processes")
    return size // nproc
