"""Failure detection + elastic restart for multi-process gangs.

The reference has no failure handling at all (``SURVEY.md`` §5): a dead rank
leaves the others blocked in NCCL collectives forever.  The TPU-native
failure mode is identical — XLA collectives over a shared coordinator hang
when a peer dies — so detection must happen at the HOST level, outside the
device stream:

- **Heartbeat** (worker side): each process touches a per-rank file at a
  bounded rate from the training loop.  A wedged device queue, a deadlocked
  collective, or a killed process all stop the beats.
- **GangMonitor** (launcher side): polls child liveness and heartbeat
  freshness; classifies the gang as ``crashed`` (a child exited nonzero) or
  ``stalled`` (a heartbeat older than the timeout).
- **Elastic restart** (launcher side, ``multi-tpu-spawn-cls.py``): on
  failure the whole gang is killed and relaunched from the newest periodic
  resume snapshot (``Trainer`` saves full state — params, Adam moments,
  step, RNG — every ``--resume_every`` steps).  Because resume is *bitwise*
  (``tests/test_resume.py``) and the data order is a seeded permutation, the
  restarted run replays the lost steps exactly: a crash costs wall-clock,
  never training math.

Gang semantics (not per-rank restart): TPU meshes are SPMD — a lone
replacement rank cannot rejoin compiled collectives — so the restart unit is
the full gang, the same model cluster schedulers (GKE/Borg) use for TPU
slices.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional


def heartbeat_dir(output_dir: str) -> str:
    return os.path.join(output_dir, "heartbeats")


def heartbeat_file(output_dir: str, process_index: int) -> str:
    return os.path.join(heartbeat_dir(output_dir), f"proc{process_index}")


class Heartbeat:
    """Rate-limited liveness beacon written from the training loop.

    A beat may carry progress metadata — ``step`` (the rank's global step)
    and ``steps_per_sec`` — written into the beat file as JSON so the
    launcher-side monitor can tell a SLOW gang (beats arriving, counter
    advancing) from a DEAD one (beats stopped).  When the caller supplies
    only ``step``, the rate is derived from consecutive beats; the obs
    regression detector supplies its smoothed rate directly
    (``RegressionDetector.heartbeat_payload``).

    ``clock`` is injectable (tests drive a fake clock instead of
    sleeping); it must be the same clock the monitor reads, and defaults
    to ``time.time`` on both sides.
    """

    def __init__(self, output_dir: str, process_index: int,
                 interval: float = 5.0,
                 clock: Callable[[], float] = time.time):
        self.path = heartbeat_file(output_dir, process_index)
        self.interval = interval
        self._clock = clock
        self._last = 0.0
        self._prev: Optional[tuple] = None  # (beat time, step) for the rate
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        # deliberately NO beat here: the first beat lands after the first
        # completed step, so the monitor's pre-first-beat grace window (4x
        # stall_timeout) covers rendezvous + XLA compile — an early beat
        # would start the stall clock before compilation finishes

    def beat(self, force: bool = False, step: Optional[int] = None,
             steps_per_sec: Optional[float] = None) -> None:
        now = self._clock()
        if not (force or (now - self._last) >= self.interval):
            return
        self._last = now
        rate = steps_per_sec
        if rate is None and step is not None and self._prev is not None:
            dt = now - self._prev[0]
            ds = step - self._prev[1]
            if dt > 0 and ds >= 0:
                rate = ds / dt
        if step is not None:
            self._prev = (now, int(step))
        payload: Dict = {"t": now}
        if step is not None:
            payload["step"] = int(step)
        if rate is not None:
            payload["steps_per_sec"] = round(float(rate), 3)
        # write-then-rename: the monitor must never read a torn beat
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)


class GangMonitor:
    """Launcher-side failure detector over child processes + heartbeats."""

    def __init__(self, procs: List, output_dir: str, num_processes: int,
                 stall_timeout: float = 120.0,
                 clock: Callable[[], float] = time.time):
        self.procs = procs
        self.output_dir = output_dir
        self.num_processes = num_processes
        self.stall_timeout = stall_timeout
        self._clock = clock
        self.started = clock()

    def _read_beat(self, process_index: int) -> Optional[Dict]:
        """One rank's beat payload ``{"t": ..., "step"?, "steps_per_sec"?}``
        or None.  The beat TIMESTAMP comes from the payload the worker
        wrote (same injected clock domain as this monitor — and immune to
        the coarse-mtime granularity that made the stall test flaky);
        mtime is only the fallback for legacy plain-float files."""
        p = heartbeat_file(self.output_dir, process_index)
        try:
            with open(p) as f:
                text = f.read()
        except OSError:
            return None
        try:
            payload = json.loads(text)
            if not isinstance(payload, dict):
                payload = {"t": float(payload)}
        except (ValueError, TypeError):
            try:
                payload = {"t": float(text)}
            except ValueError:
                try:
                    payload = {"t": os.path.getmtime(p)}
                except OSError:
                    return None
        return payload if "t" in payload else None

    def _read_beats(self) -> List[Optional[Dict]]:
        """One payload (or None) per rank — read ONCE per poll/status, so
        age and progress never pay a second filesystem pass."""
        return [self._read_beat(i) for i in range(self.num_processes)]

    def _heartbeat_age(self, beats: Optional[List] = None) -> Optional[float]:
        """Age in seconds of the STALEST rank heartbeat (None before all
        ranks have beaten).  Beats older than this monitor's start are
        leftovers from a previous incarnation, not beats."""
        ages = []
        for beat in self._read_beats() if beats is None else beats:
            if beat is None:
                return None  # not all ranks beating yet — grace period
            if beat["t"] < self.started:
                return None
            ages.append(self._clock() - beat["t"])
        return max(ages) if ages else None

    @staticmethod
    def _progress(beats: List[Optional[Dict]]) -> Dict:
        """Gang progress metadata from the beat payloads: the SLOWEST
        rank's step (the gang advances at its laggard's pace) and rate."""
        steps = []
        rates = []
        for beat in beats:
            beat = beat or {}
            if "step" in beat:
                steps.append(int(beat["step"]))
            if "steps_per_sec" in beat:
                rates.append(float(beat["steps_per_sec"]))
        out: Dict = {}
        if steps:
            out["last_step"] = min(steps)
        if rates:
            out["steps_per_sec"] = round(min(rates), 3)
        return out

    def status(self) -> Dict:
        """Instantaneous health snapshot (no verdict): stalest beat age +
        progress metadata — what distinguishes *slow* (step advancing,
        rate depressed) from *dead* (beats stopped)."""
        beats = self._read_beats()
        age = self._heartbeat_age(beats)
        out = {"stalest_beat_s": round(age, 1) if age is not None else None}
        out.update(self._progress(beats))
        return out

    def status_line(self) -> str:
        s = self.status()
        parts = [f"stalest beat "
                 f"{s['stalest_beat_s']}s" if s["stalest_beat_s"] is not None
                 else "no beats yet"]
        if "last_step" in s:
            parts.append(f"step {s['last_step']}")
        if "steps_per_sec" in s:
            parts.append(f"{s['steps_per_sec']} steps/s")
        return "[gang] " + "  ".join(parts)

    def poll(self) -> Optional[Dict]:
        """None while healthy; otherwise a verdict dict:
        ``{"kind": "crashed"|"stalled", ...}``.  ``kind`` is None-equivalent
        ("done") when every child exited 0.  Stall verdicts carry the last
        known ``last_step``/``steps_per_sec`` so the launcher's log shows
        where progress stopped, not just that it did."""
        codes = [p.poll() for p in self.procs]
        if any(c is not None and c != 0 for c in codes):
            return {"kind": "crashed",
                    "codes": codes}
        if all(c == 0 for c in codes):
            return {"kind": "done", "codes": codes}
        beats = self._read_beats()
        age = self._heartbeat_age(beats)
        if age is not None and age > self.stall_timeout:
            return {"kind": "stalled", "stalest_beat_s": round(age, 1),
                    "codes": codes, **self._progress(beats)}
        # also treat "no rank ever beat within the timeout" (e.g. rendezvous
        # deadlock at startup) as a stall
        if age is None and (self._clock() - self.started) > 4 * self.stall_timeout:
            return {"kind": "stalled", "stalest_beat_s": None, "codes": codes,
                    **self._progress(beats)}
        return None

    def kill_gang(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in self.procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            if p.poll() is None:
                p.kill()
