"""Failure detection + elastic restart for multi-process gangs.

The reference has no failure handling at all (``SURVEY.md`` §5): a dead rank
leaves the others blocked in NCCL collectives forever.  The TPU-native
failure mode is identical — XLA collectives over a shared coordinator hang
when a peer dies — so detection must happen at the HOST level, outside the
device stream:

- **Heartbeat** (worker side): each process touches a per-rank file at a
  bounded rate from the training loop.  A wedged device queue, a deadlocked
  collective, or a killed process all stop the beats.
- **GangMonitor** (launcher side): polls child liveness and heartbeat
  freshness; classifies the gang as ``crashed`` (a child exited nonzero) or
  ``stalled`` (a heartbeat older than the timeout).
- **Elastic restart** (launcher side, ``multi-tpu-spawn-cls.py``): on
  failure the whole gang is killed and relaunched from the newest periodic
  resume snapshot (``Trainer`` saves full state — params, Adam moments,
  step, RNG — every ``--resume_every`` steps).  Because resume is *bitwise*
  (``tests/test_resume.py``) and the data order is a seeded permutation, the
  restarted run replays the lost steps exactly: a crash costs wall-clock,
  never training math.

Gang semantics (not per-rank restart): TPU meshes are SPMD — a lone
replacement rank cannot rejoin compiled collectives — so the restart unit is
the full gang, the same model cluster schedulers (GKE/Borg) use for TPU
slices.
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional


def heartbeat_dir(output_dir: str) -> str:
    return os.path.join(output_dir, "heartbeats")


def heartbeat_file(output_dir: str, process_index: int) -> str:
    return os.path.join(heartbeat_dir(output_dir), f"proc{process_index}")


class Heartbeat:
    """Rate-limited liveness beacon written from the training loop.

    A beat may carry progress metadata — ``step`` (the rank's global step)
    and ``steps_per_sec`` — written into the beat file as JSON so the
    launcher-side monitor can tell a SLOW gang (beats arriving, counter
    advancing) from a DEAD one (beats stopped).  When the caller supplies
    only ``step``, the rate is derived from consecutive beats; the obs
    regression detector supplies its smoothed rate directly
    (``RegressionDetector.heartbeat_payload``).

    ``clock`` is injectable (tests drive a fake clock instead of
    sleeping); it must be the same clock the monitor reads, and defaults
    to ``time.time`` on both sides.
    """

    def __init__(self, output_dir: str, process_index: int,
                 interval: float = 5.0,
                 clock: Callable[[], float] = time.time):
        self.path = heartbeat_file(output_dir, process_index)
        self.interval = interval
        self._clock = clock
        self._last = 0.0
        self._prev: Optional[tuple] = None  # (beat time, step) for the rate
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        # deliberately NO beat here: the first beat lands after the first
        # completed step, so the monitor's pre-first-beat grace window (4x
        # stall_timeout) covers rendezvous + XLA compile — an early beat
        # would start the stall clock before compilation finishes

    def beat(self, force: bool = False, step: Optional[int] = None,
             steps_per_sec: Optional[float] = None,
             hbm: Optional[int] = None,
             hbm_peak: Optional[int] = None) -> None:
        now = self._clock()
        if not (force or (now - self._last) >= self.interval):
            return
        self._last = now
        rate = steps_per_sec
        if rate is None and step is not None and self._prev is not None:
            dt = now - self._prev[0]
            ds = step - self._prev[1]
            if dt > 0 and ds >= 0:
                rate = ds / dt
        if step is not None:
            self._prev = (now, int(step))
        payload: Dict = {"t": now}
        # the tracer-clock anchor: (t, mono) read back-to-back lets
        # trace_tpu.py merge align this rank's perf_counter span domain
        # against other ranks' (pdnlp_tpu.obs.merge)
        payload["mono"] = time.perf_counter()
        if step is not None:
            payload["step"] = int(step)
        if rate is not None:
            payload["steps_per_sec"] = round(float(rate), 3)
        if hbm is not None:
            payload["hbm"] = int(hbm)
        if hbm_peak is not None:
            payload["hbm_peak"] = int(hbm_peak)
        # write-then-rename: the monitor must never read a torn beat
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)


class GangMonitor:
    """Launcher-side failure detector over child processes + heartbeats."""

    def __init__(self, procs: List, output_dir: str, num_processes: int,
                 stall_timeout: float = 120.0,
                 clock: Callable[[], float] = time.time):
        self.procs = procs
        self.output_dir = output_dir
        self.num_processes = num_processes
        self.stall_timeout = stall_timeout
        self._clock = clock
        self.started = clock()

    def _read_beat(self, process_index: int) -> Optional[Dict]:
        """One rank's beat payload ``{"t": ..., "step"?, "steps_per_sec"?}``
        or None.  The beat TIMESTAMP comes from the payload the worker
        wrote (same injected clock domain as this monitor — and immune to
        the coarse-mtime granularity that made the stall test flaky);
        mtime is only the fallback for legacy plain-float files."""
        p = heartbeat_file(self.output_dir, process_index)
        try:
            with open(p) as f:
                text = f.read()
        except OSError:
            return None
        try:
            payload = json.loads(text)
            if not isinstance(payload, dict):
                payload = {"t": float(payload)}
        except (ValueError, TypeError):
            try:
                payload = {"t": float(text)}
            except ValueError:
                try:
                    payload = {"t": os.path.getmtime(p)}
                except OSError:
                    return None
        return payload if "t" in payload else None

    def _read_beats(self) -> List[Optional[Dict]]:
        """One payload (or None) per rank — read ONCE per poll/status, so
        age and progress never pay a second filesystem pass."""
        return [self._read_beat(i) for i in range(self.num_processes)]

    def _rank_ages(self, beats: Optional[List] = None) -> List[Optional[float]]:
        """Per-rank beat age in seconds (None = that rank has not beaten in
        THIS incarnation — beats older than the monitor's start are
        leftovers from a previous gang, not beats)."""
        now = self._clock()
        ages: List[Optional[float]] = []
        for beat in self._read_beats() if beats is None else beats:
            if beat is None or beat["t"] < self.started:
                ages.append(None)
            else:
                ages.append(now - beat["t"])
        return ages

    def _heartbeat_age(self, beats: Optional[List] = None) -> Optional[float]:
        """Age in seconds of the STALEST rank heartbeat (None before all
        ranks have beaten)."""
        ages = self._rank_ages(beats)
        if any(a is None for a in ages):
            return None  # not all ranks beating yet — grace period
        return max(ages) if ages else None

    @staticmethod
    def _progress(beats: List[Optional[Dict]]) -> Dict:
        """Gang progress metadata from the beat payloads: the SLOWEST
        rank's step (the gang advances at its laggard's pace), its rate,
        and the HOTTEST rank's peak HBM (the budget binds at the fullest
        device, obs.memory rides the beats)."""
        steps = []
        rates = []
        hbm_peaks = []
        for beat in beats:
            beat = beat or {}
            if "step" in beat:
                steps.append(int(beat["step"]))
            if "steps_per_sec" in beat:
                rates.append(float(beat["steps_per_sec"]))
            if "hbm_peak" in beat:
                hbm_peaks.append(int(beat["hbm_peak"]))
        out: Dict = {}
        if steps:
            out["last_step"] = min(steps)
        if rates:
            out["steps_per_sec"] = round(min(rates), 3)
        if hbm_peaks:
            out["hbm_peak_gb"] = round(max(hbm_peaks) / 2**30, 3)
        return out

    def status(self) -> Dict:
        """Instantaneous health snapshot (no verdict): stalest beat age +
        progress metadata — what distinguishes *slow* (step advancing,
        rate depressed) from *dead* (beats stopped)."""
        beats = self._read_beats()
        age = self._heartbeat_age(beats)
        out = {"stalest_beat_s": round(age, 1) if age is not None else None}
        out.update(self._progress(beats))
        return out

    def status_line(self) -> str:
        s = self.status()
        parts = [f"stalest beat "
                 f"{s['stalest_beat_s']}s" if s["stalest_beat_s"] is not None
                 else "no beats yet"]
        if "last_step" in s:
            parts.append(f"step {s['last_step']}")
        if "steps_per_sec" in s:
            parts.append(f"{s['steps_per_sec']} steps/s")
        if "hbm_peak_gb" in s:
            parts.append(f"peak HBM {s['hbm_peak_gb']} GB")
        return "[gang] " + "  ".join(parts)

    def poll(self) -> Optional[Dict]:
        """None while healthy; otherwise a verdict dict:
        ``{"kind": "crashed"|"stalled", ...}``.  ``kind`` is None-equivalent
        ("done") when every child exited 0.  Stall verdicts carry the last
        known ``last_step``/``steps_per_sec`` so the launcher's log shows
        where progress stopped, not just that it did.

        Failure verdicts also carry ``dead_ranks`` — the ranks CLASSIFIED
        dead (a nonzero exit, or beats stopped past the timeout), never
        merely slow (a slow rank keeps beating, its ``steps_per_sec`` just
        drops) — the eviction policy's input: the supervisor shrinks the
        gang to the survivors instead of restarting at full width and dying
        again on the same bad host."""
        codes = [p.poll() for p in self.procs]
        if any(c is not None and c != 0 for c in codes):
            return {"kind": "crashed", "codes": codes,
                    "dead_ranks": [i for i, c in enumerate(codes)
                                   if c is not None and c != 0]}
        if all(c == 0 for c in codes):
            return {"kind": "done", "codes": codes}
        beats = self._read_beats()
        ages = self._rank_ages(beats)
        age = self._heartbeat_age(beats)
        if age is not None and age > self.stall_timeout:
            return {"kind": "stalled", "stalest_beat_s": round(age, 1),
                    "codes": codes,
                    "dead_ranks": [i for i, a in enumerate(ages)
                                   if a is not None and a > self.stall_timeout],
                    **self._progress(beats)}
        # also treat "no rank ever beat within the timeout" (e.g. rendezvous
        # deadlock at startup) as a stall; ranks that never produced a beat
        # count as dead alongside any whose beats went stale
        if age is None and (self._clock() - self.started) > 4 * self.stall_timeout:
            return {"kind": "stalled", "stalest_beat_s": None, "codes": codes,
                    "dead_ranks": [i for i, a in enumerate(ages)
                                   if a is None or a > self.stall_timeout],
                    **self._progress(beats)}
        return None

    def kill_gang(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in self.procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            if p.poll() is None:
                p.kill()


class GangSupervisor:
    """Degrade-don't-die gang supervision: relaunch, evict, back off.

    The launcher-side policy loop over :class:`GangMonitor` verdicts —
    extracted from the spawn entrypoint so the eviction/backoff/budget
    logic is unit-testable with fake processes and an injected clock:

    - **restart** the whole gang from the newest snapshot on any failure
      (SPMD collectives cannot absorb a lone replacement rank);
    - **evict** when the verdict names dead ranks (crashed, or beats
      stopped — never merely slow): the next incarnation launches at the
      surviving width and the workers' elastic-width resume remaps the
      data position (``Trainer._remap_elastic_width``).  A verdict that
      condemns the ENTIRE gang (startup rendezvous wedge, whole-gang
      stall) restarts at full width — there is no survivor set to degrade
      to, and the cause is usually transient;
    - **capped exponential backoff** between restarts (a flapping host
      must not hot-loop the launcher into the coordinator);
    - **restart budget**: after ``max_restarts`` failures the supervisor
      gives up with the final verdict on stderr.

    ``launch(width)`` must return the new gang's process list; ``sleep``/
    ``clock`` are injectable for tests.
    """

    def __init__(self, launch, output_dir: str, num_processes: int, *,
                 stall_timeout: float = 300.0, max_restarts: int = 2,
                 shrink: bool = True, min_processes: int = 1,
                 backoff: float = 1.0, backoff_cap: float = 30.0,
                 poll_interval: float = 0.2,
                 clock: Callable[[], float] = time.time,
                 sleep: Callable[[float], None] = time.sleep,
                 log: Optional[Callable[[str], None]] = None):
        self.launch = launch
        self.output_dir = output_dir
        self.num_processes = int(num_processes)
        self.stall_timeout = stall_timeout
        self.max_restarts = int(max_restarts)
        self.shrink = bool(shrink)
        self.min_processes = max(1, int(min_processes))
        self.backoff = float(backoff)
        self.backoff_cap = float(backoff_cap)
        self.poll_interval = poll_interval
        self._clock = clock
        self._sleep = sleep
        self._log = log if log is not None else (
            lambda msg: print(msg, file=sys.stderr))
        self.restarts = 0
        self.width = self.num_processes
        self.widths_launched: List[int] = []

    def run(self) -> int:
        while True:
            self.widths_launched.append(self.width)
            procs = self.launch(self.width)
            mon = GangMonitor(procs, self.output_dir, self.width,
                              stall_timeout=self.stall_timeout,
                              clock=self._clock)
            verdict = None
            while verdict is None:
                self._sleep(self.poll_interval)
                verdict = mon.poll()
            if verdict["kind"] == "done":
                return 0
            mon.kill_gang()
            if self.restarts >= self.max_restarts:
                self._log(f"[elastic] giving up after {self.restarts} "
                          f"restarts: {verdict}")
                return 1
            self.restarts += 1
            dead = verdict.get("dead_ranks") or []
            if self.shrink and dead and len(dead) < self.width:
                new_width = max(self.min_processes, self.width - len(dead))
                if new_width != self.width:
                    self._log(f"[elastic] evicting dead rank(s) {dead} — "
                              f"resuming at width {new_width} (was "
                              f"{self.width})")
                    self.width = new_width
            delay = min(self.backoff_cap,
                        self.backoff * (2 ** (self.restarts - 1)))
            self._log(f"[elastic] gang failure {verdict} — restart "
                      f"{self.restarts}/{self.max_restarts} at width "
                      f"{self.width} from latest snapshot (backoff "
                      f"{delay:.1f}s)")
            if delay > 0:
                self._sleep(delay)
