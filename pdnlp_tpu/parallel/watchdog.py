"""Failure detection + elastic restart for multi-process gangs.

The reference has no failure handling at all (``SURVEY.md`` §5): a dead rank
leaves the others blocked in NCCL collectives forever.  The TPU-native
failure mode is identical — XLA collectives over a shared coordinator hang
when a peer dies — so detection must happen at the HOST level, outside the
device stream:

- **Heartbeat** (worker side): each process touches a per-rank file at a
  bounded rate from the training loop.  A wedged device queue, a deadlocked
  collective, or a killed process all stop the beats.
- **GangMonitor** (launcher side): polls child liveness and heartbeat
  freshness; classifies the gang as ``crashed`` (a child exited nonzero) or
  ``stalled`` (a heartbeat older than the timeout).
- **Elastic restart** (launcher side, ``multi-tpu-spawn-cls.py``): on
  failure the whole gang is killed and relaunched from the newest periodic
  resume snapshot (``Trainer`` saves full state — params, Adam moments,
  step, RNG — every ``--resume_every`` steps).  Because resume is *bitwise*
  (``tests/test_resume.py``) and the data order is a seeded permutation, the
  restarted run replays the lost steps exactly: a crash costs wall-clock,
  never training math.

Gang semantics (not per-rank restart): TPU meshes are SPMD — a lone
replacement rank cannot rejoin compiled collectives — so the restart unit is
the full gang, the same model cluster schedulers (GKE/Borg) use for TPU
slices.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional


def heartbeat_dir(output_dir: str) -> str:
    return os.path.join(output_dir, "heartbeats")


def heartbeat_file(output_dir: str, process_index: int) -> str:
    return os.path.join(heartbeat_dir(output_dir), f"proc{process_index}")


class Heartbeat:
    """Rate-limited liveness beacon written from the training loop."""

    def __init__(self, output_dir: str, process_index: int,
                 interval: float = 5.0):
        self.path = heartbeat_file(output_dir, process_index)
        self.interval = interval
        self._last = 0.0
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        # deliberately NO beat here: the first beat lands after the first
        # completed step, so the monitor's pre-first-beat grace window (4x
        # stall_timeout) covers rendezvous + XLA compile — an early beat
        # would start the stall clock before compilation finishes

    def beat(self, force: bool = False) -> None:
        now = time.time()
        if force or (now - self._last) >= self.interval:
            self._last = now
            with open(self.path, "w") as f:
                f.write(str(now))


class GangMonitor:
    """Launcher-side failure detector over child processes + heartbeats."""

    def __init__(self, procs: List, output_dir: str, num_processes: int,
                 stall_timeout: float = 120.0):
        self.procs = procs
        self.output_dir = output_dir
        self.num_processes = num_processes
        self.stall_timeout = stall_timeout
        self.started = time.time()

    def _heartbeat_age(self) -> Optional[float]:
        """Age in seconds of the STALEST rank heartbeat (None before all
        ranks have beaten).  Files older than this monitor's start are
        leftovers from a previous incarnation, not beats."""
        ages = []
        for i in range(self.num_processes):
            p = heartbeat_file(self.output_dir, i)
            try:
                mtime = os.path.getmtime(p)
            except OSError:
                return None  # not all ranks beating yet — grace period
            if mtime < self.started:
                return None
            ages.append(time.time() - mtime)
        return max(ages) if ages else None

    def poll(self) -> Optional[Dict]:
        """None while healthy; otherwise a verdict dict:
        ``{"kind": "crashed"|"stalled", ...}``.  ``kind`` is None-equivalent
        ("done") when every child exited 0."""
        codes = [p.poll() for p in self.procs]
        if any(c is not None and c != 0 for c in codes):
            return {"kind": "crashed",
                    "codes": codes}
        if all(c == 0 for c in codes):
            return {"kind": "done", "codes": codes}
        age = self._heartbeat_age()
        if age is not None and age > self.stall_timeout:
            return {"kind": "stalled", "stalest_beat_s": round(age, 1),
                    "codes": codes}
        # also treat "no rank ever beat within the timeout" (e.g. rendezvous
        # deadlock at startup) as a stall
        if age is None and (time.time() - self.started) > 4 * self.stall_timeout:
            return {"kind": "stalled", "stalest_beat_s": None, "codes": codes}
        return None

    def kill_gang(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in self.procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            if p.poll() is None:
                p.kill()
