"""Shared experiment assembly — what the reference copy-pastes 9×, built once.

Every reference script repeats the same ~60 lines: seed, load/split data,
tokenizer, loaders, model, optimizer (e.g. ``/root/reference/single-gpu-cls.py:
207-255``).  Entry scripts here call these two functions and stay thin; the
*strategy* (placement/sharding/launcher) is the only thing they add.
"""
from __future__ import annotations

from typing import Tuple

import jax

from pdnlp_tpu.data import Collator, DataLoader, WordPieceTokenizer, load_data, split_data
from pdnlp_tpu.data.sampler import DistributedShardSampler
from pdnlp_tpu.data.tokenizer import get_or_build_vocab
from pdnlp_tpu.models import bert, get_config
from pdnlp_tpu.models.config import args_overrides
from pdnlp_tpu.train.optim import build_optimizer
from pdnlp_tpu.utils.seeding import set_seed


def setup_data(args, *, num_shards: int = 1, shard_id: int = 0,
               device_batch_mult: int = 1,
               train_override=None) -> Tuple[DataLoader, DataLoader, WordPieceTokenizer]:
    """(train_loader, dev_loader, tokenizer).

    ``device_batch_mult`` scales the per-host batch for single-controller
    data parallelism (global batch = per-device 32 × #devices, so step count
    matches the reference's ``DistributedSampler`` math: 288 single / 144 at
    2-way).  ``num_shards``/``shard_id`` split the *dataset* across host
    processes for the multi-process launcher variants.  ``train_override``
    replaces the train split's examples (the supervised-pretrain stage trains
    on the labeled externals while keeping the standard dev split).
    """
    data = load_data(args.data_path)
    train, dev = split_data(data, seed=args.seed, limit=args.data_limit, ratio=args.ratio)
    if train_override is not None:
        train = list(train_override)
    tok = WordPieceTokenizer(get_or_build_vocab(args))
    from pdnlp_tpu.data import native

    native.attach(tok)  # no-op unless `make -C csrc` has been run
    col = Collator(tok, args.max_seq_len)
    from pdnlp_tpu.data.collate import EncodedDataset

    # one-time encode of each split: epochs re-index cached arrays instead
    # of re-tokenizing (identical bytes either way — Collator stays the
    # reference-semantics spec and the parity test pins them equal)
    train_enc = EncodedDataset(train, tok, args.max_seq_len)
    dev_enc = EncodedDataset(dev, tok, args.max_seq_len)
    train_loader = build_length_train_loader(
        args, train, col, train_enc,
        batch_size=args.train_batch_size * device_batch_mult,
        num_shards=num_shards, shard_id=shard_id)
    dev_loader = DataLoader(
        dev, col, args.dev_batch_size * device_batch_mult,
        sampler=DistributedShardSampler(len(dev), num_shards, shard_id, shuffle=False),
        prefetch=args.prefetch, encoded=dev_enc,
    )
    return train_loader, dev_loader, tok


def build_length_train_loader(args, train, col, train_enc, *, batch_size,
                              num_shards: int = 1, shard_id: int = 0):
    """The train ``DataLoader`` under ``--length_mode`` — ONE place, shared
    by ``setup_data`` and ``bench.py --length``, so the mode wiring cannot
    drift between the entrypoints and the smoke that measures them.

    - ``full``: the reference path — seeded shard sampler, every batch
      padded to ``max_seq_len``.
    - ``bucket``: seeded length-grouped sampler; each batch pads to the
      smallest bucket covering its longest example, batches stay
      bucket-homogeneous (and ``fuse_steps`` groups shape-homogeneous).
    - ``pack``: the split is packed once into multi-example rows
      (``data.packing``); epochs shuffle packed rows through the ordinary
      shard sampler — one static shape, ~1/segments-per-row the steps.
      When ``--length_buckets`` names SEVERAL kernel-tiling widths
      (multiples of 128) whose largest covers the encode width, packing
      goes multi-width (``MultiWidthPackedDataset``): each example packs
      at its smallest covering width, per-width segment caps
      (``data.packing.segment_cap``), and the length-grouped sampler
      batches width-homogeneous packed rows — the long-document layout
      the segment-native flash kernel serves at 512-2048.

    Both bucket and pack validate the bucket widths against the model's
    position-table size at setup (``validate_length_buckets``) — an
    out-of-table width would silently gather garbage embeddings (JAX
    clamps the gather), so it is a loud setup error instead.

    Eval loaders stay unpacked/full-width in every mode: eval semantics
    (and the dev-accuracy definition) never change with the training
    layout.
    """
    from pdnlp_tpu.data.packing import (
        MultiWidthPackedDataset, pack_classification,
    )
    from pdnlp_tpu.data.sampler import (
        LengthGroupedSampler, parse_buckets, resolve_length_mode,
        validate_length_buckets,
    )
    from pdnlp_tpu.models import get_config

    mode = resolve_length_mode(args)
    if mode in ("bucket", "pack"):
        widths = parse_buckets(args.length_buckets, args.max_seq_len)
        validate_length_buckets(
            widths, max_position=get_config(args.model).max_position,
            model=args.model, mode=mode, max_seq_len=args.max_seq_len)
    if mode == "bucket":
        sampler = LengthGroupedSampler(
            train_enc.lengths(), batch_size=batch_size,
            buckets=widths,
            num_shards=num_shards, shard_id=shard_id, shuffle=True,
            seed=args.seed)
        return DataLoader(train, col, batch_size, sampler=sampler,
                          prefetch=args.prefetch, encoded=train_enc)
    if mode == "pack":
        cap = getattr(args, "pack_max_segments", 16)
        # multi-width needs >1 kernel-tiling width AND coverage of the
        # encode width; otherwise the legacy single-width pack (one
        # static shape at max_seq_len, resident-pipeline-eligible) stands
        tiling = tuple(w for w in widths if w >= 128 and w % 128 == 0)
        if len(tiling) > 1 and tiling[-1] >= args.max_seq_len:
            packed = MultiWidthPackedDataset(train_enc, tiling,
                                             max_segments=cap)
            sampler = LengthGroupedSampler(
                packed.row_width_table(), batch_size=batch_size,
                buckets=tiling, num_shards=num_shards, shard_id=shard_id,
                shuffle=True, seed=args.seed)
            return DataLoader(train, col, batch_size, sampler=sampler,
                              prefetch=args.prefetch, encoded=packed)
        packed = pack_classification(train_enc, max_segments=cap)
        return DataLoader(
            train, col, batch_size,
            sampler=DistributedShardSampler(len(packed), num_shards,
                                            shard_id, shuffle=True,
                                            seed=args.seed),
            prefetch=args.prefetch, encoded=packed)
    return DataLoader(
        train, col, batch_size,
        sampler=DistributedShardSampler(len(train), num_shards, shard_id,
                                        shuffle=True, seed=args.seed),
        prefetch=args.prefetch, encoded=train_enc)


def setup_pipeline(args, loader, put=None, put_fused=None, mesh=None,
                   allow_resident: bool = True):
    """The input pipeline for a wired loader (``data.pipeline``): resident
    (split held in HBM, zero steady-state transport) / double-buffered
    prefetch / sync behind ``--pipeline``; shared by the strategy runners
    and the single-device entrypoint so the mode decision can't drift.

    Configures the obs tracer from ``--trace`` FIRST: the resident
    pipeline's one-time residency upload happens inside ``build_pipeline``
    and must land in the trace, not precede it."""
    from pdnlp_tpu.data.pipeline import build_pipeline
    from pdnlp_tpu.obs.trace import configure_from_args

    configure_from_args(args)
    return build_pipeline(args, loader, put=put, put_fused=put_fused,
                          mesh=mesh, allow_resident=allow_resident)


def setup_model(args, vocab_size: int, total_steps: int = None):
    """(cfg, tx, state) — seeded the reference's way (one seed, 123).
    ``total_steps`` sizes the optional ``--lr_schedule``."""
    from pdnlp_tpu.train.optim import make_schedule
    from pdnlp_tpu.train.steps import init_state
    from pdnlp_tpu.utils.seeding import train_key

    if getattr(args, "offload_opt_state", False):
        raise ValueError("--offload_opt_state is wired into the mesh "
                         "strategies (dp/zero via build_parallel_trainer), "
                         "not this entrypoint — it would be silently ignored "
                         "here")
    cfg = get_config(args.model, vocab_size=vocab_size, num_labels=args.num_labels,
                     dropout=args.dropout, attn_dropout=args.attn_dropout,
                     **args_overrides(args))
    root = set_seed(args.seed)
    init_key, _ = jax.random.split(root)
    train_rng = train_key(args.seed, getattr(args, "rng_impl", "rbg"))
    params = bert.init_params(init_key, cfg)
    if getattr(args, "init_from", None):
        from pdnlp_tpu.train.pretrain import load_encoder

        params = load_encoder(args.init_from, params,
                              head=getattr(args, "init_head", False))
    tx = build_optimizer(params, args,
                         schedule=make_schedule(args, total_steps))
    state = init_state(init_key, cfg, tx, rng=train_rng, params=params,
                       ema=getattr(args, "ema_decay", 0.0) > 0)
    return cfg, tx, state
