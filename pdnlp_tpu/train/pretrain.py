"""In-repo MLM pretraining — the TPU-native twin of "load pretrained weights".

The reference's whole benchmark fine-tunes *pretrained*
``hfl/chinese-bert-wwm-ext`` (``/root/reference/single-gpu-cls.py:252-255``)
and owes its ~0.57 dev accuracy to those weights; this environment has no
egress and no checkpoint, so the capability is rebuilt as a pretraining
*stage*: masked-LM over the full 40,133-text corpus (the fine-tune split
only ever uses the first 10,000 — ``single-gpu-cls.py:226`` — so the rest
is free pretraining data), then fine-tune from the saved encoder.

TPU-native choices:
- **packing** (``data.packing``): ~7 texts per 128-token row behind a
  block-diagonal segment mask — ~7x the tokens/FLOP of padded rows;
- **masking on device**: the 80/10/10 BERT corruption runs inside the
  jitted step (threefry, static shapes), re-sampled every step for free
  dynamic masking — no host-side mask materialization;
- **mesh DP**: batch sharded along ``data``, state replicated; the same
  placement story as the fine-tune strategies.

Held-out hygiene: the fine-tune DEV split's texts are excluded from the
pretraining stream (the reference's downloaded weights never saw them
either).
"""
from __future__ import annotations

import time
from typing import Dict, Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pdnlp_tpu.data.corpus import load_data, split_data
from pdnlp_tpu.data.packing import pack_texts
from pdnlp_tpu.data.tokenizer import WordPieceTokenizer, get_or_build_vocab
from pdnlp_tpu.models import bert, get_config
from pdnlp_tpu.models.config import args_overrides
from pdnlp_tpu.parallel import make_global_batch, make_mesh
from pdnlp_tpu.parallel.sharding import batch_sharding, replicated
from pdnlp_tpu.train import checkpoint as ckpt
from pdnlp_tpu.train.async_ckpt import AsyncCheckpointer
from pdnlp_tpu.train.optim import build_optimizer
from pdnlp_tpu.train.precision import resolve_dtype
from pdnlp_tpu.utils.logging import rank0_print
from pdnlp_tpu.utils.seeding import set_seed

N_SPECIALS = 5  # [PAD],[UNK],[CLS],[SEP],[MASK] — ids 0..4, never masked


def mask_tokens(rng: jax.Array, input_ids: jax.Array, mask_id: int,
                vocab_size: int, mlm_prob: float = 0.15,
                span: bool = False) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """BERT's 80/10/10 corruption, traced on device.

    Returns ``(corrupted_ids, labels, weights)``: labels are the original
    ids, weights select the masked positions (0 elsewhere).  Only real
    tokens (id >= N_SPECIALS) are candidates, so [CLS]/[SEP]/[PAD] and
    packing filler never train the head.

    ``span=True`` selects contiguous n-grams (40/30/20/10% of length
    1/2/3/4, expected 2) instead of i.i.d. positions — the segmenter-free
    analog of the reference model's Chinese whole-word masking
    (``hfl/chinese-bert-wwm-ext``): most Chinese words are 2-4 chars, so
    masking the whole span stops the model answering from the other half
    of the word.  Spans truncate at specials, so they never cross packed
    text boundaries.
    """
    k_sel, k_split, k_rand = jax.random.split(rng, 3)
    maskable = input_ids >= N_SPECIALS
    if span:
        k_sel, k_len = jax.random.split(k_sel)
        # start-rate = target / E[len]: i.i.d. starts, then extend rightward
        starts = jax.random.uniform(k_sel, input_ids.shape) < (mlm_prob / 2.0)
        lens = jax.random.choice(k_len, jnp.arange(1, 5), input_ids.shape,
                                 p=jnp.array([0.4, 0.3, 0.2, 0.1]))
        # r_i = remaining span length extending from position i.  Propagate
        # rightward (max with any new start), zeroing at non-maskable
        # positions so a span DIES at [SEP]/[PAD] instead of resuming in the
        # next packed text; 3 steps converge (spans are <= 4 long).
        init = jnp.where(starts & maskable, lens, 0)
        r = init
        for _ in range(3):
            cont = jnp.zeros_like(r).at[..., 1:].set(r[..., :-1] - 1)
            r = jnp.maximum(init, jnp.where(maskable, cont, 0))
        selected = r > 0
    else:
        selected = (jax.random.uniform(k_sel, input_ids.shape) < mlm_prob) & maskable
    u = jax.random.uniform(k_split, input_ids.shape)
    random_ids = jax.random.randint(
        k_rand, input_ids.shape, N_SPECIALS, vocab_size, dtype=input_ids.dtype)
    corrupted = jnp.where(u < 0.8, mask_id,
                          jnp.where(u < 0.9, random_ids, input_ids))
    corrupted = jnp.where(selected, corrupted, input_ids)
    return corrupted, input_ids, selected.astype(jnp.float32)


def build_mlm_step(cfg, tx, args, mask_id: int):
    """Fused MLM train step: corrupt -> encode(packed) -> tied head -> CE ->
    AdamW.  ``state['params']`` carries the encoder tree plus an ``'mlm'``
    subtree (head), stripped again at fine-tune load time."""
    from pdnlp_tpu.train.steps import _unroll

    dtype = resolve_dtype(args.dtype)
    remat = bool(args.remat)
    unroll = _unroll(args)

    def loss_fn(params, batch, rng):
        k_mask, k_drop = jax.random.split(rng)
        ids, labels, w = mask_tokens(k_mask, batch["input_ids"], mask_id,
                                     cfg.vocab_size, args.mlm_prob,
                                     span=args.mlm_span)
        seg = batch["segment_ids"]
        hidden, aux = bert.encode(
            params, cfg, ids, jnp.zeros_like(ids), (seg > 0).astype(jnp.int32),
            dtype=dtype, deterministic=False, rng=k_drop, remat=remat,
            attn_impl=args.attention_impl, segment_ids=seg, unroll=unroll,
            with_aux=True,
        )
        logits = bert.mlm_logits(params, params["mlm"], cfg, hidden, dtype=dtype)
        logp = jax.nn.log_softmax(logits)
        ce = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        wsum = jnp.maximum(w.sum(), 1.0)
        loss = (ce * w).sum() / wsum
        correct = ((jnp.argmax(logits, -1) == labels) * w).sum()
        # aux (MoE load balancing; 0 for dense) joins the optimized
        # objective only — the logged loss stays bare CE
        return loss + cfg.moe_aux_coef * aux, (loss, correct, wsum)

    def train_step(state, batch):
        rng = jax.random.fold_in(state["rng"], state["step"])
        (_, (loss, correct, wsum)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"], batch, rng)
        updates, opt_state = tx.update(grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        new_state = {"params": params, "opt_state": opt_state,
                     "step": state["step"] + 1, "rng": state["rng"]}
        return new_state, {"loss": loss, "mask_acc": correct / wsum}

    return train_step


class PackedLoader:
    """Epoch-shuffled batches over pre-packed rows (all-numpy, no re-pack)."""

    def __init__(self, packed: Dict[str, np.ndarray], batch_size: int,
                 seed: int = 123):
        self.packed = packed
        self.batch_size = batch_size
        self.seed = seed
        self.epoch = 0
        self.n = len(packed["input_ids"])

    def __len__(self) -> int:
        return self.n // self.batch_size  # drop_last: static shapes for free

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        order = np.random.RandomState(self.seed + self.epoch).permutation(self.n)
        for i in range(0, len(self) * self.batch_size, self.batch_size):
            idx = order[i : i + self.batch_size]
            yield {k: v[idx] for k, v in self.packed.items()}


def build_pretrain_corpus(args, tok: WordPieceTokenizer) -> Dict[str, np.ndarray]:
    """Pack every corpus text EXCEPT the fine-tune dev split's."""
    data = load_data(args.data_path)
    _, dev = split_data(data, seed=args.seed, limit=args.data_limit,
                        ratio=args.ratio)
    held_out = {t for t, _ in dev}
    texts = [t for t, _ in data if t not in held_out]
    if args.pretrain_limit:
        texts = texts[: args.pretrain_limit]
    packed = pack_texts(tok, texts, args.max_seq_len)
    rank0_print(f"pretrain corpus: {len(texts)} texts "
                f"({len(data) - len(texts)} dev-held-out) -> "
                f"{len(packed['input_ids'])} packed rows of {args.max_seq_len}")
    return packed


def build_supervised_corpus(args):
    """Labeled examples OUTSIDE the fine-tune slice.

    The reference's protocol only ever touches ``data[:10000]``
    (``/root/reference/single-gpu-cls.py:226``); the remaining 30,133
    ``(text, label)`` pairs are unused in-repo supervision.  Texts that also
    appear verbatim in the fine-tune DEV split are dropped (49 duplicates in
    the shipped corpus) so the stage never sees a dev label."""
    data = load_data(args.data_path)
    _, dev = split_data(data, seed=args.seed, limit=args.data_limit,
                        ratio=args.ratio)
    held_out = {t for t, _ in dev}
    ext = [(t, l) for t, l in data[args.data_limit:] if t not in held_out]
    if args.pretrain_limit:
        ext = ext[: args.pretrain_limit]
    return ext


def run_supervised_stage(args) -> str:
    """Phase 2 of in-repo pretraining: supervised classification over the
    held-out labeled externals (``build_supervised_corpus``), warm-started
    from the MLM checkpoint (``args.init_from``).

    This is the in-repo twin of intermediate-task transfer: where the
    reference's accuracy comes from 5.4B externally-pretrained tokens, this
    stage mines the label signal the benchmark protocol leaves on the floor.
    The dev split is untouched (and its duplicate texts excluded), so the
    resulting dev accuracy is an honest held-out number.

    Writes FULL params (encoder + pooler + classifier) to
    ``args.ckpt_path()``; fine-tune entrypoints restore the trunk by default
    and the trained head too under ``--init_head true``.  Returns the path.
    """
    from pdnlp_tpu.train.run import build_parallel_trainer

    if args.dev:
        raise ValueError(
            "run_supervised_stage trains with dev=False: selecting a "
            "pretrain artifact on the fine-tune dev split would leak the "
            "benchmark's model-selection signal into pretraining (and "
            "Trainer.train would only write the checkpoint on an eval "
            "improvement). Evaluate after fine-tuning instead.")
    ext = build_supervised_corpus(args)
    trainer, loader, _ = build_parallel_trainer(
        args, mode="dp", train_override=ext)
    rank0_print(f"supervised stage: {len(ext)} labeled externals, "
                f"{args.epochs} epochs x {len(loader)} steps, "
                f"lr {args.learning_rate}")
    trainer.train(loader, None)
    return args.ckpt_path()


def run_pretrain(args) -> str:
    """Pretrain and write the encoder checkpoint; returns its path.

    The saved tree is the pretrain *params* (encoder + ``mlm`` head);
    ``load_encoder`` keeps the encoder and drops the head.  This is a
    weights artifact, not a resume point — optimizer moments and the
    schedule step are not saved (use ``Trainer.save_resume`` semantics if
    interruptible multi-hour pretrains ever matter; this corpus pretrains
    in minutes).
    """
    set_seed(args.seed)
    mesh = make_mesh(num_devices=args.num_devices, shape=args.mesh_shape)
    tok = WordPieceTokenizer(get_or_build_vocab(args))
    packed = build_pretrain_corpus(args, tok)
    loader = PackedLoader(packed, args.train_batch_size, seed=args.seed)

    cfg = get_config(args.model, vocab_size=tok.vocab_size,
                     num_labels=args.num_labels, dropout=args.dropout,
                     attn_dropout=args.attn_dropout,
                     **args_overrides(args))
    root = jax.random.PRNGKey(args.seed)
    # 3-way split kept although slot 3 is unused (the dropout stream now
    # comes from train_key): changing the split would change k_init/k_head
    # and silently invalidate every existing pretrained.msgpack recipe.
    k_init, k_head, _ = jax.random.split(root, 3)
    params = bert.init_params(k_init, cfg)
    params["mlm"] = bert.init_mlm_head(k_head, cfg)
    # From-scratch MLM needs a warmup->decay schedule (fine-tuning doesn't:
    # the reference uses constant 3e-5 on a pretrained trunk, which
    # build_optimizer mirrors).  BERT-style: linear warmup over the first
    # ~6%, cosine decay to zero.
    total_steps = max(1, len(loader) * args.epochs)
    tx = build_optimizer(params, args, schedule=optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=args.learning_rate,
        warmup_steps=max(1, total_steps * 6 // 100),
        decay_steps=total_steps))
    from pdnlp_tpu.utils.seeding import train_key

    state = {"params": params, "opt_state": tx.init(params),
             "step": jnp.zeros((), jnp.int32),
             "rng": train_key(args.seed, getattr(args, "rng_impl", "rbg"))}

    step_fn = jax.jit(
        build_mlm_step(cfg, tx, args, mask_id=tok.vocab["[MASK]"]),
        donate_argnums=0,
        in_shardings=(replicated(mesh),
                      {k: batch_sharding(mesh) for k in packed}),
        out_shardings=(replicated(mesh), replicated(mesh)),
    )
    put = make_global_batch(mesh)

    rank0_print(f"pretraining {args.model}: {args.epochs} epochs x "
                f"{len(loader)} steps, batch {args.train_batch_size}, "
                f"dtype {args.dtype}")
    # epoch-curve checkpoints ride the async writer: the epoch loop pays
    # only the device->host snapshot; serialization + the crash-atomic
    # publish run on the writer thread (same contract as Trainer's
    # resume saves — at most one save in flight, latest-wins per path)
    writer = AsyncCheckpointer()
    start = time.time()
    last = None
    for epoch in range(1, args.epochs + 1):
        loader.set_epoch(epoch - 1)
        for batch in loader:
            state, m = step_fn(state, put(batch))
            last = m
        if last is not None and (
                epoch % max(1, args.epochs // 30) == 0 or epoch == args.epochs):
            rank0_print(f"[pretrain] epoch {epoch}/{args.epochs} "
                        f"loss {float(last['loss']):.4f} "
                        f"mask_acc {float(last['mask_acc']):.4f}")
        if args.pretrain_ckpt_every and epoch % args.pretrain_ckpt_every == 0 \
                and epoch != args.epochs:
            # epoch-curve checkpoints: lets an accuracy-vs-pretrain-compute
            # sweep fine-tune from several depths of ONE run.  snapshot()
            # is collective (every process runs it); submit() no-ops off
            # rank 0 — the rank-0-writes split of the sync path
            writer.submit(
                args.ckpt_path(f"pretrained-e{epoch}.msgpack"),
                ckpt.snapshot(_mlm_artifact(state["params"])))
    if last is not None:
        float(jax.device_get(last["loss"]))  # completion barrier
    minutes = (time.time() - start) / 60
    rank0_print(f"pretrain 耗时：{minutes:.4f}分钟")
    path = args.ckpt_path(args.ckpt_name or "pretrained.msgpack")
    # the final artifact is durability work that must count toward the
    # reported runtime: publish it synchronously (outside the step loop),
    # then drain any still-in-flight epoch-curve saves so no partially
    # published curve file outlives the run
    ckpt.save_params(path, {"params": _mlm_artifact(state["params"])})
    writer.wait()
    rank0_print(f"pretrained encoder -> {path}")
    return path


def _mlm_artifact(params):
    """What the MLM stage actually trained: encoder + tied head.  The fresh
    pooler/classifier are dropped so ``load_encoder(head=True)`` on an MLM
    artifact fails loudly instead of silently restoring untrained noise."""
    return {k: v for k, v in params.items() if k not in ("pooler", "classifier")}


def upcycle_layers(dense_layers, moe_layers, noise_scale: float = 0.01,
                   seed: int = 0):
    """Dense->MoE *sparse upcycling*: build an MoE layer stack whose every
    expert starts as a copy of the pretrained dense MLP.

    The standard warm start for MoE (Komatsuzaki et al., "Sparse Upcycling"):
    each expert's up/down kernel ``[L, E, in, out]`` is the dense kernel
    ``[L, in, out]`` broadcast over the expert dim plus small seeded noise
    (``noise_scale`` x the kernel's own std) to break expert symmetry; biases
    copy exactly; the gate keeps its fresh init (there is nothing to upcycle
    a router from).  All non-MLP trees (attention, LayerNorms) must match
    shapes exactly and copy through.
    """
    rs = np.random.RandomState(seed)
    out = {}
    for sub, tmpl in moe_layers.items():
        if sub == "gate":
            out[sub] = tmpl  # fresh router
            continue
        if sub in ("up", "down"):
            E = tmpl["kernel"].shape[1]
            dk = np.asarray(dense_layers[sub]["kernel"])    # [L, in, out]
            db = np.asarray(dense_layers[sub]["bias"])      # [L, out]
            if tmpl["kernel"].shape != (dk.shape[0], E) + dk.shape[1:]:
                raise ValueError(
                    f"cannot upcycle {sub!r}: dense kernel {dk.shape} does "
                    f"not broadcast to expert shape {tmpl['kernel'].shape}")
            kernels = np.broadcast_to(dk[:, None], tmpl["kernel"].shape).copy()
            kernels += rs.normal(0.0, noise_scale * max(float(dk.std()), 1e-8),
                                 kernels.shape).astype(kernels.dtype)
            out[sub] = {"kernel": jnp.asarray(kernels, jnp.float32),
                        "bias": jnp.asarray(
                            np.broadcast_to(db[:, None], tmpl["bias"].shape),
                            jnp.float32)}
            continue
        got = jax.tree_util.tree_map(jnp.asarray, dense_layers[sub])
        t_shapes = jax.tree_util.tree_map(lambda l: l.shape, tmpl)
        g_shapes = jax.tree_util.tree_map(lambda l: l.shape, got)
        if t_shapes != g_shapes:
            raise ValueError(f"cannot upcycle: {sub!r} shapes differ "
                             f"({g_shapes} vs {t_shapes})")
        out[sub] = got
    return out


def load_encoder(path: str, params, head: bool = False):
    """Initialize fine-tune params from a pretrain checkpoint: embeddings +
    layers come from the file, pooler/classifier stay at fresh init — the
    ``from_pretrained`` analog (new head on a pretrained trunk).

    ``head=True`` additionally restores pooler + classifier — for checkpoints
    written by the supervised stage (``run_supervised_stage``), whose head was
    trained on the same 6-class task and is worth keeping.

    Loading a DENSE checkpoint into an MoE template (``gate`` in the
    template's layers, none in the file's) upcycles instead of failing:
    every expert warm-starts as the pretrained dense MLP (+ seeded
    symmetry-breaking noise), the gate stays fresh (``upcycle_layers``)."""
    import flax.serialization as ser

    with open(path, "rb") as f:
        restored = ser.msgpack_restore(f.read())
    if head and "mlm" in restored:
        # an 'mlm' tree marks an MLM-stage artifact; legacy ones also carry
        # the fresh-init pooler/classifier, which must not masquerade as a
        # trained head
        raise ValueError(
            f"{path!r} is an MLM-stage artifact (has an 'mlm' tree) — "
            "--init_head needs a supervised-stage checkpoint; its "
            "pooler/classifier were never trained")
    keys = ("embeddings", "layers") + (("pooler", "classifier") if head else ())
    out = dict(params)
    for key in keys:
        if key not in restored:
            raise ValueError(
                f"{path!r} has no {key!r} tree — "
                + ("not a supervised-pretrain checkpoint? (--init_head needs "
                   "one; MLM checkpoints carry no classifier)" if head else
                   "not a pretrain checkpoint?"))
        tmpl = params[key]
        if key == "layers" and "gate" in tmpl and "gate" not in restored[key]:
            out[key] = upcycle_layers(restored[key], tmpl)
            rank0_print(f"upcycled dense MLPs from {path} into "
                        f"{tmpl['up']['kernel'].shape[1]} experts "
                        "(fresh gate, seeded symmetry-breaking noise)")
            continue
        got = jax.tree_util.tree_map(jnp.asarray, restored[key])
        t_shapes = jax.tree_util.tree_map(lambda l: l.shape, tmpl)
        g_shapes = jax.tree_util.tree_map(lambda l: l.shape, got)
        if t_shapes != g_shapes:
            raise ValueError(
                f"pretrained {key!r} shapes do not match the model: "
                f"{g_shapes} vs {t_shapes}")
        out[key] = got
    return out
