"""Declarative managed trainer — the HF ``Trainer``/``TrainingArguments``
analog.

Capability twin of ``/root/reference/multi-gpu-transformers-cls.py:150-184``:
the user states *what* they want in a frozen ``TrainerArgs`` (step-based
eval/save cadence, precision, best-model tracking, seed) and ``AutoTrainer``
owns the whole run: loop, eval every ``eval_steps``, a rotating
``checkpoint-<step>`` directory per save (``save_steps``/``save_total_limit``),
``load_best_model_at_end`` with ``metric_for_best_model``, and a
``compute_metrics`` hook (``:91-96``).  Parallelism is the framework's mesh
DP — the analog of HF Trainer's implicit DDP — plus ``mode="zero"`` for
fully-sharded, a knob HF Trainer delegates to DeepSpeed.

Resume (HF's ``resume_from_checkpoint``): ``save_optimizer_state=True``
writes a full train state per rotation dir and
``resume_from_checkpoint="<dir>"|"latest"`` continues bitwise from it
(params + Adam moments + step + RNG restored, seeded data order
fast-forwarded).  Best-model tracking survives the crash too: each
resumable save writes a ``trainer_state.json`` (HF's file of the same
name) and resume restores ``best_metric``/``best_ckpt`` from it, so a
post-resume run that never beats the pre-crash best still ships it.

The training LOOP itself lives in ``Trainer.train`` — this class only
supplies managed-cadence callbacks (``LoopHooks``); see ``train()``.
"""
from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pdnlp_tpu.train import checkpoint as ckpt
from pdnlp_tpu.utils.config import Args
from pdnlp_tpu.utils.logging import rank0_print


@dataclasses.dataclass(frozen=True)
class TrainerArgs:
    """The ``TrainingArguments`` twin (reference fields at
    ``multi-gpu-transformers-cls.py:150-168``)."""

    output_dir: str = "output/auto"
    num_train_epochs: int = 1
    per_device_train_batch_size: int = 32
    per_device_eval_batch_size: int = 32
    learning_rate: float = 3e-5
    weight_decay: float = 0.01
    eval_steps: int = 50                  # evaluation_strategy="steps"
    save_steps: int = 50
    save_total_limit: Optional[int] = 3
    logging_steps: int = 10
    bf16: bool = False                    # fp16=True analog
    seed: int = 123
    load_best_model_at_end: bool = True
    metric_for_best_model: str = "accuracy"
    greater_is_better: bool = True
    # K optimizer steps fused into one device dispatch (lax.scan —
    # math-identical, per-step losses come back stacked), the same
    # fuse_steps knob the other strategies expose.  Must divide
    # logging/eval/save steps so every cadence boundary falls on a fused-
    # group boundary.  The big win is on high-RTT device transports where
    # per-step dispatch dominates the epoch.
    fuse_steps: int = 1
    # Rotation checkpoints are cast to this dtype ON DEVICE before the
    # fetch: "bfloat16" halves both the device->host bytes (the dominant
    # cost over a tunneled transport at save_steps=50: 8 full-precision
    # fetches measured ~6.5 min of a 7.2-min epoch in round 3) and the
    # disk bytes, the analog of HF Trainer's fp16 checkpoint files.  The
    # final/best model is NOT affected: a full-precision copy of the best
    # params is kept in HBM, adopted at the end, and re-written over the
    # best step's rotation dir (once, outside ``train_runtime``), so both
    # ``load_best_model_at_end`` AND the on-disk best artifact that
    # ``test_tpu.py`` sweeps are exact — only non-best rotation saves
    # (crash recovery points) stay bf16-rounded.
    save_dtype: str = "bfloat16"
    # HF's resume story: save_optimizer_state=True additionally writes
    # train_state.msgpack (params + Adam moments + step + RNG, full
    # precision — the analog of HF's optimizer.pt/scheduler.pt/rng_state)
    # into each rotation dir, and resume_from_checkpoint="<dir>" (or
    # "latest") restores it and fast-forwards the seeded data order to the
    # saved step — a bitwise continuation, like the elastic launcher's.
    # Off by default: it doubles the per-save device fetch, which dominates
    # the epoch on high-RTT transports (see save_dtype above).
    save_optimizer_state: bool = False
    resume_from_checkpoint: Optional[str] = None
    mode: str = "dp"                      # "zero" = the DeepSpeed delegation
    model: str = "bert-base"
    init_from: Optional[str] = None       # model_name_or_path analog (pretrain ckpt)
    init_head: bool = False               # restore the supervised-stage head too
    data_path: str = "/root/reference/data/train.json"
    data_limit: int = 10_000
    max_seq_len: int = 128

    def to_args(self) -> Args:
        return Args(
            strategy=f"auto-{self.mode}",
            model=self.model,
            data_path=self.data_path,
            output_dir=self.output_dir,
            train_batch_size=self.per_device_train_batch_size,
            dev_batch_size=self.per_device_eval_batch_size,
            learning_rate=self.learning_rate,
            weight_decay=self.weight_decay,
            epochs=self.num_train_epochs,
            seed=self.seed,
            eval_step=self.eval_steps,
            log_every=self.logging_steps,
            dtype="bfloat16" if self.bf16 else "float32",
            data_limit=self.data_limit,
            max_seq_len=self.max_seq_len,
            init_from=self.init_from,
            init_head=self.init_head,
            fuse_steps=self.fuse_steps,
            # the shared loop gates in-loop eval on dev, and the managed
            # runtime is reported against a warm compile (HF runs sit on a
            # warm CUDA context the same way)
            dev=True,
            warmup_compile=True,
        )


def _cast_like(params, dtype_name: str):
    """Device-side copy of a params tree with float leaves cast to
    ``dtype_name`` ("float32" = plain copy).  The cast runs on device, so a
    bf16 rotation save moves half the bytes over the device transport."""
    if dtype_name not in ("bfloat16", "float32"):
        raise ValueError(
            f"save_dtype={dtype_name!r} — use 'bfloat16' (half-byte "
            "rotation saves) or 'float32'; a silent fallback would quietly "
            "forfeit the transport/disk savings the knob exists for")
    dtype = jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32

    def leaf(x):
        if jnp.issubdtype(getattr(x, "dtype", np.float32), jnp.floating) \
                and getattr(x, "dtype", None) != dtype:
            return jnp.asarray(x, dtype)
        # same dtype: explicit copy — asarray would alias the live buffer,
        # which the next train step donates away
        return jnp.copy(x)

    return jax.tree_util.tree_map(leaf, params)


def default_compute_metrics(preds: np.ndarray, labels: np.ndarray) -> Dict[str, float]:
    """The reference's ``compute_metrics`` (argmax accuracy, ``:91-96``)."""
    return {"accuracy": float((preds == labels).mean()) if len(labels) else 0.0}


class AutoTrainer:
    """Fully-managed: ``AutoTrainer(targs).train()`` then ``.evaluate()``."""

    def __init__(self, targs: TrainerArgs,
                 compute_metrics: Callable[..., Dict[str, float]] = None):
        from pdnlp_tpu.train.run import build_parallel_trainer

        if targs.fuse_steps > 1:
            for name in ("logging_steps", "eval_steps", "save_steps"):
                if getattr(targs, name) % targs.fuse_steps:
                    raise ValueError(
                        f"fuse_steps={targs.fuse_steps} must divide {name}="
                        f"{getattr(targs, name)} — cadence boundaries must "
                        "fall on fused-group boundaries")
        self.targs = targs
        self.args = targs.to_args()
        self.compute_metrics = compute_metrics or default_compute_metrics
        self._trainer, self.train_loader, self.dev_loader = build_parallel_trainer(
            self.args, mode=targs.mode)
        self.state_history: List[Tuple[int, str]] = []  # (step, ckpt_dir)
        if targs.resume_from_checkpoint and not targs.save_optimizer_state \
                and targs.save_total_limit is not None:
            raise ValueError(
                "resume_from_checkpoint with params-only rotation saves "
                "would rotate away the pre-crash train_state.msgpack dirs — "
                "the run's ONLY recovery points if it crashes again.  Pass "
                "save_optimizer_state=True (keep writing resumable "
                "checkpoints) or save_total_limit=None (never rotate)")
        if targs.resume_from_checkpoint:
            # adopt the pre-crash rotation dirs so save_total_limit keeps
            # bounding TOTAL disk across crash/resume cycles (HF scans the
            # on-disk dirs the same way)
            import glob
            import re as _re

            for d in glob.glob(os.path.join(targs.output_dir, "checkpoint-*")):
                m = _re.fullmatch(r"checkpoint-(\d+)", os.path.basename(d))
                if m:
                    self.state_history.append((int(m.group(1)), d))
            self.state_history.sort()
        self.best_metric: Optional[float] = None
        self.best_ckpt: Optional[str] = None
        self._best_params = None  # full-precision best copy, device-held
        self._writers: List[threading.Thread] = []  # in-flight async saves
        self._writer_errors: List[Tuple[str, BaseException]] = []

    # ---------------------------------------------------------------- train
    def train(self) -> Dict[str, float]:
        """Managed run, driven by the ONE loop in ``Trainer.train``: this
        method only supplies the managed cadence callbacks (HF-style log
        line, eval-and-track-best, rotation checkpointing) via ``LoopHooks``
        — the epoch/fused-group machinery, elastic fast-forward, fused-
        boundary guard, heartbeat and profiler all come from the shared
        driver instead of a second copy of it."""
        from pdnlp_tpu.train.trainer import LoopHooks

        t = self._trainer
        targs = self.targs
        start_step = 0
        if targs.resume_from_checkpoint:
            state_path = self._resolve_resume(targs.resume_from_checkpoint)
            t.load_resume(state_path)
            start_step = int(jax.device_get(t.state["step"]))
            rank0_print(f"resumed from {state_path} at step {start_step}")
            # HF restores best-model tracking from trainer_state.json; so do
            # we — without it a resumed run whose post-resume evals never
            # beat the pre-crash best would silently ship a worse final
            # model (and rotation could delete the pre-crash best dir)
            self._restore_trainer_state(os.path.dirname(state_path))
        hooks = LoopHooks(
            on_log=lambda e, g, tot, loss: rank0_print(
                f"step {g}/{tot} loss {loss:.4f}"),
            on_eval=self._eval_and_log,
            save_every=targs.save_steps,
            on_save=self._save_checkpoint,
            # writer drain + rotation are durability work the reported
            # train_runtime must include (files must exist before reload)
            on_end=lambda: (self._drain_writers(), self._rotate()),
            end_save=False,  # best-model adoption below, not Trainer's ritual
        )
        minutes = t.train(self.train_loader, self.dev_loader, hooks=hooks)
        runtime = minutes * 60
        gstep = int(jax.device_get(t.state["step"]))
        if targs.load_best_model_at_end and self.best_ckpt:
            if self._best_params is not None:
                # the exact full-precision params of the best eval step,
                # kept in HBM — bit-equal to reloading a full-precision
                # save of that step, and free of the rotation dtype
                t.state["params"] = self._best_params
                self._best_params = None
                # re-write the best dir at FULL precision (once, outside
                # train_runtime): the on-disk artifact that test_tpu.py
                # sweeps must reproduce the reported best metric exactly,
                # not its bf16-rounded rotation copy
                ckpt.save_params(os.path.join(self.best_ckpt, "model.msgpack"),
                                 {"params": t.state["params"]})
            else:  # defensive: no HBM copy — reload the disk rotation save
                path = os.path.join(self.best_ckpt, "model.msgpack")
                restored = ckpt.load_params(path, t.state["params"])
                # an interrupted run's rotation save may be bf16: restore
                # the live tree's dtypes so the jitted eval signature holds
                t.state["params"] = jax.tree_util.tree_map(
                    lambda r, cur: jnp.asarray(r, getattr(cur, "dtype", None)),
                    restored, t.state["params"])
            rank0_print(f"loaded best model ({targs.metric_for_best_model}="
                        f"{self.best_metric:.4f}) from {self.best_ckpt}")
        # only steps actually executed this run count toward throughput —
        # a resumed run's fast-forwarded steps trained in a previous life
        n_examples = max(0, gstep - start_step) * self.args.train_batch_size
        return {"train_runtime": runtime,
                "train_samples_per_second":
                    n_examples / runtime if runtime > 0 else 0.0,
                "global_step": gstep}

    # ----------------------------------------------------------------- eval
    def evaluate(self) -> Dict[str, float]:
        r = self._trainer.test(self.dev_loader)
        m = self.compute_metrics(np.asarray(r["y_pred"]), np.asarray(r["y_true"]))
        return {"eval_loss": r["loss"], **{f"eval_{k}": v for k, v in m.items()}}

    def _eval_and_log(self, gstep: int) -> None:
        m = self.evaluate()
        rank0_print("  ".join(f"{k} {v:.4f}" for k, v in m.items()))
        key = f"eval_{self.targs.metric_for_best_model}"
        val = m.get(key)
        if val is None:
            return
        better = (self.best_metric is None
                  or (val > self.best_metric) == self.targs.greater_is_better)
        if better:
            self.best_metric = val
            self.best_ckpt = self._ckpt_dir(gstep)
            if self.targs.load_best_model_at_end:
                # full-precision device-held copy (the live buffers are
                # donated): what train() adopts at the end
                self._best_params = jax.tree_util.tree_map(
                    jnp.copy, self._trainer.state["params"])
            # A best model must exist on disk for load_best_model_at_end even
            # when eval_steps is not aligned to save_steps (HF Trainer instead
            # forbids the misalignment); _save_checkpoint dedupes, so a
            # coinciding save_steps boundary won't write twice.
            if self.targs.load_best_model_at_end:
                self._save_checkpoint(gstep)

    # ----------------------------------------------------------- checkpoints
    def _ckpt_dir(self, gstep: int) -> str:
        return os.path.join(self.targs.output_dir, f"checkpoint-{gstep}")

    def _resolve_resume(self, spec: str) -> str:
        """``resume_from_checkpoint``: a checkpoint dir, a train_state file,
        or "latest" (newest rotation dir that has a train_state)."""
        if spec == "latest":
            import glob

            cands = sorted(
                glob.glob(os.path.join(self.targs.output_dir, "checkpoint-*",
                                       "train_state.msgpack")),
                key=lambda p: int(p.split("checkpoint-")[-1].split(os.sep)[0]))
            if not cands:
                raise FileNotFoundError(
                    f"no checkpoint-*/train_state.msgpack under "
                    f"{self.targs.output_dir} — resumable checkpoints need "
                    "save_optimizer_state=True")
            return cands[-1]
        if os.path.isdir(spec):
            spec = os.path.join(spec, "train_state.msgpack")
        if not os.path.exists(spec):
            raise FileNotFoundError(
                f"{spec} not found — resumable checkpoints are written only "
                "under save_optimizer_state=True (params-only rotation saves "
                "cannot restore the optimizer)")
        return spec

    def _restore_trainer_state(self, ckpt_dir: str) -> None:
        """Restore best-model tracking from the checkpoint's
        ``trainer_state.json`` (HF Trainer's file of the same name).  A
        missing file (pre-r5 checkpoint) degrades to fresh tracking — the
        pre-crash best is then only re-discovered if beaten."""
        path = os.path.join(ckpt_dir, "trainer_state.json")
        if not os.path.exists(path):
            return
        with open(path) as f:
            saved = json.load(f)
        best_ckpt = saved.get("best_ckpt")
        if best_ckpt and not os.path.isdir(best_ckpt):
            rank0_print(f"saved best checkpoint {best_ckpt} no longer "
                        "exists; best-model tracking restarts")
            return
        self.best_metric = saved.get("best_metric")
        self.best_ckpt = best_ckpt
        if self.best_metric is not None:
            rank0_print(
                f"restored best {self.targs.metric_for_best_model}="
                f"{self.best_metric:.4f} from {self.best_ckpt} "
                "(rotation will keep protecting it)")

    def _save_checkpoint(self, gstep: int) -> None:
        """Checkpoint WITHOUT stalling the device: snapshot params in HBM
        cast to ``save_dtype`` (the live buffers are donated; the cast also
        halves the bytes when bf16), then fetch + serialize in a writer
        thread that overlaps with continued training.  HF Trainer blocks
        the step loop on every save; over a tunneled device transport that
        serialization dominated the epoch (measured 4.3 min vs ~0.6 for the
        other strategies at the reference's save_steps=50 cadence), and the
        full-precision fetches still cost ~6.5 min of round 3's 7.2-min
        epoch even asynchronously — the transport is shared, so the train
        steps queue behind the transfer bytes either way.

        Multi-process runs save synchronously: ``consolidate`` runs
        collective all-gathers, which must not race training collectives on
        another thread."""
        d = self._ckpt_dir(gstep)
        if any(dir_ == d for _, dir_ in self.state_history):
            return  # already written this step (best-model save + save_steps)
        path = os.path.join(d, "model.msgpack")
        if self.targs.save_optimizer_state:
            # the resume artifact (params + moments + step + RNG), written
            # SYNCHRONOUSLY from the live state between steps — full
            # precision by necessity (bitwise resume), which is exactly why
            # it is opt-in: it adds a full-state fetch per save
            ckpt.save_state(os.path.join(d, "train_state.msgpack"),
                            self._trainer.state)
            if jax.process_index() == 0:
                # the trainer_state.json analog: best-model tracking must
                # survive a crash/resume cycle (restored by train())
                with open(os.path.join(d, "trainer_state.json"), "w") as f:
                    json.dump({"best_metric": self.best_metric,
                               "best_ckpt": self.best_ckpt,
                               "global_step": gstep}, f)
        if jax.process_count() > 1:
            ckpt.save_params(path, {
                "params": _cast_like(self._trainer.state["params"],
                                     self.targs.save_dtype)})
        else:
            snap = _cast_like(self._trainer.state["params"],
                              self.targs.save_dtype)

            def write(path=path, snap=snap):
                try:
                    ckpt.save_params(path, {"params": snap})
                except BaseException as e:  # surfaced at the next drain
                    self._writer_errors.append((path, e))

            t = threading.Thread(target=write, daemon=True)
            t.start()
            self._writers.append(t)
        self.state_history.append((gstep, d))
        # bound in-flight disk usage near the user's cap (a few extra dirs
        # may exist transiently while writers overlap training)
        if len(self.state_history) > (self.targs.save_total_limit or 16):
            self._drain_writers()
            self._rotate()

    def _drain_writers(self) -> None:
        for t in self._writers:
            t.join()
        self._writers.clear()
        if self._writer_errors:
            path, err = self._writer_errors[0]
            self._writer_errors.clear()
            raise RuntimeError(
                f"async checkpoint write failed for {path}") from err

    def _rotate(self) -> None:
        if jax.process_index() != 0:
            return
        limit = self.targs.save_total_limit
        while limit and len(self.state_history) > limit:
            _, old = self.state_history.pop(0)
            if old != self.best_ckpt:  # never rotate away the best model
                shutil.rmtree(old, ignore_errors=True)
