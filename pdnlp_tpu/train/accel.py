"""``Accelerator`` — the HF Accelerate analog: write single-device code,
call ``prepare``, and it runs distributed.

Capability twin of ``/root/reference/multi-gpu-accelerate-cls.py:289-294``:
``Accelerator()`` detects the runtime; ``prepare(...)`` wraps the pieces the
user already built (state pytree, data loaders, step functions) so the same
hand-written training loop executes data-parallel over the whole mesh.  The
reference's ``accelerator.backward(loss)`` has no TPU twin because backward
is inside the jitted step; what ``prepare`` does instead is (a) re-batch the
loaders to the global batch (the auto-sharded DataLoader analog, which is
also why the reference divides ``total_step`` by device count, ``:145,271``),
(b) shard/replicate the state onto the mesh, and (c) compile user step
functions with the right in/out shardings.

Unlike ``train.run.build_parallel_trainer`` (which wires this framework's
own ``Trainer``), ``Accelerator`` distributes *your* functions and *your*
loop — see ``multi-tpu-accelerate-cls.py`` for the loop written in reference
style.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Optional, Tuple

import jax

from pdnlp_tpu.data.loader import DataLoader
from pdnlp_tpu.data.sampler import DistributedShardSampler
from pdnlp_tpu.parallel import (
    init_runtime, local_batch_mult, make_global_batch, make_mesh,
)
from pdnlp_tpu.parallel.sharding import batch_sharding, replicated, state_shardings


class _PreparedLoader:
    """A loader whose batches arrive as global, mesh-sharded ``jax.Array``s."""

    def __init__(self, loader: DataLoader, put: Callable):
        self._loader = loader
        self._put = put

    def __len__(self):
        return len(self._loader)

    def set_epoch(self, epoch: int) -> None:
        self._loader.set_epoch(epoch)

    def __iter__(self):
        for batch in self._loader:
            yield self._put(batch)


class Accelerator:
    """Runtime detection + ``prepare``; mirrors the 4-line setup of the
    reference script (``Accelerator()`` then one ``prepare`` call)."""

    def __init__(self, args=None, mode: str = "dp"):
        if args is not None:
            init_runtime(args)
        self.args = args
        self.dtype = getattr(args, "dtype", "float32") if args else "float32"
        self.mode = mode
        self.mesh = make_mesh(
            num_devices=getattr(args, "num_devices", None) if args else None,
            shape=getattr(args, "mesh_shape", None) if args else None,
        )
        self.put = make_global_batch(self.mesh)
        self.num_devices = self.mesh.size
        # prepare() scales loader batches by this; anything sized in steps
        # (LR schedules, total_step prints) must divide by it up front
        self.batch_mult = local_batch_mult(self.mesh)
        self.process_index = jax.process_index()
        self.is_main_process = self.process_index == 0
        self._shardings = None

    # ------------------------------------------------------- machine config
    @classmethod
    def from_config(cls, path: str, args=None) -> "Accelerator":
        """Build an ``Accelerator`` from a machine-config FILE — the analog
        of accelerate's ``default_config.yaml``
        (``/root/reference/default_config.yaml:1-15``), which the reference
        feeds via ``accelerate launch --config_file``.

        Accepts JSON or YAML.  Recognized keys (HF names, mapped to the
        TPU-native runtime; unknown keys are ignored like accelerate does):

        - ``num_processes``     -> mesh size cap (``Args.num_devices``)
        - ``mesh_shape``        -> explicit axis dict (TPU-native extension,
                                   e.g. ``{"data": 2, "model": 4}``)
        - ``mixed_precision``   -> ``"bf16"``/``"fp16"`` select bfloat16
                                   compute (fp16 has no TPU fast path)
        - ``distributed_type``  -> ``"DEEPSPEED"`` places state fully
                                   sharded (mode "zero"); anything else dp
        - ``num_machines`` / ``machine_rank`` / ``main_process_ip`` /
          ``main_process_port`` -> multi-host rendezvous
          (``jax.distributed.initialize`` via ``Args`` coordinator fields)
        """
        with open(path) as f:
            text = f.read()
        try:
            cfg = json.loads(text)
        except ValueError:
            import yaml

            cfg = yaml.safe_load(text)
        from pdnlp_tpu.utils.config import Args

        base = args if args is not None else Args()
        over = {}
        if cfg.get("num_processes"):
            over["num_devices"] = int(cfg["num_processes"])
        if cfg.get("mesh_shape"):
            over["mesh_shape"] = {str(k): int(v)
                                  for k, v in cfg["mesh_shape"].items()}
        mp = str(cfg.get("mixed_precision", "no")).lower()
        if mp in ("bf16", "fp16", "bfloat16"):
            over["dtype"] = "bfloat16"
        if int(cfg.get("num_machines", 1)) > 1:
            host = cfg.get("main_process_ip", "127.0.0.1")
            port = cfg.get("main_process_port", 12355)
            over["coordinator_address"] = f"{host}:{port}"
            over["num_processes"] = int(cfg["num_machines"])
            over["process_id"] = int(cfg.get("machine_rank", 0))
        mode = ("zero" if str(cfg.get("distributed_type", "")).upper()
                == "DEEPSPEED" else "dp")
        return cls(args=base.replace(**over), mode=cfg.get("mode", mode))

    # ------------------------------------------------------------- prepare
    def prepare(self, state: Any, *loaders: DataLoader) -> Tuple:
        """(state, *loaders) distributed: state placed on the mesh under the
        chosen mode, loaders re-batched to global batch and yielding sharded
        arrays.  Mirrors ``model, optimizer, loaders = accelerator.prepare(...)``."""
        self._shardings = state_shardings(state, self.mesh, self.mode)
        state = jax.device_put(state, self._shardings)
        mult = local_batch_mult(self.mesh)
        prepared = []
        for loader in loaders:
            sampler = loader.sampler
            if hasattr(sampler, "chunks"):
                # A batching sampler (LengthGroupedSampler) owns the chunk
                # size: re-batching the loader without rebuilding it would
                # leave every chunk at the UNSCALED batch size — take()
                # pads to batch*mult, so (mult-1)/mult of each batch would
                # be zero-weight filler, a silent mult× throughput loss.
                # Rebuild it at the scaled batch (and, multi-process, on
                # this host's shard of the SAME seeded global batches).
                from pdnlp_tpu.data.sampler import LengthGroupedSampler

                multi = jax.process_count() > 1
                sampler = LengthGroupedSampler(
                    sampler.lengths, loader.batch_size * mult,
                    buckets=sampler.buckets,
                    num_shards=jax.process_count() if multi
                    else sampler.num_shards,
                    shard_id=jax.process_index() if multi
                    else sampler.shard_id,
                    shuffle=sampler.shuffle, seed=sampler.seed,
                    drop_last=sampler.drop_last,
                )
            elif jax.process_count() > 1 and \
                    sampler.num_shards != jax.process_count():
                # Multi-process: each host must feed a DISJOINT shard, or
                # make_array_from_process_local_data assembles a global batch
                # of process_count duplicates (the reference's sampler-less
                # DeepSpeed/Accelerate double-count, SURVEY.md §7 — here it
                # would silently corrupt training, not just eval reports).
                sampler = DistributedShardSampler(
                    sampler.num_examples, jax.process_count(),
                    jax.process_index(), shuffle=sampler.shuffle,
                    seed=sampler.seed, drop_last=sampler.drop_last,
                )
            scaled = DataLoader(
                loader.data, loader.collator, loader.batch_size * mult,
                sampler=sampler, drop_last=loader.drop_last,
                prefetch=loader.prefetch, encoded=loader.encoded,
            )
            prepared.append(_PreparedLoader(scaled, self.put))
        return (state, *prepared)

    def compile_step(self, fn: Callable, donate_state: bool = True) -> Callable:
        """Compile a user train step ``fn(state, batch) -> (state, metrics)``
        over the mesh (the ``accelerator.backward`` + DDP-wrapping analog:
        XLA inserts the gradient all-reduce)."""
        if self._shardings is None:
            raise RuntimeError("call prepare(state, ...) before compile_step")
        return jax.jit(
            fn,
            donate_argnums=0 if donate_state else (),
            in_shardings=(self._shardings, batch_sharding(self.mesh)),
            out_shardings=(self._shardings, replicated(self.mesh)),
        )

    def compile_eval(self, fn: Callable) -> Callable:
        """Compile a user eval step ``fn(params, batch) -> metrics``."""
        if self._shardings is None:
            raise RuntimeError("call prepare(state, ...) before compile_eval")
        return jax.jit(
            fn,
            in_shardings=(self._shardings["params"], batch_sharding(self.mesh)),
            out_shardings=replicated(self.mesh),
        )

    # ------------------------------------------------------------- helpers
    def gather(self, x) -> Any:
        """Fetch a (replicated) device value to the host — also the true
        completion barrier (see ``Trainer.train``)."""
        return jax.device_get(x)

    def print(self, *a, **kw) -> None:
        if self.is_main_process:
            print(*a, **kw)
