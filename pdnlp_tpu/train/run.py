"""One shared parallel runner behind every multi-device entrypoint.

The reference's nine scripts each re-assemble the same experiment around a
different wrapper (DDP / Horovod / DeepSpeed / ...).  Here the experiment is
assembled once and the *strategy* is three knobs:

- ``mode``: ``"dp"`` (replicated state — DDP analog) or ``"zero"`` (fully
  sharded state — DeepSpeed ZeRO-3 analog);
- ``explicit_collectives``: compile through ``shard_map`` with hand-written
  ``psum`` + bf16 gradient compression (Horovod analog) instead of letting
  XLA insert collectives from shardings;
- ``scale_batch``: ``True`` scales the global batch by the data-axis size so
  steps shrink with devices (DDP's ``DistributedSampler`` math: 144 @ 2-way);
  ``False`` keeps the reference's ``nn.DataParallel`` semantics — same
  32-row global batch scattered over devices, step count unchanged (288)
  (``/root/reference/multi-gpu-dataparallel-cls.py:255``, ``README.md:44-74``).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax

from pdnlp_tpu.data.corpus import LABELS
from pdnlp_tpu.parallel import (
    local_batch_mult, make_global_batch, make_mesh, make_parallel_eval_step,
    make_parallel_train_step, make_shardmap_train_step, init_runtime,
    setup_sharded_model,
)
from pdnlp_tpu.parallel.execution import make_parallel_multi_step
from pdnlp_tpu.train.setup import setup_data, setup_pipeline
from pdnlp_tpu.train.trainer import Trainer
from pdnlp_tpu.utils.config import Args
from pdnlp_tpu.utils.logging import rank0_print
from pdnlp_tpu.utils.metrics import classification_report


def build_parallel_trainer(
    args: Args,
    *,
    mode: str = "dp",
    explicit_collectives: bool = False,
    scale_batch: bool = True,
    mesh=None,
    train_override=None,
) -> Tuple[Trainer, object, object]:
    """(trainer, train_loader, dev_loader) wired for the given strategy.

    ``train_override`` swaps the train split's examples (supervised-pretrain
    stage); everything else — dev split, mesh, sharding, step — is shared."""
    if mesh is None:
        proc0 = init_runtime(args)[0] == 0  # noqa: F841  (rendezvous side effect)
        mesh = make_mesh(num_devices=args.num_devices, shape=args.mesh_shape)
    if getattr(args, "offload_opt_state", False) and (
            explicit_collectives or args.fuse_steps > 1 or mode == "tp"):
        raise ValueError("--offload_opt_state works with the jit dp/zero "
                         "strategies, not shard_map, fused multi-steps, or "
                         "tp — the staged host<->device transfers are only "
                         "wired into the plain data-axis train step")
    from pdnlp_tpu.data.sampler import resolve_length_mode

    if explicit_collectives and resolve_length_mode(args) != "full":
        raise ValueError(
            "--length_mode bucket/pack is wired into the jit strategies "
            "(shapes re-specialize per bucket; packed batches carry extra "
            "channels) — the hand-written shard_map step compiles one "
            "fixed-shape program; use the dp/zero jit path instead")
    if scale_batch:
        # which slice of the global batch this process feeds — handles both
        # a data axis split across processes (dp/zero: each host its shard)
        # and one replicated across them (e.g. tp/ep with the model/expert
        # axis spanning the process boundary: every host the full batch)
        from pdnlp_tpu.parallel.mesh import local_data_extent

        num_shards, shard_id, mult = local_data_extent(mesh)
    else:
        num_shards, shard_id, mult = (jax.process_count(),
                                      jax.process_index(), 1)
    train_loader, dev_loader, tok = setup_data(
        args,
        num_shards=num_shards,
        shard_id=shard_id,
        device_batch_mult=mult,
        train_override=train_override,
    )
    cfg, tx, state, shardings = setup_sharded_model(
        args, tok.vocab_size, mesh, mode,
        total_steps=len(train_loader) * args.epochs)
    if explicit_collectives:
        train_step = make_shardmap_train_step(cfg, tx, args, mesh)
    else:
        train_step = make_parallel_train_step(cfg, tx, args, mesh, shardings)
    eval_step = make_parallel_eval_step(cfg, args, mesh, shardings["params"])
    multi_step = put_fused = None
    if args.fuse_steps > 1 and not explicit_collectives:
        multi_step = make_parallel_multi_step(cfg, tx, args, mesh, shardings)
        put_fused = make_global_batch(mesh, leading_stack=True)
    put = make_global_batch(mesh)
    pipeline = setup_pipeline(args, train_loader, put=put,
                              put_fused=put_fused, mesh=mesh)
    trainer = Trainer(args, cfg, state, train_step, eval_step,
                      put=put, multi_step=multi_step, put_fused=put_fused,
                      pipeline=pipeline)
    rank0_print(
        f"mesh: {dict(mesh.shape)}  process {jax.process_index()}/{jax.process_count()}"
        f"  mode: {mode}{' +shard_map' if explicit_collectives else ''}"
        f"  dtype: {args.dtype}  global batch: "
        f"{args.train_batch_size * mesh.shape.get('data', 1) if scale_batch else args.train_batch_size}"
        f"  steps/epoch: {len(train_loader)}  pipeline: {pipeline.mode}")
    return trainer, train_loader, dev_loader


def _try_resume(trainer, args: Args) -> None:
    """Restore the newest resume snapshot when one exists.  Same-width
    restores continue bitwise; a snapshot saved at a different data-
    parallel width reshards onto this mesh and remaps the data position
    (``Trainer.load_resume``/``_remap_elastic_width``).  A snapshot whose
    file AND retained previous are both corrupt degrades to a fresh start
    with a loud warning — for an elastic gang, re-training beats
    crash-looping the supervisor's restart budget away."""
    import os

    from pdnlp_tpu.train import checkpoint as ckpt

    if not (args.resume_from and os.path.exists(args.resume_path())):
        return
    try:
        trainer.load_resume(args.resume_path())
    except ckpt.CorruptCheckpointError as e:
        rank0_print(f"WARNING: resume snapshot unusable ({e}) — no valid "
                    "previous snapshot retained either; starting from "
                    "scratch")
        return
    rank0_print(f"resumed from {args.resume_path()} at step "
                f"{int(jax.device_get(trainer.state['step']))}")


def run_parallel(args: Args, **strategy) -> float:
    """Train + test; returns wall-clock minutes (the north-star metric)."""
    trainer, train_loader, dev_loader = build_parallel_trainer(args, **strategy)
    _try_resume(trainer, args)
    minutes = trainer.train(train_loader, dev_loader)
    result = trainer.test(dev_loader)
    rank0_print(f"test loss：{result['loss']:.6f} accuracy：{result['accuracy']:.4f}")
    rank0_print(classification_report(result["y_true"], result["y_pred"], LABELS))
    return minutes


def build_sp_trainer(args: Args, mesh=None):
    """(trainer, train_loader, dev_loader) for the sequence-parallel (ring
    attention) path — multi-process aware: on a mesh whose ``seq`` axis
    spans processes, the data axis is process-local, every process feeds the
    full global batch, and ``make_sp_batch`` hands each device its sequence
    slice (the ring's ``ppermute`` then crosses the process boundary)."""
    from pdnlp_tpu.data.sampler import resolve_length_mode
    from pdnlp_tpu.parallel import init_runtime, make_mesh
    from pdnlp_tpu.parallel.mesh import local_data_extent
    from pdnlp_tpu.parallel.sp import (
        SEQ, make_sp_batch, make_sp_eval_step, make_sp_train_step,
    )
    from pdnlp_tpu.train.setup import setup_model

    if resolve_length_mode(args) != "full":
        raise ValueError(
            "--length_mode bucket/pack is not wired into the sequence-"
            "parallel TRAINER yet: the ring/step layer itself speaks the "
            "packed channel layout as of PR 12 (per-hop shard-local masks, "
            "cross-shard [CLS] gather — parity in tests/test_longcontext."
            "py), but this entrypoint's loader/fuse wiring still assumes "
            "one full-width shape per step — use the dp/zero strategies "
            "for length-aware training")
    if mesh is None:
        init_runtime(args)
        shape = args.mesh_shape or {"data": 1, SEQ: len(jax.devices())}
        mesh = make_mesh(num_devices=args.num_devices, shape=shape)
    num_shards, shard_id, mult = local_data_extent(mesh)
    if jax.process_count() > 1 and num_shards > 1 \
            and local_data_extent(mesh, SEQ)[0] > 1:
        raise ValueError(
            "a mesh whose data AND seq axes both span processes needs "
            "per-process partial batches with seq slicing — order the mesh "
            "so one of the two axes stays process-local")
    train_loader, dev_loader, tok = setup_data(
        args, num_shards=num_shards, shard_id=shard_id,
        device_batch_mult=mult)
    cfg, tx, state = setup_model(args, tok.vocab_size,
                                 total_steps=len(train_loader) * args.epochs)
    example = next(iter(train_loader))
    train_step = make_sp_train_step(cfg, tx, args, mesh)(example)
    eval_step = make_sp_eval_step(cfg, args, mesh)(example)
    sp_put = make_sp_batch(mesh)
    # resident disallowed: the ring slices each batch along seq, not the
    # plain data-axis placement the resident gather produces
    pipeline = setup_pipeline(args, train_loader, put=sp_put,
                              allow_resident=False)
    trainer = Trainer(args, cfg, state, train_step, eval_step,
                      put=sp_put, pipeline=pipeline)
    rank0_print(f"mesh: {dict(mesh.shape)}  process "
                f"{jax.process_index()}/{jax.process_count()}  ring axis: "
                f"{SEQ} (local seq {args.max_seq_len // mesh.shape[SEQ]})  "
                f"steps/epoch: {len(train_loader)}")
    return trainer, train_loader, dev_loader


def run_sp(args: Args) -> float:
    """Train + test on the sequence-parallel path; returns wall-clock min."""
    trainer, train_loader, dev_loader = build_sp_trainer(args)
    minutes = trainer.train(train_loader, dev_loader)
    result = trainer.test(dev_loader)
    rank0_print(f"test loss：{result['loss']:.6f} accuracy：{result['accuracy']:.4f}")
    rank0_print(classification_report(result["y_true"], result["y_pred"], LABELS))
    return minutes


def build_pipeline_trainer(args: Args, mesh=None):
    """(trainer, train_loader, dev_loader) for the pipeline (GPipe) path —
    the ``pp`` twin of ``build_parallel_trainer``, multi-process aware: on a
    mesh whose ``stage`` (and optionally ``data``) axes span processes, each
    process feeds its data shard (or the full batch when there is no data
    axis — the batch is then replicated, stages exchange activations)."""
    from pdnlp_tpu.data.sampler import resolve_length_mode
    from pdnlp_tpu.parallel.pp import (
        STAGE, make_pp_batch, make_pp_eval_step, make_pp_train_step,
        setup_pp_model,
    )
    from pdnlp_tpu.parallel import init_runtime, make_mesh
    from pdnlp_tpu.parallel.mesh import local_data_extent

    if resolve_length_mode(args) != "full":
        raise ValueError(
            "--length_mode bucket/pack is not supported on the pipeline "
            "(GPipe) path: stages compile one fixed microbatch shape and "
            "the per-segment head gather lives on the last stage only — "
            "use the dp/zero strategies")
    if mesh is None:
        init_runtime(args)
        shape = args.mesh_shape or {STAGE: len(jax.devices())}
        mesh = make_mesh(num_devices=args.num_devices, shape=shape)
    # which slice of the global batch this process feeds: on a stage-major
    # multi-process mesh the data axis is replicated across processes and
    # every host feeds the full batch; on a data-major one each host feeds
    # its shard (local_data_extent covers both)
    num_shards, shard_id, mult = local_data_extent(mesh)
    train_loader, dev_loader, tok = setup_data(
        args, num_shards=num_shards, shard_id=shard_id,
        device_batch_mult=mult,
    )
    cfg, tx, state, _ = setup_pp_model(
        args, tok.vocab_size, mesh,
        total_steps=len(train_loader) * args.epochs)
    train_step = make_pp_train_step(cfg, tx, args, mesh,
                                    n_micro=args.microbatches)
    eval_step = make_pp_eval_step(cfg, args, mesh, n_micro=args.microbatches)
    pp_put = make_pp_batch(mesh)
    # resident disallowed: pp places batches along the stage-major layout,
    # not the plain data-axis sharding the resident gather produces
    pipeline = setup_pipeline(args, train_loader, put=pp_put,
                              allow_resident=False)
    trainer = Trainer(args, cfg, state, train_step, eval_step,
                      put=pp_put, pipeline=pipeline)
    rank0_print(f"mesh: {dict(mesh.shape)}  process "
                f"{jax.process_index()}/{jax.process_count()}  stages: "
                f"{mesh.shape[STAGE]} x {cfg.num_layers // mesh.shape[STAGE]}"
                f" layers  microbatches: {args.microbatches}  "
                f"steps/epoch: {len(train_loader)}")
    return trainer, train_loader, dev_loader


def run_pipeline(args: Args) -> float:
    """Train + test on the pipeline path; returns wall-clock minutes."""
    trainer, train_loader, dev_loader = build_pipeline_trainer(args)
    _try_resume(trainer, args)
    minutes = trainer.train(train_loader, dev_loader)
    result = trainer.test(dev_loader)
    rank0_print(f"test loss：{result['loss']:.6f} accuracy：{result['accuracy']:.4f}")
    rank0_print(classification_report(result["y_true"], result["y_pred"], LABELS))
    return minutes
