"""Optimizer construction — AdamW with the reference's two weight-decay groups.

The reference builds HF AdamW over two param groups: decay 0.01 for most
weights, 0.0 for anything named ``bias`` or ``LayerNorm.weight``
(``/root/reference/single-gpu-cls.py:86-97``).  The TPU-native equivalent is
a single ``optax.adamw`` with a decay *mask* over the param pytree — same
math, one fused update, no group bookkeeping.

Our pytree's no-decay leaves are every ``bias`` and every LayerNorm
``scale``/``bias`` (named ``*_ln`` / ``ln``), matching the reference's
``['bias', 'LayerNorm.weight']`` filter.
"""
from __future__ import annotations

import jax
import optax


def decay_mask(params) -> object:
    """True = apply weight decay.  LayerNorm params and biases are exempt."""

    def walk(tree, in_ln=False):
        if isinstance(tree, dict):
            return {
                k: walk(v, in_ln or k == "ln" or k.endswith("_ln"))
                for k, v in tree.items()
            }
        return not in_ln

    masked = walk(params)

    # biases inside dense blocks: {'kernel': ..., 'bias': ...}
    def debias(tree, mask):
        if isinstance(tree, dict):
            return {
                k: (False if k == "bias" else debias(tree[k], mask[k]))
                for k in tree
            }
        return mask

    return debias(params, masked)


def make_schedule(args, total_steps):
    """Learning-rate schedule from ``Args`` (``--lr_schedule``), or ``None``
    for the reference's constant LR.  ``warmup_linear`` (the BERT-paper
    recipe) measured best on the fine-tune sweep: +0.8 dev accuracy over
    constant 3e-5 at peak 5e-5 (``scripts/sweep_recipe.py``).

    Raises when a schedule is configured but ``total_steps`` is missing or
    zero — a silently constant LR under ``--lr_schedule`` (e.g. from an
    empty loader) is the failure mode this guard exists for."""
    if not getattr(args, "lr_schedule", None):
        return None
    if not total_steps:
        raise ValueError(
            f"--lr_schedule {args.lr_schedule!r} needs a positive "
            f"total_steps to size warmup/decay; got {total_steps!r} "
            "(empty train loader, or a caller not passing loader length x "
            "epochs)")
    w = max(1, int(total_steps * args.warmup_ratio))
    if args.lr_schedule == "warmup_linear":
        return optax.join_schedules(
            [optax.linear_schedule(0.0, args.learning_rate, w),
             optax.linear_schedule(args.learning_rate, 0.0, total_steps - w)],
            [w])
    if args.lr_schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            0.0, args.learning_rate, w, total_steps)
    raise ValueError(f"unknown lr_schedule {args.lr_schedule!r} "
                     "(warmup_linear|warmup_cosine)")


def build_optimizer(params, args, schedule=None) -> optax.GradientTransformation:
    """AdamW lr/b1/b2/eps/wd from ``Args`` (defaults mirror
    ``single-gpu-cls.py:86-97``: lr 3e-5, decay 0.01, no schedule).

    ``schedule`` overrides the constant learning rate: the MLM pretraining
    stage always passes one (warmup+decay), and fine-tune entrypoints pass
    ``make_schedule(args, total_steps)`` when ``--lr_schedule`` is set
    (constant LR — the reference's semantics — remains the default)."""
    return optax.adamw(
        learning_rate=schedule if schedule is not None else args.learning_rate,
        b1=args.adam_b1,
        b2=args.adam_b2,
        eps=args.adam_eps,
        weight_decay=args.weight_decay,
        mask=decay_mask(params),
    )


def count_decayed(params) -> tuple:
    """(decayed, exempt) leaf counts — used by tests and logging."""
    mask = decay_mask(params)
    leaves = jax.tree_util.tree_leaves(mask)
    dec = sum(1 for m in leaves if m)
    return dec, len(leaves) - dec
