"""The Trainer — train / dev / test with the reference's semantics.

Twin of the per-script ``Trainer`` classes
(``/root/reference/multi-gpu-distributed-cls.py:113-239``):

- ``train``: epoch loop, per-step loss line ``【train】 epoch：e/E step：s/S
  loss：x``, optional dev every ``eval_step`` with best-accuracy
  checkpointing (``:183-192``), wall-clock ``耗时：X分钟`` at the end
  (``:193-195``), end-of-run checkpoint when ``dev`` is off (``:196-197``).
- ``dev``: eval over the dev loader -> (mean loss, accuracy) — the psum/
  all-gather math happens inside the jitted eval step.
- ``test``: dev + collected predictions for the classification report.

TPU-specific behavior: the per-step loss is fetched lazily — jax dispatch is
async, so ``float(loss)`` only blocks on steps that actually print
(``log_every``), keeping the device queue full between log lines.  The
reference instead syncs every step (`.item()` after an explicit barrier).
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, Optional, Tuple

import jax
import numpy as np

from pdnlp_tpu.train import checkpoint as ckpt
from pdnlp_tpu.utils.logging import (
    fmt_best, fmt_dev, fmt_elapsed_minutes, fmt_train, rank0_print,
)
from pdnlp_tpu.utils.profiling import Profiler, StepStats


@dataclasses.dataclass
class LoopHooks:
    """Cadence callbacks for ``Trainer.train`` — ONE epoch/fused-group/
    cadence driver serves both the reference-style Trainer and the managed
    ``AutoTrainer`` (which supplies rotation-checkpoint and best-model
    callbacks here instead of re-implementing the loop; heartbeat, profiler,
    elastic fast-forward, and the fused-boundary guard therefore work
    identically on both paths).

    Every hook receives resolved host values (the loop's async-dispatch
    discipline is preserved around them)."""

    # replaces the 【train】 log line: (epoch, gstep, total_step, loss)
    on_log: Optional[Callable[[int, int, int, float], None]] = None
    # replaces Trainer._dev_and_maybe_save at the eval_step cadence: (gstep)
    on_eval: Optional[Callable[[int], None]] = None
    # extra cadence (e.g. TrainerArgs.save_steps) + its callback: (gstep)
    save_every: Optional[int] = None
    on_save: Optional[Callable[[int], None]] = None
    # runs after the completion barrier but BEFORE the wall-clock stops —
    # work that must count toward the reported runtime (e.g. draining async
    # checkpoint writers so every file is durable)
    on_end: Optional[Callable[[], None]] = None
    # Trainer's native end-of-run ritual (save checkpoint / adopt best);
    # False when the caller owns checkpointing (AutoTrainer)
    end_save: bool = True


class Trainer:
    def __init__(
        self,
        args,
        cfg,
        state: Dict,
        train_step: Callable,
        eval_step: Callable,
        put: Optional[Callable] = None,
        multi_step: Optional[Callable] = None,
        put_fused: Optional[Callable] = None,
        pipeline=None,
        tracer=None,
    ):
        self.args = args
        self.cfg = cfg
        self.state = state
        self.train_step = train_step
        self.eval_step = eval_step
        self.put = put or (lambda b: b)
        # K-step fusion (steps.build_multi_step): one dispatch per K
        # optimizer steps; the loader's remainder runs through train_step
        self.multi_step = multi_step
        self.put_fused = put_fused or self.put
        # input pipeline (data.pipeline): when it wraps the loader train()
        # is given, batches arrive ALREADY on device (resident mode:
        # zero steady-state transport; prefetch: double-buffered upload)
        # and the per-step self.put disappears from the hot loop.  Keyed
        # by loader identity so a trainer handed a different loader falls
        # back to the classic put-in-loop path instead of training on the
        # wrong data.
        self.pipeline = pipeline
        # obs span tracer (pdnlp_tpu.obs): --trace configures the process-
        # global tracer here, so EVERY entrypoint that builds a Trainer
        # gets phase spans + the step breakdown + the regression detector
        # without its own wiring.  Disabled (the default) it is a shared
        # no-op object, not a branch in the hot loop.
        from pdnlp_tpu.obs import trace as _trace

        self.tracer = tracer if tracer is not None \
            else _trace.configure_from_args(args)
        # per-phase mean/p50/p95 of the LAST train() call (None untraced) —
        # bench.py --trace embeds it in its JSON
        self.trace_summary = None
        self.best_accuracy = 0.0
        self._best_params = None  # device-held copy; written once at end
        # async resume-snapshot writer (train/async_ckpt.py): the in-loop
        # ckpt_save span pays the device->host snapshot only; serialization
        # + crash-atomic publish ride this writer's thread.  Built lazily
        # on the first in-loop save (--ckpt_async, default on); drained
        # before train() reports its runtime.
        self._ckpt_writer = None
        # len(train_loader) of the active train() call — stamped into every
        # resume snapshot's manifest meta so a restart on a DIFFERENT
        # data-parallel width can remap the saved step counter onto its own
        # steps-per-epoch (elastic-width resume)
        self._steps_per_epoch = None
        # manifest meta of the snapshot load_resume restored (None = fresh)
        self._restored_meta = None
        # (minutes-since-train-start, dev accuracy) per in-loop eval: the
        # time-to-accuracy record bench.py reports (minutes_to_target)
        self.eval_history: list = []
        self._t0: Optional[float] = None
        # device-resident eval batches, keyed by loader identity (the held
        # reference keeps the id stable): the dev set is static across the
        # in-loop evals, so re-uploading it every eval only pays transport
        # (~1 MB/batch x 13 batches x 9 evals over this environment's
        # tunnel); HBM cost is the encoded dev set, ~2 MB at 800 x seq 128
        self._eval_cache: Optional[tuple] = None

    def _eval_params(self):
        """Weights eval/checkpointing use: the EMA tree when the state
        carries one (``--ema_decay``), else the live params."""
        return self.state.get("ema", self.state["params"])

    def _use_pipeline(self, loader) -> bool:
        """The pipeline speaks for ``loader`` only when it wraps that exact
        object (identity-keyed, like the eval cache)."""
        return self.pipeline is not None and self.pipeline.loader is loader

    def _routed_attn(self, seq: int, segmented: bool) -> str:
        """The attention impl a train dispatch at this (static) shape routes
        to — ``ops.attention.routed_impl``, the same decision the traced
        step resolves (memoized at the routing point, so the hot loop pays
        a dict hit)."""
        from pdnlp_tpu.ops.attention import routed_impl_cached

        return routed_impl_cached(
            getattr(self.args, "attention_impl", "auto"), seq,
            segmented=segmented,
            dropout=getattr(self.args, "attn_dropout", 0.0) > 0)

    def _first_device_batch(self, train_loader):
        """One device batch shaped/placed exactly like the hot loop's."""
        if self._use_pipeline(train_loader):
            return self.pipeline.warmup_batch(1)
        host = next(iter(train_loader), None)
        return self.put(host) if host is not None else None

    # -------------------------------------------------- warmup / probe
    def warmup_compile(self, train_loader, dev_loader=None) -> None:
        """AOT-compile the step programs before the timed epoch (the
        warm-CUDA-context analog; ``bench.py`` does the same inline).
        Steps without ``.lower`` (the lazily-built shard_map pipelines)
        compile on their first real call instead — cheap under a warmed
        persistent ``xla_cache``.  ``dev_loader`` supplies the eval step's
        real batch shape (dev_batch_size may differ from train's)."""
        use_pipe = self._use_pipeline(train_loader)
        if use_pipe:
            host = None
            batch = self.pipeline.warmup_batch(1)
        else:
            host = next(iter(train_loader), None)
            batch = self.put(host) if host is not None else None
        if batch is None:
            return
        if hasattr(self.train_step, "lower"):
            self.train_step.lower(self.state, batch).compile()
        if self.multi_step is not None and hasattr(self.multi_step, "lower"):
            k = getattr(self.args, "fuse_steps", 1)
            if use_pipe:
                fused = self.pipeline.warmup_batch(k)
                # a short epoch may have no full K-group to warm against
                if fused is not None and fused["input_ids"].ndim == 3:
                    self.multi_step.lower(self.state, fused).compile()
            else:
                stacked = {key: np.stack([v] * k) for key, v in host.items()}
                self.multi_step.lower(self.state,
                                      self.put_fused(stacked)).compile()
        if self.eval_step is not None and hasattr(self.eval_step, "lower"):
            dev_host = (next(iter(dev_loader), None)
                        if dev_loader is not None else None)
            dev_batch = self.put(dev_host) if dev_host is not None else batch
            self.eval_step.lower(self.state["params"], dev_batch).compile()

    def probe_steps_per_sec(self, train_loader, n: int = 30):
        """Steady-state hot-loop rate: ``n`` re-fed steps on a COPY of the
        state (``train_step`` donates its argument), fetched once — the
        controlled per-strategy speed metric, free of loader/eval/transport
        effects.  Returns None when unsupported (host-offloaded moments:
        ``jnp.copy`` would silently move them on-device and probe a
        different program) — and None, not a crash, when the state copy
        itself OOMs: the copy transiently doubles the state's HBM, so a
        near-capacity config that trains fine must still complete its run
        with ``probe n/a`` rather than die inside the probe."""
        if getattr(self.args, "offload_opt_state", False):
            return None
        batch = self._first_device_batch(train_loader)
        if batch is None:
            return None
        import jax.numpy as jnp

        state = m = None
        try:
            state = jax.tree_util.tree_map(jnp.copy, self.state)
            for _ in range(3):
                state, m = self.train_step(state, batch)
            float(jax.device_get(m["loss"]))
            t0 = time.time()
            for _ in range(n):
                state, m = self.train_step(state, batch)
            float(jax.device_get(m["loss"]))
            dt = time.time() - t0
        except jax.errors.JaxRuntimeError as e:  # RESOURCE_EXHAUSTED et al.
            if "RESOURCE_EXHAUSTED" not in str(e):
                raise
            rank0_print("probe skipped: state copy exceeds device memory")
            return None
        finally:
            del state, m  # release the doubled state promptly
        return n / dt if dt > 0 else None

    def _macro_batches(self, loader, k: int, stage=None):
        """Yield ``(host_batch, n_steps, fused, examples)``: groups of ``k``
        host batches stacked on a leading step axis, remainder as singles.

        Fused groups are assembled into ``stage``'s preallocated ping-pong
        buffers (``data.pipeline._MacroStage``) instead of a fresh
        ``np.stack`` per key per group; the train loop verifies on the
        first fused upload that the uploaded batch does not alias the
        staging memory (identity/zero-copy puts disable reuse) — a yielded
        fused batch is only valid until the next iteration."""
        from pdnlp_tpu.data.pipeline import host_macro_batches

        eff_k = k if self.multi_step is not None else 1
        yield from host_macro_batches(loader, eff_k, stage)

    # ------------------------------------------------------------------ train
    def train(self, train_loader, dev_loader=None,
              hooks: Optional[LoopHooks] = None) -> float:
        """Run ``args.epochs`` epochs; returns wall-clock minutes.

        Elastic hooks (all off by default):  a state restored via
        ``load_resume`` fast-forwards the seeded data order to its step
        counter and continues bitwise; ``args.resume_every`` snapshots full
        state every N steps; ``args.heartbeat_interval`` beats a liveness
        file for the launcher-side ``GangMonitor``.

        ``hooks`` (``LoopHooks``) swaps the log/eval/save behaviors at the
        existing cadences without duplicating the loop — the managed
        ``AutoTrainer`` path runs through here.
        """
        args = self.args
        hooks = hooks or LoopHooks()
        total_step = len(train_loader) * args.epochs
        gstep = 0
        self._steps_per_epoch = len(train_loader)
        # fast-forward: a restored state carries the step it was saved at;
        # the sampler is a seeded permutation, so skipping exactly that many
        # batches replays the identical remaining stream (bitwise resume)
        start_step = int(jax.device_get(self.state["step"]))
        start_step = self._remap_elastic_width(start_step, len(train_loader))
        if start_step > total_step:
            raise ValueError(
                f"restored state is at step {start_step} but this "
                f"configuration trains only {total_step} steps — the "
                "resumed run's epochs/data do not match the saved run's")
        pending: Tuple[int, int, jax.Array] | None = None  # (epoch, gstep, loss)
        last_loss = None
        profiler = Profiler(getattr(args, "profile_dir", None))
        # obs tracing: phase spans feed a per-step breakdown, which feeds
        # the EWMA regression detector (whose smoothed rate rides the
        # heartbeat).  tr is a no-op object when --trace is off — the
        # span/block calls below stay in place unconditionally.
        tr = self.tracer
        breakdown = detector = sampler = None
        if tr.enabled:
            from pdnlp_tpu.obs import (
                MemorySampler, RegressionDetector, StepBreakdown,
            )

            detector = RegressionDetector(
                on_event=lambda ev: rank0_print(f"[obs] {ev}"))
            breakdown = StepBreakdown(on_step=detector.observe)
            tr.add_listener(breakdown.feed)
            # HBM accounting at phase boundaries: the sampler listens for
            # device_block/eval/ckpt_save records and reads the allocator
            # counters (pure host calls — no sync); samples land back in
            # the trace as "hbm" records, so the breakdown table, merged
            # traces and the heartbeat all carry the memory columns.  On
            # backends without memory_stats (CPU) the first sample flips
            # it to a permanent no-op.
            sampler = MemorySampler(tracer=tr)
            tr.add_listener(sampler.feed)
        # live telemetry (--metrics_port / --flight_recorder): Prometheus
        # /metrics + JSON /healthz served off the hot path, plus a bounded
        # flight-recorder JSONL appending snapshots so a SIGKILL'd run
        # still leaves evidence.  Sources snapshot live objects at scrape
        # time; the step loop never sees the exporter.
        exporter = None
        if getattr(args, "metrics_port", 0) \
                or getattr(args, "flight_recorder", None):
            from pdnlp_tpu.obs import memory_snapshot
            from pdnlp_tpu.obs.exporter import build_from_args

            sources = {"memory": (sampler.snapshot if sampler is not None
                                  else memory_snapshot)}
            if breakdown is not None:
                sources["train"] = breakdown.summary
            if self.pipeline is not None \
                    and getattr(self.pipeline, "stats", None) is not None:
                sources["transport"] = self.pipeline.stats.snapshot
            pidx = jax.process_index()
            exporter = build_from_args(
                args, sources, f"flight_proc{pidx}.jsonl",
                process_index=pidx)
            if exporter is not None and exporter.port is not None:
                rank0_print(f"[obs] /metrics + /healthz on "
                            f"http://127.0.0.1:{exporter.port}")
        # the listener must detach even when the loop raises (resume
        # mismatch, fault injection, KeyboardInterrupt): a stale feed
        # on the process-global tracer would double-count every span
        # of the NEXT traced train() in this process
        try:
            fuse = getattr(args, "fuse_steps", 1)
            resume_every = getattr(args, "resume_every", None)
            heartbeat = None
            if getattr(args, "heartbeat_interval", 0) > 0:
                from pdnlp_tpu.parallel.watchdog import Heartbeat

                heartbeat = Heartbeat(args.output_dir, jax.process_index(),
                                      args.heartbeat_interval)
            # chaos hook for the elastic tests: PDNLP_FAULT_STEP kills rank
            # PDNLP_FAULT_PROC at that step — but only on a fresh (non-resumed)
            # incarnation, so the restarted gang survives
            fault_step = int(os.environ.get("PDNLP_FAULT_STEP", "0"))
            fault_proc = int(os.environ.get("PDNLP_FAULT_PROC", "0"))
            examples = 0
            if getattr(args, "warmup_compile", False):
                self.warmup_compile(train_loader, dev_loader)
            if getattr(args, "probe_steps", 0):
                rate = self.probe_steps_per_sec(train_loader, args.probe_steps)
                if rate is not None:
                    rank0_print(f"probe steps/s：{rate:.2f}")
            # the per-step upload route: a pipeline wrapping THIS loader hands
            # over device batches (resident: zero steady-state transport;
            # prefetch: double-buffered upload); otherwise put runs inline (the
            # sync fallback the jaxlint R7 baseline records)
            use_pipe = self._use_pipeline(train_loader)
            stage = None
            if not use_pipe:
                from pdnlp_tpu.data.pipeline import _MacroStage

                stage = _MacroStage(fuse)
            start = time.time()
            self._t0 = start
            for epoch in range(1, args.epochs + 1):
                if gstep + len(train_loader) <= start_step:
                    # resume fast-forward, whole-epoch short-circuit: nothing in
                    # this epoch executes, so don't collate (or, in prefetch
                    # mode, upload) any of its batches — the seeded sampler
                    # makes skipping by count exact
                    gstep += len(train_loader)
                    if heartbeat is not None:
                        heartbeat.beat(step=gstep)
                    continue
                if use_pipe:
                    self.pipeline.set_epoch(epoch - 1)
                    groups = self.pipeline.macro_batches(
                        fuse if self.multi_step is not None else 1)
                else:
                    train_loader.set_epoch(epoch - 1)
                    groups = self._macro_batches(train_loader, fuse, stage)
                # data_wait: host time blocked obtaining each group (collation,
                # the prefetch queue, or the resident gather dispatch)
                groups = tr.wrap_iter("data_wait", groups)
                for batch, n, fused, n_examples in groups:
                    if gstep + n <= start_step:  # already done before the restart
                        gstep += n
                        if heartbeat is not None:  # long fast-forwards stay live
                            heartbeat.beat(step=gstep)
                        continue
                    if gstep < start_step:
                        # the restored step falls inside this fused group:
                        # executing it would re-apply updates the restored
                        # optimizer state already contains
                        raise ValueError(
                            f"resume step {start_step} is not a fused-group "
                            f"boundary under fuse_steps={fuse} (group covers "
                            f"steps {gstep + 1}..{gstep + n}) — resume with the "
                            "fuse_steps the snapshot was saved under, or 1")
                    if fault_step and start_step == 0 and gstep >= fault_step \
                            and jax.process_index() == fault_proc:
                        if os.environ.get("PDNLP_FAULT_KIND") == "sigkill":
                            # the preemption shape: no atexit, no stdio
                            # flush, no collective teardown — peers wedge
                            # in their next collective until the gang
                            # supervisor notices the corpse
                            import signal

                            os.kill(os.getpid(), signal.SIGKILL)
                        os._exit(13)
                    # bucket attr on the dispatch/block spans: the obs
                    # breakdown splits step phases per token width, so a
                    # bucketed run's phase table shows where each bucket's
                    # time goes (int() — shape dims must not leak numpy
                    # scalars into span attrs)
                    seq = int(batch["input_ids"].shape[-1])
                    # the attention impl this dispatch actually routes to
                    # (ops.attention.routed_impl — the same decision the
                    # traced step makes), stamped on the dispatch span so
                    # pallas adoption is visible in trace_tpu.py summarize
                    impl = self._routed_attn(seq, "segment_ids" in batch)
                    if fused:
                        if use_pipe:
                            dev = batch
                        else:
                            with tr.span("h2d_put", step=gstep + n):
                                dev = self.put_fused(batch)
                            if stage is not None:
                                stage.verify(batch, dev)  # aliasing guard, once
                        with tr.span("step_dispatch", step=gstep + n, n=n,
                                     bucket=seq, attn_impl=impl):
                            self.state, metrics = self.multi_step(self.state, dev)
                        last_loss = metrics["loss"][-1]
                    else:
                        if use_pipe:
                            dev = batch
                        else:
                            with tr.span("h2d_put", step=gstep + n):
                                dev = self.put(batch)
                        with tr.span("step_dispatch", step=gstep + n, n=n,
                                     bucket=seq, attn_impl=impl):
                            self.state, metrics = self.train_step(self.state, dev)
                        last_loss = metrics["loss"]
                    # traced runs attribute device time to a separate
                    # device_block span (dispatch above measured enqueue only);
                    # untraced runs keep the async discipline — block is a
                    # no-op on a disabled tracer, never a hidden barrier
                    tr.block(last_loss, step=gstep + n, n=n, bucket=seq)
                    prev = gstep
                    gstep += n
                    examples += n_examples
                    profiler.step(gstep)
                    if heartbeat is not None:
                        heartbeat.beat(
                            step=gstep,
                            steps_per_sec=detector.steps_per_sec
                            if detector is not None else None,
                            **(sampler.beat_payload()
                               if sampler is not None else {}))
                    if resume_every and gstep // resume_every != prev // resume_every:
                        # async (default): the span covers the device->host
                        # snapshot + enqueue only — serialization and disk
                        # ride the writer thread (drained in ckpt_wait)
                        with tr.span("ckpt_save", step=gstep):
                            self._snapshot_resume(args.resume_path())
                    if gstep // args.log_every != prev // args.log_every:
                        if pending is not None:  # print the *previous* line's loss:
                            e, s, l = pending     # it is done by now — no sync stall
                            with tr.span("log", step=gstep):
                                if hooks.on_log is not None:
                                    hooks.on_log(e, s, total_step, float(l))
                                else:
                                    rank0_print(fmt_train(
                                        e, args.epochs, s, total_step, float(l)))
                        pending = (epoch, gstep, last_loss)
                    # boundary-crossing, not equality: with fuse_steps=K the
                    # counter advances K at a time, so when K does not divide
                    # eval_step the eval lands up to K-1 steps late (count per
                    # epoch preserved).  Pick eval_step divisible by fuse_steps
                    # (bench.py: 48 under K=4) for exact reference cadence;
                    # AutoTrainer instead rejects non-divisible combinations.
                    if dev_loader is not None and args.dev and \
                            gstep // args.eval_step != prev // args.eval_step:
                        with tr.span("eval", step=gstep):
                            if hooks.on_eval is not None:
                                hooks.on_eval(gstep)
                            else:
                                self._dev_and_maybe_save(dev_loader)
                    if hooks.save_every and hooks.on_save is not None and \
                            gstep // hooks.save_every != prev // hooks.save_every:
                        hooks.on_save(gstep)
            if pending is not None:
                e, s, l = pending
                if hooks.on_log is not None:
                    hooks.on_log(e, s, total_step, float(l))
                else:
                    rank0_print(fmt_train(e, args.epochs, s, total_step, float(l)))
            # True completion barrier: fetch a VALUE from the last enqueued
            # program.  Device programs execute in order, so the fetch cannot
            # return before every prior step has run.  block_until_ready alone
            # is not trustworthy on async-RPC device tunnels (observed on the
            # 'axon' TPU platform: it returns at enqueue, not completion).
            if last_loss is not None:
                float(jax.device_get(last_loss))
            jax.block_until_ready(self.state["params"])
            # durability drain: every in-flight async snapshot must be
            # published before the run reports its runtime (a preempted
            # host loses unflushed saves; a finished run must not).  Off
            # the step loop by construction — its own ckpt_wait phase, so
            # the in-loop ckpt_save p95 budget stays honest.
            if self._ckpt_writer is not None:
                with tr.span("ckpt_wait", step=gstep):
                    self._ckpt_writer.wait()
            profiler.close()
        finally:
            if breakdown is not None:
                tr.remove_listener(breakdown.feed)
            if sampler is not None:
                tr.remove_listener(sampler.feed)
            if exporter is not None:
                # final flight-recorder snapshot + shutdown on EVERY exit
                # path: a run that raises must still leave its last
                # metrics line on disk
                try:
                    exporter.stop(final_flight=True)
                except Exception:
                    pass
            if self._ckpt_writer is not None:
                # exception path: best-effort drain (bounded) so the newest
                # snapshot survives the failure; errors here must not mask
                # the original exception
                try:
                    self._ckpt_writer.wait(timeout=60.0)
                except Exception:
                    pass
            if breakdown is not None:
                # crash-path flush: the ring + summary land on disk from
                # the finally, so a raising train() (fault injection,
                # preemption, resume mismatch) never silently loses its
                # last steps' spans.  Guarded — telemetry flushing must
                # not mask the original exception — but a flush failure
                # is PRINTED, never swallowed: on a clean run a disk-full
                # OSError here would otherwise surface later as a
                # confusing missing trace_summary.
                try:
                    from pdnlp_tpu.obs import format_table

                    breakdown.close()
                    self.trace_summary = breakdown.summary()
                    path = tr.flush()
                    rank0_print("[obs] phase breakdown:\n"
                                + format_table(self.trace_summary)
                                + (f"\n[obs] spans -> {path}"
                                   if path else ""))
                except Exception as flush_err:  # noqa: BLE001
                    rank0_print(f"WARNING: trace flush failed: "
                                f"{type(flush_err).__name__}: {flush_err}")
        if hooks.on_end is not None:
            hooks.on_end()  # durability work that must count in the runtime
        minutes = (time.time() - start) / 60
        rank0_print(fmt_elapsed_minutes(minutes))
        rank0_print(StepStats(gstep, examples, minutes).line())
        if not hooks.end_save:
            pass  # the caller owns checkpointing (AutoTrainer)
        elif not args.dev:
            self._save(args.ckpt_path())
        elif self._best_params is not None:
            # adopt + persist the best-of-epoch params (the reference's
            # best-checkpoint ritual; its test.py then evaluates that file).
            # Under EMA the snapshot IS averaged weights — both trees adopt
            # it so the post-train test() evaluates exactly what was saved.
            self.state["params"] = self._best_params
            if "ema" in self.state:
                # distinct copy — assigning the same tree would alias the
                # buffers and a further donated train step would invalidate
                # both references
                self.state["ema"] = jax.tree_util.tree_map(
                    jax.numpy.copy, self._best_params)
            ckpt.save_params(args.ckpt_path(), {"params": self._best_params})
        return minutes

    def _dev_and_maybe_save(self, dev_loader) -> None:
        """Eval; keep the best params (the reference checkpoints to disk on
        every improvement INSIDE the timed loop, ``multi-gpu-distributed-
        cls.py:183-192`` — here the best copy stays in HBM and one write
        happens after training, same end state without serializing the epoch
        behind checkpoint I/O)."""
        loss, acc = self.dev(dev_loader)
        rank0_print(fmt_dev(loss, acc))
        if self._t0 is not None:
            # dev() fetched values, so every prior train step has completed:
            # the elapsed time honestly covers the compute that produced acc
            self.eval_history.append(
                {"minutes": (time.time() - self._t0) / 60, "accuracy": acc})
        if acc > self.best_accuracy:
            self.best_accuracy = acc
            # jnp.copy: the live params are donated buffers; the copy is
            # ours.  With EMA enabled the averaged weights ARE the model
            # being evaluated, so they are what "best" snapshots.
            self._best_params = jax.tree_util.tree_map(
                jax.numpy.copy, self._eval_params())
            rank0_print(fmt_best(acc))

    def _save(self, path: str) -> None:
        # all processes enter (consolidate is collective); rank 0 writes
        ckpt.save_params(path, {"params": self._eval_params()})

    # ---------------------------------------------------------------- resume
    def _resume_meta(self) -> Dict:
        """Manifest meta stamped on every resume snapshot: the saved step
        and (when a train() is active) this width's steps-per-epoch — what
        an elastic restart at a DIFFERENT data-parallel width needs to
        remap the data position."""
        meta: Dict = {"step": int(jax.device_get(self.state["step"]))}
        if self._steps_per_epoch:
            meta["steps_per_epoch"] = int(self._steps_per_epoch)
        return meta

    def _resume_writer(self):
        """The lazily built async snapshot writer, or None when the run
        opted back into synchronous saves (``--ckpt_async false``)."""
        if not getattr(self.args, "ckpt_async", True):
            return None
        if self._ckpt_writer is None:
            from pdnlp_tpu.train.async_ckpt import AsyncCheckpointer

            self._ckpt_writer = AsyncCheckpointer()
        return self._ckpt_writer

    def _snapshot_resume(self, path: str) -> None:
        """The in-loop resume snapshot: device→host copy here (inside the
        caller's ``ckpt_save`` span), serialization + crash-atomic publish
        on the async writer's thread — the step loop never blocks on disk,
        and at most one save is in flight (``train/async_ckpt.py``).
        ``--ckpt_async false`` falls back to the synchronous
        :meth:`save_resume`."""
        writer = self._resume_writer()
        if writer is None:
            self.save_resume(path)
            return
        meta = self._resume_meta()
        writer.submit(path, ckpt.snapshot(self.state), meta=meta)
        if self._best_params is not None:
            writer.submit(path + "-best", ckpt.snapshot(self._best_params))
            writer.submit_json(path + "-best.json",
                               {"best_accuracy": self.best_accuracy})

    def save_resume(self, path: str) -> None:
        """Full mid-training snapshot: params + optimizer moments + step +
        RNG, published crash-atomically with a checksum manifest.  The
        reference cannot resume (``SURVEY.md`` §5: no optimizer state
        saving anywhere); this framework can, bitwise.

        The best-of-epoch tracker rides along in sidecar files (``<path>``
        + ``-best``/``-best.json``) so an elastic restart cannot regress the
        shipped best model to a later, worse eval."""
        ckpt.save_state(path, self.state, meta=self._resume_meta())
        if self._best_params is not None:
            ckpt.save_params(path + "-best", {"params": self._best_params})
            if jax.process_index() == 0:
                ckpt.write_json_atomic(path + "-best.json",
                                       {"best_accuracy": self.best_accuracy})

    def load_resume(self, path: str) -> None:
        """Restore a resume snapshot onto the LIVE state's shardings.

        The file always holds fully consolidated host arrays
        (``checkpoint.save`` all-gathers shards before writing), so this is
        consolidate-then-reshard by construction: whatever data-parallel
        width and sharding mode the live state was built with —
        including a width different from the one that saved the snapshot —
        ``_put_like`` re-places every leaf (params AND Adam moments) onto
        the live ``parallel/sharding.py`` specs.  A corrupt file falls back
        to the retained previous snapshot (``checkpoint.read_verified``)
        with a loud warning."""
        raw, meta, used = ckpt.read_verified(path)
        restored = ckpt.from_restored(raw, self.state, path=used)
        self.state = _put_like(restored, self.state)
        self._restored_meta = dict(meta) if meta else {}
        if os.path.exists(path + "-best"):
            # sidecar corruption must not fail the restore: the MAIN state
            # is already valid and adopted — degrade to fresh best-tracking
            # with a loud warning instead of reporting "from scratch"
            try:
                best = ckpt.load_params(path + "-best", self.state["params"])
                import json

                with open(path + "-best.json") as f:
                    acc = json.load(f)["best_accuracy"]
            except (ckpt.CorruptCheckpointError, OSError, ValueError,
                    KeyError):
                rank0_print(f"WARNING: {path}-best sidecar missing/corrupt "
                            "— main state restored; best-accuracy tracking "
                            "restarts from the restored weights")
            else:
                self._best_params = _put_like(best, self.state["params"])
                self.best_accuracy = acc

    def _remap_elastic_width(self, start_step: int, spe: int) -> int:
        """Map a restored step counter onto THIS run's steps-per-epoch.

        Same width (or fresh start): identity — resume stays bitwise.  A
        snapshot saved under a different data-parallel width carries its
        ``steps_per_epoch`` in the manifest meta; the data position then
        continues by EPOCH FRACTION (ceil: examples the old optimizer
        already consumed are never re-applied; at most one new-width
        batch's worth of rows is skipped instead).  The on-device step
        counter is rebased to the remapped value so subsequent snapshots,
        fast-forward math, and log lines all speak this width's units.
        Optimizer state (Adam moments + count) is restored exactly —
        elastic resume changes the data layout, never the training math
        already done."""
        meta, self._restored_meta = (self._restored_meta or {}), None
        old_spe = meta.get("steps_per_epoch")
        if not start_step or not old_spe or old_spe == spe:
            return start_step
        remapped = -(-start_step * spe // old_spe)  # ceil
        fuse = getattr(self.args, "fuse_steps", 1)
        if self.multi_step is not None and fuse > 1:
            # resume must land on a fused-group boundary (train() rejects
            # interior steps); round up — same skip-don't-replay policy
            remapped = -(-remapped // fuse) * fuse
        rank0_print(
            f"elastic resume: remapped step {start_step} (of {old_spe}/epoch "
            f"at save time) -> {remapped} (of {spe}/epoch at this width); "
            "data position continues by epoch fraction, optimizer state is "
            "exact")
        like = self.state["step"]
        self.state["step"] = _put_like(
            np.asarray(remapped, dtype=getattr(like, "dtype", np.int32)), like)
        return remapped

    # ------------------------------------------------------------------- eval
    def _evaluate(self, loader, collect_preds: bool,
                  static_eval: bool = True) -> Dict:
        # Dispatch the whole pass first, fetch once at the end: a per-batch
        # float() would serialize host and device through the dev set (the
        # train loop's async-dispatch treatment, applied to eval).
        if not static_eval:
            # shuffling/augmenting loader: re-upload THIS iteration's
            # batches and leave the identity-keyed cache untouched (a
            # static loader used elsewhere keeps its device copy)
            batches = [self.put(b) for b in loader]
        else:
            if self._eval_cache is None or self._eval_cache[0] is not loader:
                self._eval_cache = (loader, [self.put(b) for b in loader])
            batches = self._eval_cache[1]
        pending = [self.eval_step(self._eval_params(), batch)
                   for batch in batches]
        fetched = jax.device_get(pending)
        y_true, y_pred = [], []
        loss_sum = weight = correct = 0.0
        for m in fetched:
            loss_sum += float(m["loss_sum"])
            weight += float(m["weight"])
            correct += float(m["correct"])
            if collect_preds:
                real = np.asarray(m["ew"]) > 0  # drop filler rows
                y_pred.extend(np.asarray(m["pred"])[real].tolist())
                y_true.extend(np.asarray(m["label"])[real].tolist())
        weight = max(weight, 1.0)
        return {"loss": loss_sum / weight, "accuracy": correct / weight,
                "y_true": y_true, "y_pred": y_pred}

    def dev(self, loader, static_eval: bool = True) -> Tuple[float, float]:
        """(weighted mean loss, accuracy) over the dev set.

        ``static_eval=True`` (default) caches the eval batches on device
        keyed by loader IDENTITY (``_evaluate``), so the loader must yield
        the same batches on every iteration — the shipped ``shuffle=False``
        dev loaders satisfy this, and the in-loop eval cadence then pays
        upload transport once instead of per eval.  A shuffling or
        augmenting loader would be silently evaluated on its FIRST
        iteration's frozen batches forever: pass ``static_eval=False`` for
        such loaders to re-upload fresh batches on every call (the cache,
        if any, is left untouched).
        """
        r = self._evaluate(loader, collect_preds=False,
                           static_eval=static_eval)
        return r["loss"], r["accuracy"]

    def test(self, loader, static_eval: bool = True) -> Dict:
        """Eval + predictions: feeds the classification report
        (``/root/reference/test.py:144-170``).

        Shares ``dev()``'s device-side batch cache and therefore its
        static-content requirement: the loader must yield identical batches
        on every iteration, unless ``static_eval=False`` (see :meth:`dev`).
        """
        return self._evaluate(loader, collect_preds=True,
                              static_eval=static_eval)


def _shardings_of(state):
    """Current sharding tree of a live state (resume re-places restored host
    arrays exactly where the originals lived — replicated or ZeRO-sharded)."""
    return jax.tree_util.tree_map(
        lambda x: x.sharding if isinstance(x, jax.Array) else None, state)


def _put_like(host_tree, live_tree):
    """Place a restored host tree onto the live tree's shardings.

    Single-process shardings are fully addressable and go through
    ``device_put``.  Multi-process shardings span other hosts' devices, which
    plain ``device_put`` refuses — every process read the same snapshot, so
    each materializes its own addressable shards of the global array
    (``make_array_from_callback`` slices the host copy per shard)."""
    shardings = _shardings_of(live_tree)
    if all(getattr(s, "is_fully_addressable", True)
           for s in jax.tree_util.tree_leaves(shardings)):
        return jax.device_put(host_tree, shardings)

    def put(x, sh):
        if jax.dtypes.issubdtype(getattr(x, "dtype", np.float32),
                                 jax.dtypes.prng_key):
            data = np.asarray(jax.random.key_data(x))
            g = jax.make_array_from_callback(
                data.shape, sh, lambda idx: data[idx])
            return jax.random.wrap_key_data(g, impl=jax.random.key_impl(x))
        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, sh, lambda idx: arr[idx])

    return jax.tree_util.tree_map(put, host_tree, shardings)
