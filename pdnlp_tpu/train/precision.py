"""Mixed-precision policy — the TPU answer to ``torch.cuda.amp``.

The reference's AMP variant wraps forward/backward in ``autocast`` with a
dynamic ``GradScaler`` (``/root/reference/multi-gpu-distributed-mp-amp-cls.py:
160-175``).  On TPU the equivalent is simply computing in bfloat16: bf16 has
fp32's exponent range, so there is nothing to underflow and **no loss scaler
is needed** — master params stay fp32, matmuls/activations run bf16 on the
MXU, softmax/LayerNorm reduce fp32, logits and the loss come back fp32.
``--dtype bfloat16`` is therefore the whole AMP feature.
"""
from __future__ import annotations

import jax.numpy as jnp

_DTYPES = {
    "float32": jnp.float32,
    "f32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "bf16": jnp.bfloat16,
    "float16": jnp.float16,  # accepted for parity; bf16 is the TPU choice
}


def resolve_dtype(name) -> jnp.dtype:
    if not isinstance(name, str):
        return name
    try:
        return _DTYPES[name.lower()]
    except KeyError:
        raise ValueError(f"unknown dtype {name!r}; use one of {sorted(_DTYPES)}")
