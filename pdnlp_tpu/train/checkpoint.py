"""Checkpoint save/load (msgpack over pytrees).

Reference behavior being covered:
- rank-0 ``torch.save(model.state_dict())`` at end / on best dev accuracy
  (``/root/reference/multi-gpu-distributed-cls.py:192,196-197``);
- loading with the ``module.``-prefix strip (``/root/reference/test.py:96-101``)
  — a non-problem here because pytree keys never grow wrapper prefixes;
- DeepSpeed's sharded engine checkpoints + ``zero_to_fp32.py`` consolidation
  (``/root/reference/README.md:481-485``) — covered by ``consolidate``, which
  all-gathers sharded ``jax.Array`` leaves to host numpy before serializing,
  so a ZeRO-sharded run writes the same single-file format as a single-chip
  run and every checkpoint loads everywhere.

Beyond the reference: ``save_state`` persists optimizer state + step + RNG
key, enabling true mid-training resume (the reference cannot resume).
"""
from __future__ import annotations

import os
import re
from typing import Any, Dict, Optional

import jax
import numpy as np
from flax import serialization


def consolidate(tree):
    """Fetch every leaf to host numpy (all-gathering sharded leaves).

    Single-process sharded arrays are fully addressable and fetch directly;
    multi-process shards (some devices belong to other hosts) go through
    ``multihost_utils.process_allgather`` so every host sees the full value.
    """
    def gather(x):
        if isinstance(x, jax.Array) and not getattr(x, "is_fully_addressable", True):
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return x

    gathered = jax.tree_util.tree_map(gather, tree)
    # one batched transfer for everything still on device: device_get
    # pipelines the copies, where per-leaf np.asarray round-trips the
    # (possibly tunneled) transport once per leaf
    return jax.device_get(gathered)


def _wrap_rng(tree: Dict[str, Any]) -> Dict[str, Any]:
    """PRNG key arrays don't serialize; store key_data (rewrapped in load)."""
    out = dict(tree)
    if "rng" in out:
        out["rng"] = jax.random.key_data(out["rng"])
    return out


def save(path: str, tree) -> None:
    """Consolidate + write.

    EVERY process must call this (consolidate runs a collective all-gather
    for cross-host shards); only process 0 touches the filesystem — the
    rank-0-writes split of ``multi-gpu-distributed-cls.py:192,196-197``
    without its deadlock risk.
    """
    data_tree = consolidate(_wrap_rng(tree) if isinstance(tree, dict) else tree)
    if jax.process_index() != 0:
        return
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    data = serialization.to_bytes(data_tree)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)  # atomic: a crash never leaves a torn checkpoint


def load(path: str, like) -> Any:
    """Restore a pytree with the structure/dtypes of ``like``.

    Raises ``ValueError`` on leaf-shape mismatch — flax ``from_bytes`` does
    not validate shapes, which would defer the failure to an opaque XLA
    error at the next forward pass (e.g. loading a ``bert-tiny`` checkpoint
    into a ``bert-base`` template).
    """
    with open(path, "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    return from_restored(raw, like, path=path)


def from_restored(raw, like, *, path: str = "<restored>") -> Any:
    """:func:`load`'s template fit + shape validation applied to an
    already-restored raw tree (:func:`load_raw`'s output) — consumers that
    must inspect the raw tree first (the serve engine probes for int8
    ``qscale`` leaves) pay ONE file read + msgpack decode, not two.
    ``path`` only labels error messages."""
    template = _wrap_rng(like) if isinstance(like, dict) and "rng" in like else like
    restored = serialization.from_state_dict(template, raw)
    got_leaves = jax.tree_util.tree_leaves(restored)
    want = jax.tree_util.tree_leaves_with_path(template)
    got_shapes = [getattr(l, "shape", None) for l in got_leaves]
    want_shapes = [getattr(l, "shape", None) for _, l in want]
    if got_shapes != want_shapes:
        (keypath, _), bad_got, bad_want = next(
            (w, g, ws) for w, g, ws in zip(want, got_shapes, want_shapes)
            if g != ws)
        leaf = jax.tree_util.keystr(keypath)
        # a [2]u32-vs-[4]u32 *rng* leaf means the checkpoint was saved under
        # a different PRNG impl (threefry2x32 vs rbg), not a different model
        if "rng" in leaf and {bad_got, bad_want} <= {(2,), (4,)}:
            raise ValueError(
                f"checkpoint {path!r} stores an RNG key of a different PRNG "
                f"impl than the current --rng_impl (key_data {bad_got} vs "
                f"{bad_want}: threefry2x32 is [2]u32, rbg is [4]u32) — rerun "
                "with the --rng_impl it was saved under")
        raise ValueError(
            f"checkpoint {path!r} does not match the model template: "
            f"leaf {leaf} has shape {bad_got} vs expected {bad_want}")
    if isinstance(restored, dict) and "rng" in restored and isinstance(like, dict):
        restored = dict(restored)
        # rewrap with the template key's impl (rbg key_data is [4]u32,
        # threefry [2]u32 — default wrap would mis-type an rbg stream)
        restored["rng"] = jax.random.wrap_key_data(
            restored["rng"], impl=jax.random.key_impl(like["rng"]))
    return restored


def load_raw(path: str) -> Any:
    """Template-free restore: the checkpoint's raw pytree as host numpy.

    The read-only half of :func:`load` for consumers that have no model
    template yet — the serving engine peeks a checkpoint's leaf shapes to
    fail fast on a model mismatch BEFORE paying device transfer, and the
    ``serve_tpu.py`` CLI prints what a file contains.  Never use this to
    feed a forward pass directly; :func:`load` (shape-validated against the
    model template) is the loading path.
    """
    with open(path, "rb") as f:
        return serialization.msgpack_restore(f.read())


def save_params(path: str, state: Dict[str, Any]) -> None:
    """Model-only checkpoint — the ``state_dict`` analog used by test/predict."""
    save(path, state["params"])


def load_params(path: str, like_params) -> Any:
    return load(path, like_params)


def save_state(path: str, state: Dict[str, Any]) -> None:
    """Full resume checkpoint: params + opt_state + step + rng."""
    save(path, state)


def load_state(path: str, like_state: Dict[str, Any]) -> Dict[str, Any]:
    return load(path, like_state)


_STEP_RE = re.compile(r"[-_.](\d+)$")


def _filename_step(path: str, pattern: str) -> Optional[tuple]:
    """``(stem, step)`` for a step-family checkpoint name — a TRAILING
    integer set off by ``-``/``_``/``.`` right before the suffix
    (``ckpt-1500.msgpack`` -> ``("ckpt", 1500)``) — or None.  Interior or
    attached digits are NOT steps: ``zero2-cls`` and ``pretrained-e5``
    name a strategy and an epoch tag, not a step counter."""
    base = os.path.basename(path)
    if base.endswith(pattern):
        base = base[:len(base) - len(pattern)]
    m = _STEP_RE.search(base)
    return (base[:m.start()], int(m.group(1))) if m else None


def latest(output_dir: str, pattern: str = ".msgpack") -> Optional[str]:
    """Newest checkpoint in a directory, or None.

    mtime alone is the wrong order key twice over: coarse-mtime
    filesystems tie checkpoints written within the same second, and a
    ``cp -p`` restore resurrects old timestamps wholesale — after which
    "newest mtime" silently serves a stale file.  When every candidate
    belongs to ONE step family (same stem, trailing ``-<step>`` before
    the suffix), the step ORDERS them (mtime only breaks step ties);
    any mixed-family directory falls back to mtime with deterministic
    name tie-breaks, so `pretrained-e5.msgpack` can never outrank a
    newer `zero2-cls.msgpack` on its epoch digit.

    Deliberate consequence: within one family the highest STEP wins even
    when a lower-step file is newer on disk — a reused output_dir whose
    new run restarts the step counter should be cleaned (or given a new
    dir) first, the same contract resume already has.
    """
    if not os.path.isdir(output_dir):
        return None
    cands = [os.path.join(output_dir, f) for f in os.listdir(output_dir)
             if f.endswith(pattern)]
    if not cands:
        return None
    steps = {c: _filename_step(c, pattern) for c in cands}
    if all(s is not None for s in steps.values()) \
            and len({s[0] for s in steps.values()}) == 1:
        return max(cands, key=lambda c: (steps[c][1], os.path.getmtime(c)))
    return max(cands, key=lambda c: (os.path.getmtime(c),
                                     steps[c][1] if steps[c] else -1,
                                     os.path.basename(c)))
