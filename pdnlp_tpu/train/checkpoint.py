"""Checkpoint save/load (msgpack over pytrees).

Reference behavior being covered:
- rank-0 ``torch.save(model.state_dict())`` at end / on best dev accuracy
  (``/root/reference/multi-gpu-distributed-cls.py:192,196-197``);
- loading with the ``module.``-prefix strip (``/root/reference/test.py:96-101``)
  — a non-problem here because pytree keys never grow wrapper prefixes;
- DeepSpeed's sharded engine checkpoints + ``zero_to_fp32.py`` consolidation
  (``/root/reference/README.md:481-485``) — covered by ``consolidate``, which
  all-gathers sharded ``jax.Array`` leaves to host numpy before serializing,
  so a ZeRO-sharded run writes the same single-file format as a single-chip
  run and every checkpoint loads everywhere.

Beyond the reference: ``save_state`` persists optimizer state + step + RNG
key, enabling true mid-training resume (the reference cannot resume).

Durability contract (what a PUBLISHED snapshot promises):

- every write is crash-atomic — bytes land in ``<path>.tmp`` and are
  ``os.replace``d into place, so a reader can never observe a torn file;
- every publish also writes ``<path>.manifest.json`` (atomically, after the
  data) carrying the file's byte count and CRC32 — :func:`load` re-verifies
  both, so silent truncation/corruption (host crash before the page cache
  drained, disk-full, bit rot) is DETECTED instead of surfacing as an
  opaque msgpack error three layers later;
- the previously published snapshot survives as ``<path>.prev`` (retained
  via hardlink before the new data replaces ``path``) — a verified-corrupt
  ``path`` falls back to it with a loud warning instead of crashing the
  resume, losing at most one snapshot interval of progress.

The split :func:`snapshot` (device→host, collective) / :func:`publish`
(serialize + atomic write, host-only) is what the async checkpointer
(``train/async_ckpt.py``) builds on: the step loop pays only the snapshot,
the writer thread pays the rest.
"""
from __future__ import annotations

import os
import re
import shutil
import sys
import zlib
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from flax import serialization


class CorruptCheckpointError(RuntimeError):
    """A checkpoint file failed manifest verification or msgpack decoding —
    distinct from a *template mismatch* (``ValueError``), which means the
    file is fine but belongs to a different model."""


def consolidate(tree):
    """Fetch every leaf to host numpy (all-gathering sharded leaves).

    Single-process sharded arrays are fully addressable and fetch directly;
    multi-process shards (some devices belong to other hosts) go through
    ``multihost_utils.process_allgather`` so every host sees the full value.
    """
    def gather(x):
        if isinstance(x, jax.Array) and not getattr(x, "is_fully_addressable", True):
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return x

    gathered = jax.tree_util.tree_map(gather, tree)
    # one batched transfer for everything still on device: device_get
    # pipelines the copies, where per-leaf np.asarray round-trips the
    # (possibly tunneled) transport once per leaf
    return jax.device_get(gathered)


def _wrap_rng(tree: Dict[str, Any]) -> Dict[str, Any]:
    """PRNG key arrays don't serialize; store key_data (rewrapped in load)."""
    out = dict(tree)
    if "rng" in out:
        out["rng"] = jax.random.key_data(out["rng"])
    return out


def snapshot(tree) -> Any:
    """Device→host copy of a checkpointable tree — the ONLY part of a save
    the step loop must pay.  Collective when the tree holds cross-host
    shards (every process must call it); the returned host tree is plain
    numpy and safe to serialize on any thread."""
    return consolidate(_wrap_rng(tree) if isinstance(tree, dict) else tree)


def manifest_path(path: str) -> str:
    return path + ".manifest.json"


def prev_path(path: str) -> str:
    """Where the previously published snapshot is retained for fallback."""
    return path + ".prev"


def _atomic_write_bytes(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic: a crash never leaves a torn file


def write_json_atomic(path: str, obj) -> None:
    """Crash-atomic JSON sidecar write (tmp + ``os.replace``) — the same
    no-torn-reads contract as checkpoint publishes, for the small metadata
    files that ride along (``-best.json``, trainer state)."""
    import json

    _atomic_write_bytes(path, json.dumps(obj, indent=2).encode("utf-8"))


def _retain_prev(path: str) -> None:
    """Keep the currently published ``path`` (and its manifest) reachable as
    ``path.prev`` before the new data replaces it.  Hardlink where the
    filesystem allows (free, and ``path`` itself is never absent during the
    publish); copy as the fallback."""
    for src in (path, manifest_path(path)):
        if not os.path.exists(src):
            continue
        dst = prev_path(path) if src == path else manifest_path(prev_path(path))
        tmp = dst + ".tmp"
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
            os.link(src, tmp)
        except OSError:
            shutil.copyfile(src, tmp)
        os.replace(tmp, dst)


def publish(path: str, data: bytes, meta: Optional[Dict] = None) -> None:
    """Crash-atomically publish one checkpoint file + its manifest.

    Order matters: retain the previous snapshot, replace the data, then
    replace the manifest.  A crash at ANY point leaves a loadable state —
    either the old (data+manifest) pair, or new data whose stale manifest
    fails verification and routes :func:`load` to the retained ``.prev``.
    Only a completed publish (new data + matching manifest) supersedes the
    previous snapshot.  ``meta`` (e.g. step / steps-per-epoch at save time)
    is carried in the manifest, not the msgpack payload, so readers can
    inspect it without decoding the full state."""
    import json

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # retain ONLY a still-verifying pair: after a torn publish (new data,
    # stale manifest) the retained .prev is the one loadable snapshot —
    # overwriting it with the corrupt pair would leave zero on a second
    # crash in the same window
    if os.path.exists(path) and _manifest_matches(path):
        _retain_prev(path)
    _atomic_write_bytes(path, data)
    crc = zlib.crc32(data) & 0xFFFFFFFF
    man = {"version": 1, "file": os.path.basename(path), "bytes": len(data),
           "crc32": crc}
    if meta:
        man["meta"] = dict(meta)
    _atomic_write_bytes(manifest_path(path),
                        json.dumps(man, indent=2).encode("utf-8"))
    _published_crc[path] = (len(data), crc)


def load_manifest(path: str) -> Optional[Dict]:
    """The manifest published alongside ``path``, or None (pre-manifest
    file).  An UNDECODABLE manifest raises ``ValueError`` (json's decode
    error) — the verified readers convert that to
    :class:`CorruptCheckpointError` so a bit-rotted manifest routes to the
    ``.prev`` fallback instead of crashing the caller raw."""
    import json

    try:
        with open(manifest_path(path)) as f:
            return json.load(f)
    except OSError:
        return None


#: (bytes, crc32) of the last pair THIS process published per path — lets
#: the retention guard trust its own completed publishes from the manifest
#: alone instead of re-reading + re-CRCing the full previous state file
#: (hundreds of MB at scale) on every save
_published_crc: Dict[str, Tuple[int, int]] = {}


def _manifest_matches(path: str) -> bool:
    """No-msgpack-decode check that ``path``'s bytes agree with its
    manifest — the retention guard: only a pair that still verifies may
    overwrite the previous ``.prev``.  A legacy file without a manifest
    passes (nothing to disagree with).  When the manifest equals the pair
    this process last published to ``path``, the data file is NOT re-read
    — publish completed, so the bytes on disk are the ones the manifest
    describes; only the first publish of a path (unknown provenance) pays
    the full read + CRC."""
    try:
        man = load_manifest(path)
    except ValueError:
        return False
    if man is None:
        return True
    if not isinstance(man, dict):
        return False
    if _published_crc.get(path) == (man.get("bytes"), man.get("crc32")):
        return True
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    return (man.get("bytes") == len(data)
            and man.get("crc32") == (zlib.crc32(data) & 0xFFFFFFFF))


def discard(path: str) -> None:
    """Remove a snapshot and every artifact the publish protocol leaves
    around it (manifest, retained ``.prev`` + its manifest, stray tmps) —
    the elastic launcher's stale-state cleanup."""
    for p in (path, manifest_path(path), prev_path(path),
              manifest_path(prev_path(path))):
        for q in (p, p + ".tmp"):
            if os.path.exists(q):
                os.remove(q)


def save(path: str, tree, meta: Optional[Dict] = None) -> None:
    """Consolidate + atomically publish (data + checksum manifest).

    EVERY process must call this (consolidate runs a collective all-gather
    for cross-host shards); only process 0 touches the filesystem — the
    rank-0-writes split of ``multi-gpu-distributed-cls.py:192,196-197``
    without its deadlock risk.
    """
    data_tree = snapshot(tree)
    if jax.process_index() != 0:
        return
    publish(path, serialization.to_bytes(data_tree), meta=meta)


def _read_raw_verified(path: str) -> Tuple[Any, Optional[Dict]]:
    """``(raw_tree, manifest_meta)`` after checksum + decode verification.

    Raises :class:`CorruptCheckpointError` when the published manifest does
    not match the bytes on disk or the msgpack payload fails to decode; a
    missing manifest (pre-manifest file) skips the checksum but still
    decode-verifies."""
    with open(path, "rb") as f:
        data = f.read()
    try:
        man = load_manifest(path)
    except ValueError as e:  # bit-rotted/truncated manifest JSON
        raise CorruptCheckpointError(
            f"checkpoint {path!r}: manifest {manifest_path(path)!r} is not "
            f"decodable JSON: {e}") from e
    if man is not None:
        if not isinstance(man, dict) or "crc32" not in man:
            raise CorruptCheckpointError(
                f"checkpoint {path!r}: manifest {manifest_path(path)!r} is "
                "unreadable")
        if man.get("bytes") != len(data) or \
                man.get("crc32") != (zlib.crc32(data) & 0xFFFFFFFF):
            raise CorruptCheckpointError(
                f"checkpoint {path!r} fails manifest verification "
                f"(expected {man.get('bytes')} bytes crc32 "
                f"{man.get('crc32')}, found {len(data)} bytes crc32 "
                f"{zlib.crc32(data) & 0xFFFFFFFF}) — truncated or corrupt "
                "write")
    try:
        raw = serialization.msgpack_restore(data)
    except Exception as e:
        raise CorruptCheckpointError(
            f"checkpoint {path!r} is not decodable msgpack: {e}") from e
    return raw, (man or {}).get("meta")


def read_verified(path: str, *, fallback: bool = True
                  ) -> Tuple[Any, Optional[Dict], str]:
    """Verified raw restore with previous-snapshot fallback:
    ``(raw_tree, manifest_meta, path_actually_read)``.

    A corrupt (or vanished) ``path`` falls back to the retained
    ``path.prev`` with a LOUD warning — resuming from the previous snapshot
    loses at most one snapshot interval, where crashing loses the run."""
    try:
        raw, meta = _read_raw_verified(path)
        return raw, meta, path
    except (CorruptCheckpointError, FileNotFoundError) as e:
        prev = prev_path(path)
        if not (fallback and os.path.exists(prev)):
            raise
        print(f"WARNING: {e} — falling back to the previous published "
              f"snapshot {prev!r}", file=sys.stderr)
        raw, meta = _read_raw_verified(prev)
        return raw, meta, prev


def verify(path: str) -> Tuple[bool, Optional[str]]:
    """``(ok, reason)`` — does ``path`` satisfy the published-snapshot
    contract (manifest checksum + decodable payload)?  Template-free; the
    bench resilience gate and tests use it."""
    try:
        _read_raw_verified(path)
        return True, None
    except FileNotFoundError:
        return False, "missing"
    except CorruptCheckpointError as e:
        return False, str(e)


def load(path: str, like, *, fallback: bool = True) -> Any:
    """Restore a pytree with the structure/dtypes of ``like``.

    Verifies the manifest checksum first and falls back to the retained
    previous snapshot (``read_verified``) on corruption.  Raises
    ``ValueError`` on leaf-shape mismatch — flax ``from_bytes`` does not
    validate shapes, which would defer the failure to an opaque XLA error
    at the next forward pass (e.g. loading a ``bert-tiny`` checkpoint into
    a ``bert-base`` template).  A shape mismatch is NOT corruption and
    never falls back.
    """
    raw, _meta, used = read_verified(path, fallback=fallback)
    return from_restored(raw, like, path=used)


def from_restored(raw, like, *, path: str = "<restored>") -> Any:
    """:func:`load`'s template fit + shape validation applied to an
    already-restored raw tree (:func:`load_raw`'s output) — consumers that
    must inspect the raw tree first (the serve engine probes for int8
    ``qscale`` leaves) pay ONE file read + msgpack decode, not two.
    ``path`` only labels error messages."""
    template = _wrap_rng(like) if isinstance(like, dict) and "rng" in like else like
    restored = serialization.from_state_dict(template, raw)
    got_leaves = jax.tree_util.tree_leaves(restored)
    want = jax.tree_util.tree_leaves_with_path(template)
    got_shapes = [getattr(l, "shape", None) for l in got_leaves]
    want_shapes = [getattr(l, "shape", None) for _, l in want]
    if got_shapes != want_shapes:
        (keypath, _), bad_got, bad_want = next(
            (w, g, ws) for w, g, ws in zip(want, got_shapes, want_shapes)
            if g != ws)
        leaf = jax.tree_util.keystr(keypath)
        # a [2]u32-vs-[4]u32 *rng* leaf means the checkpoint was saved under
        # a different PRNG impl (threefry2x32 vs rbg), not a different model
        if "rng" in leaf and {bad_got, bad_want} <= {(2,), (4,)}:
            raise ValueError(
                f"checkpoint {path!r} stores an RNG key of a different PRNG "
                f"impl than the current --rng_impl (key_data {bad_got} vs "
                f"{bad_want}: threefry2x32 is [2]u32, rbg is [4]u32) — rerun "
                "with the --rng_impl it was saved under")
        raise ValueError(
            f"checkpoint {path!r} does not match the model template: "
            f"leaf {leaf} has shape {bad_got} vs expected {bad_want}")
    if isinstance(restored, dict) and "rng" in restored and isinstance(like, dict):
        restored = dict(restored)
        # rewrap with the template key's impl (rbg key_data is [4]u32,
        # threefry [2]u32 — default wrap would mis-type an rbg stream)
        restored["rng"] = jax.random.wrap_key_data(
            restored["rng"], impl=jax.random.key_impl(like["rng"]))
    return restored


def load_raw(path: str) -> Any:
    """Template-free restore: the checkpoint's raw pytree as host numpy.

    The read-only half of :func:`load` for consumers that have no model
    template yet — the serving engine peeks a checkpoint's leaf shapes to
    fail fast on a model mismatch BEFORE paying device transfer, and the
    ``serve_tpu.py`` CLI prints what a file contains.  Manifest-verified
    like :func:`load` but WITHOUT the ``.prev`` fallback — a template-free
    consumer must decide for itself whether an older snapshot is an
    acceptable substitute.  Never use this to feed a forward pass directly;
    :func:`load` (shape-validated against the model template) is the
    loading path.
    """
    raw, _meta = _read_raw_verified(path)
    return raw


def save_params(path: str, state: Dict[str, Any],
                meta: Optional[Dict] = None) -> None:
    """Model-only checkpoint — the ``state_dict`` analog used by test/predict."""
    save(path, state["params"], meta=meta)


def load_params(path: str, like_params) -> Any:
    return load(path, like_params)


def save_state(path: str, state: Dict[str, Any],
               meta: Optional[Dict] = None) -> None:
    """Full resume checkpoint: params + opt_state + step + rng.  ``meta``
    (step / steps-per-epoch at save time) rides the manifest — the
    elastic-width resume reads it to remap the data position onto a
    different data-parallel mesh width."""
    save(path, state, meta=meta)


def load_state(path: str, like_state: Dict[str, Any]) -> Dict[str, Any]:
    return load(path, like_state)


_STEP_RE = re.compile(r"[-_.](\d+)$")


def _filename_step(path: str, pattern: str) -> Optional[tuple]:
    """``(stem, step)`` for a step-family checkpoint name — a TRAILING
    integer set off by ``-``/``_``/``.`` right before the suffix
    (``ckpt-1500.msgpack`` -> ``("ckpt", 1500)``) — or None.  Interior or
    attached digits are NOT steps: ``zero2-cls`` and ``pretrained-e5``
    name a strategy and an epoch tag, not a step counter."""
    base = os.path.basename(path)
    if base.endswith(pattern):
        base = base[:len(base) - len(pattern)]
    m = _STEP_RE.search(base)
    return (base[:m.start()], int(m.group(1))) if m else None


def latest(output_dir: str, pattern: str = ".msgpack") -> Optional[str]:
    """Newest checkpoint in a directory, or None.

    mtime alone is the wrong order key twice over: coarse-mtime
    filesystems tie checkpoints written within the same second, and a
    ``cp -p`` restore resurrects old timestamps wholesale — after which
    "newest mtime" silently serves a stale file.  When every candidate
    belongs to ONE step family (same stem, trailing ``-<step>`` before
    the suffix), the step ORDERS them (mtime only breaks step ties);
    any mixed-family directory falls back to mtime with deterministic
    name tie-breaks, so `pretrained-e5.msgpack` can never outrank a
    newer `zero2-cls.msgpack` on its epoch digit.

    Deliberate consequence: within one family the highest STEP wins even
    when a lower-step file is newer on disk — a reused output_dir whose
    new run restarts the step counter should be cleaned (or given a new
    dir) first, the same contract resume already has.
    """
    if not os.path.isdir(output_dir):
        return None
    cands = [os.path.join(output_dir, f) for f in os.listdir(output_dir)
             if f.endswith(pattern)]
    if not cands:
        return None
    steps = {c: _filename_step(c, pattern) for c in cands}
    if all(s is not None for s in steps.values()) \
            and len({s[0] for s in steps.values()}) == 1:
        return max(cands, key=lambda c: (steps[c][1], os.path.getmtime(c)))
    return max(cands, key=lambda c: (os.path.getmtime(c),
                                     steps[c][1] if steps[c] else -1,
                                     os.path.basename(c)))
