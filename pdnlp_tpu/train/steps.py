"""Train/eval step functions — the jitted hot loop.

The reference's hot loop is ``forward -> barrier -> backward(allreduce) ->
optimizer.step -> loss allreduce`` (``/root/reference/multi-gpu-distributed-
cls.py:165-181``).  Here the whole sequence is ONE XLA program: forward,
weighted-CE loss, backward, AdamW update, fused and compiled.  Parallelism is
chosen by *placement*, not by code: the same jitted step runs

- single-device when arrays live on one chip;
- data-parallel when the batch is sharded along the mesh ``data`` axis
  (XLA inserts the gradient all-reduce the reference does via NCCL);
- ZeRO/FSDP when params/opt-state are themselves sharded (XLA inserts
  all-gather/reduce-scatter, the ``zero_optimization`` analog of
  ``/root/reference/multi-gpu-deepspeed-cls.py:232-239``).

Loss semantics: per-example cross-entropy weighted by ``example_weight`` so
the static-shape filler rows of the last batch contribute nothing (the
reference instead runs a ragged 16-example 288th step).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from pdnlp_tpu.models import BertConfig, bert
from pdnlp_tpu.ops.fused_ce import fused_weighted_ce, resolve_fused_ce
from pdnlp_tpu.train.precision import resolve_dtype

State = Dict[str, Any]  # {'params', 'opt_state', 'step', 'rng'}
Metrics = Dict[str, jax.Array]


def _unroll(args):
    """Layer-scan unroll from ``Args``: None = full unroll (fastest
    measured), an int = that factor (1 = rolled scan, flat compile)."""
    u = getattr(args, "scan_unroll", None)
    return True if u is None else u


def init_state(key: jax.Array, cfg: BertConfig, tx: optax.GradientTransformation,
               rng: jax.Array = None, params=None, ema: bool = False) -> State:
    """Canonical train-state schema.  ``params`` may be passed pre-built
    (e.g. already sharded) to avoid re-initializing the full tree.
    ``ema=True`` adds an ``'ema'`` tree (initialized to the params) that the
    train step maintains as an exponential moving average — the weights
    eval/checkpointing then prefer (``--ema_decay``)."""
    if params is None:
        params = bert.init_params(key, cfg)
    state = {
        "params": params,
        "opt_state": tx.init(params),
        "step": jnp.zeros((), jnp.int32),
        "rng": rng if rng is not None else jax.random.key(0),
    }
    if ema:
        # jnp.copy, not asarray: distinct buffers, so a donated train step
        # can never invalidate params and ema together.  (Inside a jit init
        # XLA may still alias identical outputs — setup_sharded_model does
        # a post-jit copy for that path.)
        state["ema"] = jax.tree_util.tree_map(jnp.copy, params)
    return state


def cast_kernels(params, dtype):
    """Cast every ``kernel`` leaf with >=2 dims to ``dtype``, leaving
    embeddings, LayerNorm scales, and biases in fp32.

    The rule matches exactly the leaves ``bert._dense`` casts per-use, so a
    forward through the cast tree is bitwise identical to one through the
    fp32 masters — only gradient *materialization* changes dtype."""

    def cast(path, leaf):
        last = path[-1]
        if (getattr(last, "key", None) == "kernel"
                and getattr(leaf, "ndim", 0) >= 2):
            return leaf.astype(dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(cast, params)


def weighted_ce(logits: jax.Array, labels: jax.Array, weights: jax.Array,
                smoothing: float = 0.0
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(weighted mean CE, weighted correct count, training objective);
    filler rows weigh 0.

    The first element is always the BARE cross-entropy — the reported
    metric, so smoothed and unsmoothed runs (and train vs eval lines) read
    on the same scale, mirroring how the MoE aux loss is kept out of the
    reported loss.  ``smoothing`` > 0 mixes the one-hot target with uniform
    mass eps/K (label smoothing) in the third element only; at 0 the
    objective is the bare CE array itself."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    ce = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    wsum = jnp.maximum(weights.sum(), 1.0)
    loss = (ce * weights).sum() / wsum
    objective = loss
    if smoothing:
        uniform = ((-logp.mean(-1)) * weights).sum() / wsum
        objective = (1.0 - smoothing) * loss + smoothing * uniform
    correct = ((jnp.argmax(logits, -1) == labels) * weights).sum()
    return loss, correct, objective


def build_train_step(cfg: BertConfig, tx: optax.GradientTransformation, args,
                     opt_staging=None,
                     ) -> Callable[[State, Dict[str, jax.Array]], Tuple[State, Metrics]]:
    """The *unjitted* fused train step — callers choose how to compile it
    (plain ``jit``, ``jit`` with mesh shardings, or inside ``shard_map``).

    ``opt_staging``: ``(device_shardings, host_shardings)`` trees for the
    optimizer state when it lives in host memory (``--offload_opt_state``,
    the DeepSpeed ``offload_optimizer`` analog): the step explicitly stages
    moments host->device before the update and back after — XLA refuses
    mixed-memory-space arithmetic, so the transfers are part of the program.
    Measured ~4x step cost on v5e for BERT-base; the win is the ~800MB of
    HBM the fp32 moments no longer occupy."""
    dtype = resolve_dtype(args.dtype)
    remat = bool(args.remat)
    # "auto" flows through: ops.attention.routed_impl resolves it at trace
    # time with the batch's real shape/packedness/dropout in hand
    attn_impl = args.attention_impl
    unroll = _unroll(args)
    smoothing = args.label_smoothing
    fused_ce = resolve_fused_ce(args)

    def loss_fn(params, batch, rng):
        # aux is the MoE load-balancing loss, a constant 0 for dense models
        # (XLA folds the add away); it joins the optimized objective only —
        # the reported loss stays bare CE so MoE and dense runs read on the
        # same scale
        out, aux = bert.classify(
            params, cfg, batch, dtype=dtype, deterministic=False, rng=rng,
            remat=remat, attn_impl=attn_impl, unroll=unroll, return_aux=True,
            return_pooled=fused_ce == "pallas",
        )
        # packed rows return per-SEGMENT outputs [B, M, .] with [B, M]
        # labels/weights: flatten to the per-example stream — the weighted
        # CE below is then exactly the unpacked loss over the same
        # examples (empty slots weigh 0, like filler rows)
        labels, weights = batch["label"], batch["example_weight"]
        if out.ndim == 3:
            out = out.reshape(-1, out.shape[-1])
            labels = labels.reshape(-1)
            weights = weights.reshape(-1)
        if fused_ce == "pallas":
            # ``out`` is the pooled pre-classifier features: the kernel
            # consumes the final projection itself, so the [T, C] logits
            # never round-trip HBM (ops.fused_ce)
            loss, correct, objective = fused_weighted_ce(
                out, params["classifier"]["kernel"].astype(dtype),
                params["classifier"]["bias"].astype(dtype),
                labels, weights, smoothing=smoothing)
        else:
            loss, correct, objective = weighted_ce(
                out, labels, weights, smoothing=smoothing)
        return objective + cfg.moe_aux_coef * aux, (loss, correct)

    ema_decay = getattr(args, "ema_decay", 0.0)
    bf16_grads = dtype != jnp.float32 and getattr(args, "grads_dtype",
                                                  "param") == "compute"

    def train_step(state: State, batch: Dict[str, jax.Array]) -> Tuple[State, Metrics]:
        rng = jax.random.fold_in(state["rng"], state["step"])
        params = state["params"]
        if bf16_grads:
            # Pre-cast the big matmul kernels to the compute dtype OUTSIDE
            # the differentiated function, so their gradients are *produced*
            # in bf16 — the AMP analog of fp16 grads on the wire
            # (/root/reference/multi-gpu-distributed-mp-amp-cls.py:167-175
            # keeps fp16 grads until the unscale).  Forward math is bitwise
            # unchanged (the kernels were cast per-use inside loss_fn
            # anyway); what changes is the backward's materialization: grad
            # assembly for the [L,...]-stacked kernels (dynamic-update-slice
            # chains) moves half the bytes.  The mu/nu ACCUMULATORS stay
            # fp32, but each increment is computed from the bf16 grad (nu's
            # g**2 squares in bf16) — measured NEUTRAL to -6% on v5e and
            # non-default for that reason (results/profile_r05.json).
            params = cast_kernels(params, dtype)
        (_, (loss, correct)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, rng
        )
        # bf16 grads flow into the optimizer AS bf16: Adam's moment
        # arithmetic promotes them to fp32 per-element inside the fused
        # update loops (an explicit tree-wide upcast here measured as a
        # no-op — XLA pushes the convert back into the grad-assembly chain,
        # rebuilding the fp32 DUS traffic the cast exists to avoid).
        opt_in = state["opt_state"]
        if opt_staging is not None:
            opt_in = jax.device_put(opt_in, opt_staging[0])   # host -> device
        updates, opt_state = tx.update(grads, opt_in, state["params"])
        if opt_staging is not None:
            opt_state = jax.device_put(opt_state, opt_staging[1])  # -> host
        params = optax.apply_updates(state["params"], updates)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
            "rng": state["rng"],
        }
        if "ema" in state:
            # bias-corrected-free simple EMA: eval/checkpoint weights
            # (Polyak averaging — smooths the tail of the LR schedule)
            d = jnp.asarray(ema_decay, jnp.float32)
            new_state["ema"] = jax.tree_util.tree_map(
                lambda e, p: (d * e.astype(jnp.float32)
                              + (1.0 - d) * p.astype(jnp.float32)
                              ).astype(e.dtype) if hasattr(e, "dtype")
                else e,
                state["ema"], params)
        wsum = jnp.maximum(batch["example_weight"].sum(), 1.0)
        return new_state, {"loss": loss, "accuracy": correct / wsum}

    return train_step


def make_train_step(cfg: BertConfig, tx: optax.GradientTransformation, args
                    ) -> Callable[[State, Dict[str, jax.Array]], Tuple[State, Metrics]]:
    """Build the fused train step.  Strategy = where you place the inputs."""
    return jax.jit(build_train_step(cfg, tx, args), donate_argnums=0)


def build_multi_step(step_fn: Callable) -> Callable:
    """``lax.scan`` K sequential optimizer steps into ONE device program.

    Math-identical to K separate calls (same updates, in order; per-step
    metrics come back stacked ``[K]``) — what changes is dispatch: one
    host->device round trip per K steps instead of per step; the TPU twin
    of CUDA-graph step capture.  Measured trade-off on this benchmark's
    shapes (BERT-base, batch 32, one v5e): scan-carried weights cost ~6%
    device-step speed (33.4 vs 35.4 steps/s probed — XLA loses some layout
    freedom), bought back many times over on high-latency links — K=4
    pinned the epoch at ~0.167 min on a slow-tunnel day where per-step
    dispatch took 0.269 min, which is why ``bench.py`` ships
    ``fuse_steps=4``.  On a local-PCIe host where dispatch is cheap,
    ``fuse_steps=1`` is marginally faster.
    """

    def multi_step(state: State, batches: Dict[str, jax.Array]
                   ) -> Tuple[State, Metrics]:
        return jax.lax.scan(step_fn, state, batches)

    return multi_step


def make_multi_step(cfg: BertConfig, tx: optax.GradientTransformation, args
                    ) -> Callable[[State, Dict[str, jax.Array]], Tuple[State, Metrics]]:
    """Jitted K-step fusion for single-device runs (batches: ``[K, B, ...]``)."""
    return jax.jit(build_multi_step(build_train_step(cfg, tx, args)),
                   donate_argnums=0)


def build_eval_step(cfg: BertConfig, args) -> Callable[..., Metrics]:
    """Unjitted deterministic eval step returning global sums (host
    accumulates).

    The reference's ``dev``/``test`` all-gather logits+labels across ranks
    (``multi-gpu-distributed-cls.py:145-155``); with a batch sharded over the
    mesh the same gather happens inside XLA and the returned scalars are
    already global.
    """
    dtype = resolve_dtype(args.dtype)
    attn_impl = args.attention_impl  # ops.attention routes "auto" per trace
    unroll = _unroll(args)

    def eval_step(params, batch) -> Metrics:
        logits = bert.classify(params, cfg, batch, dtype=dtype,
                               deterministic=True, attn_impl=attn_impl,
                               unroll=unroll)
        labels, w = batch["label"], batch["example_weight"]
        if logits.ndim == 3:  # packed rows: per-segment -> per-example
            logits = logits.reshape(-1, logits.shape[-1])
            labels = labels.reshape(-1)
            w = w.reshape(-1)
        loss, correct, _ = weighted_ce(logits, labels, w)
        return {
            "loss_sum": loss * jnp.maximum(w.sum(), 1.0),
            "weight": w.sum(),
            "correct": correct,
            "pred": jnp.argmax(logits, -1),
            # echo labels/weights through the device: with a sharded batch and
            # replicated outputs this is the all-gather that lets every host
            # assemble the full (pred, label) stream for the report
            # (multi-gpu-distributed-cls.py:145-155).
            "label": labels,
            "ew": w,
        }

    return eval_step


def make_eval_step(cfg: BertConfig, args) -> Callable[..., Metrics]:
    """Jitted eval step (single-device / auto-propagated sharding)."""
    return jax.jit(build_eval_step(cfg, args))
