"""Training layer: optimizer, precision policy, jitted steps, Trainer,
checkpointing, and the shared experiment setup used by every entrypoint."""
from pdnlp_tpu.train.optim import build_optimizer, decay_mask
from pdnlp_tpu.train.precision import resolve_dtype
from pdnlp_tpu.train.setup import setup_data, setup_model
from pdnlp_tpu.train.steps import init_state, make_eval_step, make_train_step, weighted_ce
from pdnlp_tpu.train.trainer import Trainer
from pdnlp_tpu.train import checkpoint

__all__ = [
    "build_optimizer", "decay_mask", "resolve_dtype", "setup_data",
    "setup_model", "init_state", "make_eval_step", "make_train_step",
    "weighted_ce", "Trainer", "checkpoint",
]
