"""Async checkpoint publishing — the step loop pays device→host only.

The synchronous save path (``checkpoint.save_state``) does three things in
the caller's thread: consolidate the state to host (device→host copy — a
barrier on every previously dispatched step, unavoidable for a consistent
snapshot), serialize it to msgpack, and write + fsync + rename the file.
Only the FIRST belongs in the step loop; on preemptible multi-host runs the
serialize+IO tail is pure stall — at resume cadences worth having (tens of
steps) it shows up directly in the ``ckpt_save`` phase of the step
breakdown.

:class:`AsyncCheckpointer` splits the save at exactly that line:

- the caller (the trainer's ``ckpt_save`` span) produces a host snapshot
  via :func:`checkpoint.snapshot` — collective, so EVERY process runs it —
  and hands it to :meth:`submit`, which returns immediately;
- one daemon writer thread serializes and crash-atomically publishes
  (tmp + rename + checksum manifest, ``checkpoint.publish``) off the loop;
- **double-buffered, at most one save in flight**: the writer processes one
  publish at a time; while it writes, at most one NEWER snapshot per path
  waits in the pending slot — a third submit for the same path replaces the
  waiting one (latest wins; the superseded snapshot was about to be
  stale anyway).  Host memory is therefore bounded at two snapshots, and
  the step loop never blocks on disk;
- :meth:`wait` drains everything (end of training — durability work that
  must count toward the reported runtime) and re-raises the first writer
  error; a failed write also surfaces LOUDLY on the next :meth:`submit`
  instead of rotting silently.

Only process 0 enqueues writes (the same rank-0-writes split as the sync
path); the snapshot handed in is plain host numpy, so the writer thread
never touches a device.
"""
from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional, Tuple


class AsyncCheckpointer:
    def __init__(self, process_index: Optional[int] = None):
        if process_index is None:
            import jax

            process_index = jax.process_index()
        self.process_index = int(process_index)
        self._cond = threading.Condition()
        # path -> (kind, payload, meta); FIFO across paths, latest-wins
        # per path.  kind "msgpack" = a checkpoint.snapshot tree to
        # serialize+publish; "json" = a small sidecar object for
        # write_json_atomic (the -best.json tracker rides the writer too —
        # no sync disk IO sneaks back into the step loop)
        self._pending: "collections.OrderedDict[str, Tuple[str, Any, Optional[Dict]]]" \
            = collections.OrderedDict()
        self._in_flight: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        self._errors: List[Tuple[str, BaseException]] = []
        self.submitted = 0
        self.published = 0
        self.superseded = 0

    # ------------------------------------------------------------ submitting
    def submit(self, path: str, host_tree: Any,
               meta: Optional[Dict] = None) -> None:
        """Enqueue one crash-atomic publish of ``host_tree`` (a
        ``checkpoint.snapshot`` result) to ``path``.  Returns immediately;
        never blocks on serialization or disk.  Non-zero ranks no-op (the
        collective snapshot already ran in the caller).  Raises the writer's
        pending error, if any, before enqueuing — a broken disk must fail
        the run at the next save, not at the end."""
        self._enqueue(path, "msgpack", host_tree, meta)

    def submit_json(self, path: str, obj: Any) -> None:
        """Enqueue a small crash-atomic JSON sidecar write (e.g. the
        ``-best.json`` tracker) on the same writer — even a few-byte fsync
        does not belong on the step loop."""
        self._enqueue(path, "json", obj, None)

    def _enqueue(self, path: str, kind: str, payload: Any,
                 meta: Optional[Dict]) -> None:
        self._raise_pending_error()
        if self.process_index != 0:
            return
        with self._cond:
            if path in self._pending:
                self.superseded += 1
                del self._pending[path]  # re-insert at FIFO tail
            self._pending[path] = (kind, payload, meta)
            self.submitted += 1
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="async-ckpt-writer", daemon=True)
                self._thread.start()
            self._cond.notify_all()

    # --------------------------------------------------------------- writer
    def _run(self) -> None:
        from flax import serialization

        from pdnlp_tpu.train import checkpoint as ckpt

        while True:
            with self._cond:
                while not self._pending:
                    self._cond.wait()
                path, (kind, payload, meta) = self._pending.popitem(last=False)
                self._in_flight = path
            try:
                if kind == "json":
                    ckpt.write_json_atomic(path, payload)
                else:
                    ckpt.publish(path, serialization.to_bytes(payload),
                                 meta=meta)
                with self._cond:
                    self.published += 1
            except BaseException as e:  # surfaced at next submit/wait
                with self._cond:
                    self._errors.append((path, e))
            finally:
                with self._cond:
                    self._in_flight = None
                    self._cond.notify_all()

    # ---------------------------------------------------------------- waits
    @property
    def in_flight(self) -> bool:
        with self._cond:
            return self._in_flight is not None or bool(self._pending)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted save is published (or ``timeout``
        seconds elapse — returns False, nothing is cancelled).  Re-raises
        the first writer error once fully drained."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._pending or self._in_flight is not None:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
        self._raise_pending_error()
        return True

    def _raise_pending_error(self) -> None:
        with self._cond:
            if not self._errors:
                return
            errors, self._errors = self._errors, []
        # every failed path is named (a disk-full can take out the main
        # snapshot AND its -best sidecar before anyone looks); the first
        # failure is chained as the cause
        raise RuntimeError(
            "async checkpoint publish failed for "
            + ", ".join(f"{p!r} ({type(e).__name__}: {e})"
                        for p, e in errors)) from errors[0][1]

    def stats(self) -> Dict[str, int]:
        with self._cond:
            return {"submitted": self.submitted, "published": self.published,
                    "superseded": self.superseded,
                    "errors": len(self._errors)}
