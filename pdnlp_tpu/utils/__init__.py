from pdnlp_tpu.utils.config import Args
from pdnlp_tpu.utils.seeding import set_seed
from pdnlp_tpu.utils.logging import get_logger, rank0_print
