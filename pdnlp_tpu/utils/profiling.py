"""Profiling and step-rate observability.

The reference's only timing is a wall-clock print around the epoch loop
(``/root/reference/single-gpu-cls.py:129,150-151``) plus DeepSpeed's
``wall_clock_breakdown`` (``multi-gpu-deepspeed-cls.py:245``).  Here:

- ``Profiler`` wraps a window of training steps in a ``jax.profiler`` trace
  (viewable in TensorBoard/XProf) when ``--profile_dir`` is set — device
  timelines, HLO cost, HBM usage; the window skips warmup steps so the
  trace shows steady state, not compilation.
- ``StepStats`` turns the epoch wall-clock into the derived rates the
  reference's README table reports informally (steps/s, examples/s).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from pdnlp_tpu.utils.logging import rank0_print


class Profiler:
    """Trace steps [start, start+steps) of training into ``profile_dir``."""

    def __init__(self, profile_dir: Optional[str], start_step: int = 10,
                 num_steps: int = 10):
        self.dir = profile_dir
        self.start_step = start_step
        self.stop_step = start_step + num_steps
        self._active = False
        self._done = False

    def step(self, gstep: int) -> None:
        """Call once per dispatch with the global step index.  Boundary
        crossings (not equality) so K-fused steps that jump over
        ``start_step``/``stop_step`` still open/close the window."""
        if not self.dir or self._done:
            return
        if gstep >= self.start_step and not self._active:
            # Open even when this dispatch already crossed stop_step (one
            # K-fused dispatch can jump the whole window): the window slides
            # forward to trace the NEXT dispatch rather than vanishing.
            import jax

            try:
                jax.profiler.start_trace(self.dir)
                self._active = True
                rank0_print(f"[profiler] tracing from step {gstep} "
                            f"(window {self.start_step}..{self.stop_step}) "
                            f"-> {self.dir}")
            except Exception as e:  # platform without profiler support
                rank0_print(f"[profiler] trace unavailable: {e}")
                self.dir = None
        elif gstep >= self.stop_step and self._active:
            self.close()

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self._done = True


@dataclasses.dataclass
class StepStats:
    """Derived rates from the timed epoch (the north-star denominators)."""

    steps: int
    examples: int
    minutes: float

    @property
    def steps_per_second(self) -> float:
        return self.steps / (self.minutes * 60) if self.minutes else 0.0

    @property
    def examples_per_second(self) -> float:
        return self.examples / (self.minutes * 60) if self.minutes else 0.0

    def line(self) -> str:
        return (f"steps/s：{self.steps_per_second:.2f}  "
                f"samples/s：{self.examples_per_second:.1f}")
