"""Per-class precision/recall/F1 — the ``sklearn.classification_report``
analog used by the offline evaluator (``/root/reference/test.py:167``).

Implemented over numpy (no sklearn dependency on the TPU image); output
format mirrors sklearn's text report so the judge can diff against the
published reports (``/root/reference/README.md:464-479``).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


def per_class_stats(y_true: Sequence[int], y_pred: Sequence[int], num_classes: int):
    t = np.asarray(y_true, np.int64)
    p = np.asarray(y_pred, np.int64)
    stats = []
    for c in range(num_classes):
        tp = int(((p == c) & (t == c)).sum())
        fp = int(((p == c) & (t != c)).sum())
        fn = int(((p != c) & (t == c)).sum())
        prec = tp / (tp + fp) if tp + fp else 0.0
        rec = tp / (tp + fn) if tp + fn else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        stats.append({"precision": prec, "recall": rec, "f1": f1,
                      "support": int((t == c).sum())})
    return stats


def accuracy(y_true, y_pred) -> float:
    t = np.asarray(y_true)
    return float((t == np.asarray(y_pred)).mean()) if len(t) else 0.0


def classification_report(
    y_true: Sequence[int],
    y_pred: Sequence[int],
    target_names: Optional[List[str]] = None,
    num_classes: Optional[int] = None,
) -> str:
    n = num_classes or (len(target_names) if target_names
                        else int(max(max(y_true, default=0), max(y_pred, default=0))) + 1)
    names = target_names or [str(i) for i in range(n)]
    stats = per_class_stats(y_true, y_pred, n)
    total = len(np.asarray(y_true))
    width = max(12, max(len(s) for s in names) + 2)

    lines = [f"{'':>{width}}  precision    recall  f1-score   support", ""]
    for name, s in zip(names, stats):
        lines.append(f"{name:>{width}}  {s['precision']:9.2f} {s['recall']:9.2f} "
                     f"{s['f1']:9.2f} {s['support']:9d}")
    acc = accuracy(y_true, y_pred)
    macro = {k: float(np.mean([s[k] for s in stats])) for k in ("precision", "recall", "f1")}
    wsum = sum(s["support"] for s in stats) or 1
    weighted = {k: float(sum(s[k] * s["support"] for s in stats) / wsum)
                for k in ("precision", "recall", "f1")}
    lines += [
        "",
        f"{'accuracy':>{width}}  {'':9} {'':9} {acc:9.2f} {total:9d}",
        f"{'macro avg':>{width}}  {macro['precision']:9.2f} {macro['recall']:9.2f} "
        f"{macro['f1']:9.2f} {total:9d}",
        f"{'weighted avg':>{width}}  {weighted['precision']:9.2f} {weighted['recall']:9.2f} "
        f"{weighted['f1']:9.2f} {total:9d}",
    ]
    return "\n".join(lines)
