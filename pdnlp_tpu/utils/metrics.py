"""Classification metrics + serving observability primitives.

Two halves:

- per-class precision/recall/F1 — the ``sklearn.classification_report``
  analog used by the offline evaluator (``/root/reference/test.py:167``),
  implemented over numpy (no sklearn dependency on the TPU image); output
  format mirrors sklearn's text report so the judge can diff against the
  published reports (``/root/reference/README.md:464-479``);
- ``Counter`` / ``Gauge`` / ``Histogram`` — the observability primitives the
  inference-serving subsystem (``pdnlp_tpu.serve``) aggregates into latency
  p50/p95/p99, queue depth, batch occupancy and compile-cache counters, all
  JSON-snapshot friendly so serve metrics land in ``results/`` next to the
  training artifacts;
- ``TransportStats`` — host->device transport counters for the input
  pipeline (``pdnlp_tpu.data.pipeline``): bytes uploaded (split into
  steady-state in-loop uploads vs amortized one-time/epoch uploads),
  put-wait seconds, padding-waste ratio, and the prefetch in-flight
  high-water mark.  ``bench.py --pipeline`` snapshots these so the
  zero-transport claim of the device-resident mode is measured, not
  asserted.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np


class Counter:
    """Monotonic event count (thread-safe: batcher worker + submitters)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value (e.g. queue depth)."""

    def __init__(self) -> None:
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Streaming histogram with exact percentiles over a bounded window.

    Keeps total count/sum/min/max exactly and the most recent ``window``
    observations for percentile queries — a serving process alive for days
    must not grow its latency record without bound, and recent-window
    percentiles are what a dashboard wants anyway.  Thread-safe.
    """

    def __init__(self, window: int = 8192):
        self._lock = threading.Lock()
        self._window = int(window)
        self._recent: List[float] = []
        self._pos = 0  # ring-buffer cursor once the window is full
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if len(self._recent) < self._window:
                self._recent.append(v)
            else:
                self._recent[self._pos] = v
                self._pos = (self._pos + 1) % self._window

    def percentile(self, p: float) -> Optional[float]:
        return (self.percentiles((p,)) or [None])[0]

    def percentiles(self, ps: Sequence[float]) -> Optional[List[float]]:
        """All requested percentiles over ONE window copy — a live
        ``/metrics`` scrape reads p50/p95/p99 of five histograms per
        tick, and converting the 8k-observation window per percentile
        (3x per histogram) was measurable GIL/lock pressure against the
        serve worker (``bench.py --telemetry``)."""
        with self._lock:
            if not self._recent:
                return None
            window = np.asarray(self._recent)
        return [float(v) for v in np.percentile(window, list(ps))]

    @property
    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None

    def snapshot(self) -> Dict[str, Optional[float]]:
        """JSON-ready summary: count/mean/min/max + p50/p95/p99."""
        ps = self.percentiles((50, 95, 99)) or [None, None, None]
        return {
            "count": self.count,
            "mean": self.mean,
            "min": self.min,
            "max": self.max,
            "p50": ps[0],
            "p95": ps[1],
            "p99": ps[2],
        }


def merged_percentiles(hists: Sequence[Histogram],
                       ps: Sequence[float]) -> List[Optional[float]]:
    """Percentiles over the POOLED recent windows of several histograms —
    one fleet-level p99, not an average of per-instrument p99s (averaging
    percentiles understates the tail whenever load is uneven across
    units, which is exactly when the pool-split controller must act).
    Returns ``None`` per requested percentile when no histogram has
    observations yet."""
    windows = []
    for h in hists:
        with h._lock:
            if h._recent:
                windows.append(np.asarray(h._recent))
    if not windows:
        return [None] * len(ps)
    pooled = np.concatenate(windows)
    return [float(v) for v in np.percentile(pooled, list(ps))]


class TransportStats:
    """Host->device transport telemetry for one input pipeline.

    Distinguishes *in-loop* uploads (paid per step, inside the timed epoch —
    the transport tax the device-resident pipeline eliminates) from
    *amortized* uploads (the one-time dataset residency and the per-epoch
    permutation indices).  Thread-safe: the prefetch pipeline records from
    its upload worker while the train loop reads.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.mode: Optional[str] = None
        self.bytes_total = 0        # every host->device upload
        self.bytes_in_loop = 0      # uploads issued per step, in the loop
        self.puts_in_loop = 0
        self.puts_amortized = 0
        self.put_wait_sec = 0.0     # host seconds blocked inside put()
        self.steps = 0              # optimizer steps fed
        self.rows = 0               # batch rows fed (incl. filler padding)
        self.rows_real = 0          # weight-1 rows (real examples)
        self.tokens = 0             # token positions fed (rows x seq_len)
        self.tokens_real = 0        # attention-mask-1 positions (non-[PAD])
        self.by_bucket: Dict[int, Dict[str, int]] = {}  # seq_len -> counters
        self.in_flight = 0          # uploaded but not yet handed to the loop
        self.in_flight_max = 0

    def record_upload(self, nbytes: int, wait_sec: float,
                      in_loop: bool = True) -> None:
        with self._lock:
            self.bytes_total += int(nbytes)
            self.put_wait_sec += float(wait_sec)
            if in_loop:
                self.bytes_in_loop += int(nbytes)
                self.puts_in_loop += 1
            else:
                self.puts_amortized += 1

    def record_batch(self, steps: int, rows: int, rows_real: int,
                     seq_len: int = 0, tokens: int = 0,
                     tokens_real: int = 0) -> None:
        """``seq_len``/``tokens``/``tokens_real`` feed the token-level
        padding-waste accounting (and its per-``seq_len``-bucket breakdown)
        the length-aware modes exist to move: ``tokens`` positions were
        paid for (batch input rows x width — under packing that is FEWER
        than the example count suggests), ``tokens_real`` were non-[PAD]."""
        with self._lock:
            self.steps += int(steps)
            self.rows += int(rows)
            self.rows_real += int(rows_real)
            if seq_len:
                self.tokens += int(tokens)
                self.tokens_real += int(tokens_real)
                b = self.by_bucket.setdefault(
                    int(seq_len),
                    {"steps": 0, "rows": 0, "rows_real": 0, "tokens": 0,
                     "tokens_real": 0})
                b["steps"] += int(steps)
                b["rows"] += int(rows)
                b["rows_real"] += int(rows_real)
                b["tokens"] += int(tokens)
                b["tokens_real"] += int(tokens_real)

    def put_started(self) -> None:
        with self._lock:
            self.in_flight += 1
            self.in_flight_max = max(self.in_flight_max, self.in_flight)

    def put_delivered(self) -> None:
        with self._lock:
            self.in_flight -= 1

    @property
    def bytes_per_step(self) -> float:
        """Steady-state in-loop bytes per optimizer step — 0 for the
        device-resident pipeline (the acceptance number)."""
        return self.bytes_in_loop / self.steps if self.steps else 0.0

    @property
    def padding_waste(self) -> float:
        """Fraction of fed rows that were zero-weight filler."""
        return 1.0 - self.rows_real / self.rows if self.rows else 0.0

    @property
    def padding_waste_tokens(self) -> float:
        """Fraction of fed token POSITIONS that were [PAD] — the FLOP
        waste the length-aware modes (bucket/pack) attack.  0.0 until a
        caller supplies ``seq_len``/``tokens_real`` to ``record_batch``."""
        return 1.0 - self.tokens_real / self.tokens if self.tokens else 0.0

    def snapshot(self) -> Dict[str, object]:
        """JSON-ready summary (the bench's ``transport`` block)."""
        with self._lock:
            snap = {
                "mode": self.mode,
                "steps": self.steps,
                "puts_in_loop": self.puts_in_loop,
                "puts_amortized": self.puts_amortized,
                "bytes_uploaded_total": self.bytes_total,
                "bytes_uploaded_in_loop": self.bytes_in_loop,
                "bytes_per_step": round(self.bytes_in_loop / self.steps, 2)
                if self.steps else 0.0,
                "put_wait_sec": round(self.put_wait_sec, 6),
                "padding_waste_ratio": round(
                    1.0 - self.rows_real / self.rows, 6) if self.rows
                else 0.0,
                "padding_waste_tokens": round(
                    1.0 - self.tokens_real / self.tokens, 6) if self.tokens
                else None,
                "prefetch_in_flight_max": self.in_flight_max,
            }
            if self.by_bucket:
                snap["by_bucket"] = {
                    str(seq): {
                        **b,
                        "padding_waste_tokens": round(
                            1.0 - b["tokens_real"] / b["tokens"], 6)
                        if b["tokens"] else 0.0,
                    }
                    for seq, b in sorted(self.by_bucket.items())
                }
            return snap


def per_class_stats(y_true: Sequence[int], y_pred: Sequence[int], num_classes: int):
    t = np.asarray(y_true, np.int64)
    p = np.asarray(y_pred, np.int64)
    stats = []
    for c in range(num_classes):
        tp = int(((p == c) & (t == c)).sum())
        fp = int(((p == c) & (t != c)).sum())
        fn = int(((p != c) & (t == c)).sum())
        prec = tp / (tp + fp) if tp + fp else 0.0
        rec = tp / (tp + fn) if tp + fn else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        stats.append({"precision": prec, "recall": rec, "f1": f1,
                      "support": int((t == c).sum())})
    return stats


def accuracy(y_true, y_pred) -> float:
    t = np.asarray(y_true)
    return float((t == np.asarray(y_pred)).mean()) if len(t) else 0.0


def classification_report(
    y_true: Sequence[int],
    y_pred: Sequence[int],
    target_names: Optional[List[str]] = None,
    num_classes: Optional[int] = None,
) -> str:
    n = num_classes or (len(target_names) if target_names
                        else int(max(max(y_true, default=0), max(y_pred, default=0))) + 1)
    names = target_names or [str(i) for i in range(n)]
    stats = per_class_stats(y_true, y_pred, n)
    total = len(np.asarray(y_true))
    width = max(12, max(len(s) for s in names) + 2)

    lines = [f"{'':>{width}}  precision    recall  f1-score   support", ""]
    for name, s in zip(names, stats):
        lines.append(f"{name:>{width}}  {s['precision']:9.2f} {s['recall']:9.2f} "
                     f"{s['f1']:9.2f} {s['support']:9d}")
    acc = accuracy(y_true, y_pred)
    macro = {k: float(np.mean([s[k] for s in stats])) for k in ("precision", "recall", "f1")}
    wsum = sum(s["support"] for s in stats) or 1
    weighted = {k: float(sum(s[k] * s["support"] for s in stats) / wsum)
                for k in ("precision", "recall", "f1")}
    lines += [
        "",
        f"{'accuracy':>{width}}  {'':9} {'':9} {acc:9.2f} {total:9d}",
        f"{'macro avg':>{width}}  {macro['precision']:9.2f} {macro['recall']:9.2f} "
        f"{macro['f1']:9.2f} {total:9d}",
        f"{'weighted avg':>{width}}  {weighted['precision']:9.2f} {weighted['recall']:9.2f} "
        f"{weighted['f1']:9.2f} {total:9d}",
    ]
    return "\n".join(lines)
