"""Rank-0 logging with the reference's message formats.

The reference logs with bare ``print`` guarded by ``local_rank == 0``
(``multi-gpu-distributed-cls.py:178-191``) in the formats
``【train】 epoch：1/1 step：10/288 loss：1.79`` and
``【dev】 loss：... accuracy：...`` / ``【best accuracy】``, plus the epoch
wall-clock line ``耗时：X分钟`` (``:193-195``).  Keeping the formats
byte-compatible makes loss traces comparable against the README's golden
logs (``README.md:96-100``).
"""
from __future__ import annotations

import logging
import sys

import jax


def is_rank0() -> bool:
    return jax.process_index() == 0


def rank0_print(*args, **kw) -> None:
    if is_rank0():
        print(*args, **kw)
        sys.stdout.flush()


def get_logger(name: str = "pdnlp_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler(sys.stdout)
        h.setFormatter(logging.Formatter("[%(asctime)s %(levelname)s %(name)s] %(message)s"))
        logger.addHandler(h)
        logger.setLevel(logging.INFO if is_rank0() else logging.WARNING)
    return logger


def fmt_train(epoch, epochs, step, total_step, loss) -> str:
    return f"【train】 epoch：{epoch}/{epochs} step：{step}/{total_step} loss：{loss:.6f}"


def fmt_dev(loss, accuracy) -> str:
    return f"【dev】 loss：{loss:.6f} accuracy：{accuracy:.4f}"


def fmt_best(accuracy) -> str:
    return f"【best accuracy】 {accuracy:.4f}"


def fmt_elapsed_minutes(minutes: float) -> str:
    return f"耗时：{minutes}分钟"
