"""Sweep-grid row selection — ONE implementation of the exact-name rule.

Every sweep script takes name tokens on the CLI to re-run a subset of its
grid.  Plain substring matching has a real failure mode in these grids:
``b64_lr6e-05_ema0.99_3ep`` is a SUBSTRING of its ``tanh_...`` sibling, so
selecting the erf row silently re-ran the tanh row's chip time too (ADVICE
round-5 item 1).  The fix, applied first in ``scripts/bench_longcontext.py``
and ``scripts/sweep_b64.py`` and now shared by every sweep via this module:

- a token that EXACTLY names a grid row selects only that row;
- substring matching applies only to tokens that are NOT themselves grid
  row names (so ``tanh`` still selects the whole tanh family);
- tokens may be space- or comma-separated (a comma list otherwise matches
  nothing and the run silently does no work).
"""
from __future__ import annotations

import sys
from typing import Callable, Collection, Iterable, List


def parse_only(tokens: Iterable[str]) -> List[str]:
    """Split space- AND comma-separated selection tokens."""
    return [t for raw in tokens for t in raw.split(",") if t]


def make_selected(only: Iterable[str], grid_names: Collection[str]
                  ) -> Callable[[str], bool]:
    """``selected(name)`` under the exact-name rule: no tokens = everything;
    an exact-name token selects ONLY that row; other tokens substring-match
    but never collide with a row name.

    A token matching NOTHING (typo'd row name, stale invocation syntax) is
    reported on stderr at construction — a sweep that silently does no work
    is this module's founding failure mode, not a feature."""
    only = list(only)
    grid = set(grid_names)
    for tok in only:
        if tok not in grid and not any(tok in n for n in grid):
            print(f"sweeps: selection token {tok!r} matches no grid row "
                  f"(rows: {', '.join(sorted(grid))})", file=sys.stderr)

    def selected(name: str) -> bool:
        if not only:
            return True
        if any(o == name for o in only):
            return True
        return any(o in name and o not in grid for o in only)

    return selected
