"""Determinism utilities.

The reference seeds ``random``/``numpy``/``torch``/``torch.cuda`` with 123 on
every rank (``single-gpu-cls.py:14-23``) so all ranks compute the same
shuffle/split.  On TPU the split stays host-side (``random``/``numpy``) and
device-side randomness flows through explicit ``jax.random`` keys — there is
no global device RNG to seed.
"""
from __future__ import annotations

import random

import jax
import numpy as np


def set_seed(seed: int = 123) -> jax.Array:
    """Seed host RNGs and return the root JAX PRNG key.

    Mirrors ``set_seed`` (``single-gpu-cls.py:14-23``); the returned key
    replaces the implicit ``torch.manual_seed`` device stream.
    """
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.key(seed)


def fold(key: jax.Array, step) -> jax.Array:
    """Derive a per-step key (e.g. for dropout) — jit-safe."""
    return jax.random.fold_in(key, step)


def train_key(seed: int, impl: str = "rbg") -> jax.Array:
    """The dropout-stream root key.

    ``impl="rbg"`` generates random bits with XLA's ``RngBitGenerator`` —
    hardware-backed on TPU and measured 20% faster per train step than
    threefry on this benchmark (dropout masks are ~190M random values/step
    for BERT-base at batch 32/seq 128; threefry computes them on the VPU).
    Key derivation (``split``/``fold_in``) still runs threefry, so per-step
    streams remain independent.  ``impl="threefry2x32"`` restores streams
    that are stable across backends/XLA versions.
    """
    return jax.random.key(seed, impl=impl)
