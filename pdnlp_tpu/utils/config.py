"""Single typed hyperparameter config.

The reference duplicates a plain ``Args`` class nine times with drift
(``eval_step`` 100 vs 50: ``single-gpu-cls.py:204`` vs
``multi-gpu-distributed-cls.py:252``; model path ``hfl/...`` vs local
``model_hub/...``: ``multi-gpu-horovod-cls.py:253``).  Here there is ONE
dataclass; strategy entrypoints override fields instead of copy-pasting.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional


_DEFAULT_DATA = "/root/reference/data/train.json"


@dataclasses.dataclass
class Args:
    """Hyperparameters (defaults mirror ``multi-gpu-distributed-cls.py:242-257``)."""

    # --- data ---
    data_path: str = _DEFAULT_DATA
    vocab_path: str = "output/vocab.txt"          # built from the corpus (no egress)
    max_seq_len: int = 128                        # single-gpu-cls.py:196
    data_limit: int = 10_000                      # first-N slice, single-gpu-cls.py:226
    ratio: float = 0.92                           # train/dev split, single-gpu-cls.py:195
    train_batch_size: int = 32                    # per device
    dev_batch_size: int = 32

    # --- model ---
    model: str = "bert-base"                      # key into models.config registry
    num_labels: int = 6
    dropout: float = 0.1
    attn_dropout: float = 0.1                     # attention_probs_dropout_prob
    init_from: Optional[str] = None               # pretrain ckpt: encoder warm-start
    mlm_prob: float = 0.15                        # pretraining mask rate
    mlm_span: bool = True                         # n-gram (wwm-analog) masking
    pretrain_limit: Optional[int] = None          # cap pretrain texts (tests)
    pretrain_ckpt_every: Optional[int] = None     # epoch-curve checkpoints
    sft_epochs: int = 0                           # supervised pretrain stage:
                                                  # epochs over the ~30k labeled
                                                  # examples outside the
                                                  # fine-tune slice (0 = off)
    sft_lr: float = 3e-5                          # its peak learning rate
    init_head: bool = False                       # --init_from also restores
                                                  # pooler+classifier (for
                                                  # supervised-pretrain ckpts)

    # --- optimization (single-gpu-cls.py:86-97,193-205) ---
    learning_rate: float = 3e-5
    label_smoothing: float = 0.0                  # CE target smoothing eps
    ema_decay: float = 0.0                        # >0 keeps an exponential
                                                  # moving average of params
                                                  # on device; eval/best/
                                                  # checkpoint use the EMA
                                                  # weights (jit dp/zero/tp/
                                                  # ep strategies)
    lr_schedule: Optional[str] = None             # warmup_linear|warmup_cosine
    warmup_ratio: float = 0.06                    # fraction of total steps
    weight_decay: float = 0.01
    adam_b1: float = 0.9
    adam_b2: float = 0.999
    adam_eps: float = 1e-6
    epochs: int = 1
    seed: int = 123

    # --- eval / checkpoint ---
    eval_step: int = 50                           # multi-gpu-distributed-cls.py:252
    dev: bool = False                             # eval during training (default off)
    output_dir: str = "output"
    ckpt_name: Optional[str] = None               # default: "<strategy>-cls.msgpack"

    # --- TPU-native knobs (replace AMP / ZeRO / launcher flags) ---
    dtype: str = "float32"                        # "bfloat16" = the AMP analog
    grads_dtype: str = "param"                    # "param": fp32 grads (default).
                                                  # "compute": kernel grads
                                                  # materialize in the compute
                                                  # dtype — measured NEUTRAL
                                                  # to -6% on v5e (XLA re-fuses
                                                  # the assembly worse); kept
                                                  # for A/B (results/
                                                  # profile_r05.json)
    rng_impl: str = "rbg"                         # dropout PRNG (utils.seeding.train_key)
    strategy: str = "single"                      # single|pmap|dp|shardmap|zero|...
    mode: str = "dp"                              # spawn launcher sharding mode:
                                                  # dp|zero|tp|ep (shared runner)
                                                  # or pp (pipeline runner) —
                                                  # lets ONE multi-process
                                                  # launcher execute any
                                                  # placement, incl. shards
                                                  # spanning process boundaries
    remat: bool = False                           # activation checkpointing (ZeRO analog)
    offload_opt_state: bool = False               # Adam moments in host RAM
                                                  # (DeepSpeed offload analog;
                                                  # ~4x step cost, frees ~8
                                                  # bytes/param of HBM)
    attention_impl: str = "auto"                  # auto|xla|pallas (CLI alias
                                                  # --attn_impl).  auto =
                                                  # the measured routing:
                                                  # segment-native pallas
                                                  # flash attention for
                                                  # PACKED batches on a TPU
                                                  # backend (no [B,1,S,S]
                                                  # segment_bias in HBM),
                                                  # XLA elsewhere; dropout
                                                  # and non-128-tiling
                                                  # widths always take XLA
                                                  # (ops.attention
                                                  # .routed_impl)
    fused_ce: str = "auto"                        # auto|xla|pallas: fused
                                                  # classifier-projection +
                                                  # weighted-CE kernel in
                                                  # the train step (ops.
                                                  # fused_ce; logits never
                                                  # round-trip HBM).  auto =
                                                  # pallas on TPU, XLA
                                                  # reference path elsewhere
    serve_dtype: str = "auto"                     # serve forward precision:
                                                  # auto (= --dtype, legacy)
                                                  # | bf16 | int8 (per-
                                                  # channel int8 weights +
                                                  # bf16 activations,
                                                  # serve/quant.py; artifact
                                                  # via scripts/
                                                  # quantize_ckpt.py)
    scan_unroll: Optional[int] = None             # layer-scan unroll; None =
                                                  # full (14% faster step,
                                                  # measured), 1 = lax.scan
                                                  # (flat compile time)
    fuse_steps: int = 1                           # K optimizer steps per dispatch
    num_devices: Optional[int] = None             # cap mesh size (None = all)
    microbatches: int = 4                         # pipeline (pp) microbatch
                                                  # count; bubble is
                                                  # (S-1)/(M+S-1)
    mesh_shape: Optional[dict] = None             # axis name -> size, -1 infers
                                                  # one; the framework shards
                                                  # over "data" (all
                                                  # strategies), "seq" (sp),
                                                  # and "model" (tp), e.g.
                                                  # {"data": 2, "model": 4}
    moe_dispatch: Optional[str] = None            # grouped|dense (None =
                                                  # model-config default;
                                                  # models/config.py)
    moe_capacity_factor: Optional[float] = None   # grouped-dispatch slots
                                                  # per expert multiplier
    moe_top_k: Optional[int] = None               # experts combined/token
    moe_experts: Optional[int] = None             # expert count override
                                                  # (scaling experiments)
    gelu: Optional[str] = None                    # erf|tanh activation
                                                  # (None = model-config
                                                  # default "erf"; tanh
                                                  # measured +7% step rate,
                                                  # models/config.py)
    accel_config: Optional[str] = None            # Accelerator machine-config
                                                  # file (JSON/YAML, the
                                                  # default_config.yaml
                                                  # analog — accel.py)
    length_mode: str = "auto"                     # length-aware training
                                                  # (data/sampler.py):
                                                  # full (pad every batch to
                                                  # max_seq_len — reference
                                                  # semantics) | bucket
                                                  # (length-grouped batches
                                                  # padded to the smallest
                                                  # covering bucket) | pack
                                                  # (multiple examples per
                                                  # row, block-diagonal
                                                  # attention).  auto = full:
                                                  # bucket/pack change batch
                                                  # COMPOSITION (not per-
                                                  # example math), so they
                                                  # are opt-in; bench.py
                                                  # --length measures the win
    length_buckets: str = "32,64,128"             # bucket widths; values over
                                                  # max_seq_len are dropped
                                                  # and max_seq_len is always
                                                  # the last bucket
    pack_max_segments: int = 16                   # examples per packed row
                                                  # cap (static shape of the
                                                  # per-segment channels) at
                                                  # the 128-token base width;
                                                  # wider rows scale linearly
                                                  # (data.packing.segment_cap)
    serve_long_widths: str = ""                   # chunked-prefill widths for
                                                  # the online batcher, e.g.
                                                  # "512,1024": requests over
                                                  # the pack width ride
                                                  # long-width packed flushes
                                                  # interleaved behind short
                                                  # traffic (serve/batcher.py;
                                                  # "" = long requests
                                                  # truncate at the largest
                                                  # bucket, the legacy path)
    decode_slots: int = 8                         # generative serving
                                                  # (serve/decode.py): KV-
                                                  # cache slots = the fixed
                                                  # decode batch rows;
                                                  # continuous batching
                                                  # keeps them full
    decode_max_len: int = 0                       # per-slot KV positions
                                                  # (prompt + generated);
                                                  # 0 = max_seq_len
    max_new_tokens: int = 32                      # default generation
                                                  # budget per stream
    kv_dtype: str = "auto"                        # KV-cache precision:
                                                  # auto (= the serve
                                                  # compute dtype) | fp32 |
                                                  # bf16 | int8 (per-
                                                  # channel scale tables —
                                                  # calibrated at warmup or
                                                  # loaded from scripts/
                                                  # quantize_ckpt.py
                                                  # --kv_calib)
    kv_hbm_mb: float = 0.0                        # declared KV-cache HBM
                                                  # budget per decode
                                                  # engine (obs.memory.
                                                  # KVBudget): caps slots
                                                  # (slot layout) or pages
                                                  # (paged layout) at
                                                  # construction, loud
                                                  # refusal (never OOM) at
                                                  # admission; 0 = off
    kv_layout: str = "paged"                      # decode KV cache layout:
                                                  # paged (page allocator +
                                                  # refcounted prefix
                                                  # sharing, serve/kvpage.
                                                  # py) | slots (the PR-14
                                                  # per-stream stripes —
                                                  # kept as the capacity/
                                                  # parity baseline)
    kv_page_sz: int = 16                          # paged layout: KV
                                                  # positions per page (the
                                                  # sharing granularity —
                                                  # prefixes share in whole
                                                  # pages, copy-on-write at
                                                  # the divergence page)
    prefetch: int = 2                             # loader collation lookahead
    pipeline: str = "auto"                        # input pipeline (data/
                                                  # pipeline.py): auto|
                                                  # resident (split held in
                                                  # HBM, zero per-step
                                                  # transport)|prefetch
                                                  # (double-buffered upload)
                                                  # |sync (reference-style
                                                  # put-in-loop).  auto =
                                                  # resident when eligible,
                                                  # else prefetch
    pipeline_hbm_mb: int = 128                    # resident-mode budget: the
                                                  # encoded split must fit
                                                  # this many MB of HBM
    log_every: int = 1
    trace: bool = False                           # obs span tracing (pdnlp_
                                                  # tpu.obs): per-step phase
                                                  # spans + breakdown +
                                                  # regression detector;
                                                  # off by default, <2%
                                                  # steps/s when on
                                                  # (bench.py --trace)
    trace_dir: Optional[str] = None               # span files (trace_proc
                                                  # <i>.jsonl); default
                                                  # <output_dir>/trace
    metrics_port: int = 0                         # live telemetry (obs.
                                                  # exporter): Prometheus
                                                  # /metrics + JSON
                                                  # /healthz on this port,
                                                  # served off the hot
                                                  # path; 0 = off.  Also
                                                  # turns on the flight
                                                  # recorder (default
                                                  # path under
                                                  # <output_dir>/telemetry)
    flight_recorder: Optional[str] = None         # bounded JSONL a
                                                  # background thread
                                                  # appends metric
                                                  # snapshots to, so a
                                                  # SIGKILL'd run leaves
                                                  # evidence; settable
                                                  # without --metrics_port
    profile_dir: Optional[str] = None             # jax.profiler trace output
    warmup_compile: bool = False                  # AOT-compile steps before
                                                  # the timed epoch (bench
                                                  # methodology; the warm-
                                                  # CUDA-context analog)
    probe_steps: int = 0                          # N re-fed steps probed
                                                  # before the epoch; prints
                                                  # the controlled steps/s
                                                  # (run_matrix's probe col)

    # --- multi-host runtime (NCCL/TCPStore rendezvous analog) ---
    coordinator_address: Optional[str] = None     # e.g. "localhost:12345"
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    # --- failure detection / elastic restart (parallel/watchdog.py) ---
    resume_every: Optional[int] = None            # full-state snapshot every N steps
    resume_from: Optional[str] = None             # snapshot path, or "auto"
    ckpt_async: bool = True                       # resume snapshots: device->
                                                  # host copy in-loop, msgpack
                                                  # + atomic publish on a
                                                  # writer thread (train/
                                                  # async_ckpt.py; at most
                                                  # one save in flight).
                                                  # false = synchronous save
                                                  # back in the step loop
    heartbeat_interval: float = 0.0               # seconds; 0 = no heartbeat
    elastic: bool = False                         # spawn launcher: restart on failure
    elastic_shrink: bool = True                   # evict DEAD ranks and
                                                  # resume the gang at the
                                                  # surviving width (the
                                                  # degrade-don't-die
                                                  # policy); false = always
                                                  # restart at full width
                                                  # (bitwise layout-matched
                                                  # continuation)
    min_processes: int = 1                        # never shrink the gang
                                                  # below this width
    stall_timeout: float = 300.0                  # launcher stall detector
                                                  # (pre-first-beat grace is
                                                  # 4x this, covering compile)
    max_restarts: int = 2                         # gang restarts before giving up
    restart_backoff: float = 1.0                  # seconds before restart 1;
                                                  # doubles per restart
    restart_backoff_cap: float = 30.0             # exponential backoff ceiling

    def replace(self, **kw) -> "Args":
        return dataclasses.replace(self, **kw)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, ensure_ascii=False)

    @classmethod
    def from_json(cls, s: str) -> "Args":
        d = json.loads(s)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def ckpt_path(self, name: Optional[str] = None) -> str:
        """One checkpoint per strategy, like the reference's per-script
        ``*.pt`` files that ``test.py:85-94`` sweeps."""
        return os.path.join(self.output_dir,
                            name or self.ckpt_name or f"{self.strategy}-cls.msgpack")

    def resume_path(self) -> str:
        """Where periodic full-state snapshots live (``resume_from="auto"``)."""
        if self.resume_from and self.resume_from != "auto":
            return self.resume_from
        return os.path.join(self.output_dir, f"resume-{self.strategy}.msgpack")


def add_dataclass_args(parser, cls, defaults=None) -> None:
    """Add one typed ``--field`` per dataclass field: Optional[T] unwraps to
    T, bools accept 1/true/yes, and structured fields (dicts/lists) parse as
    JSON — loud failure on malformed input beats silent str-typing.  Shared
    by ``parse_cli`` (Args) and the AutoTrainer entrypoint (TrainerArgs)."""
    import types
    import typing

    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        if defaults is not None:
            default = getattr(defaults, f.name)
        elif f.default is not dataclasses.MISSING:
            default = f.default
        elif f.default_factory is not dataclasses.MISSING:
            default = f.default_factory()
        else:
            default = None  # required field: argparse surfaces the miss
        hint = hints.get(f.name, str)
        # Unwrap Optional[T] so `--num_processes 4` parses as int, not "4".
        if typing.get_origin(hint) in (typing.Union, types.UnionType):
            inner = [a for a in typing.get_args(hint) if a is not type(None)]
            hint = inner[0] if len(inner) == 1 else str
        if hint is bool:
            parser.add_argument(f"--{f.name}",
                                type=lambda s: s.lower() in ("1", "true", "yes"),
                                default=default)
        elif hint in (int, float, str):
            parser.add_argument(f"--{f.name}", type=hint, default=default)
        else:
            parser.add_argument(f"--{f.name}", type=json.loads, default=default)


def enable_compilation_cache(args: "Args") -> None:
    """Point XLA's persistent compilation cache at ``<output_dir>/xla_cache``
    so repeat runs of any entrypoint skip the 30-60s first compile (the
    reference's warm-CUDA-context analog).  Safe to call before or after
    backend init; harmless on CPU."""
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(args.output_dir, "xla_cache"))
    except Exception:
        pass  # never let cache plumbing break a training run


def pop_cli_flag(argv, name: str, default=None, cast=str):
    """``(argv_without_the_pair, value)`` for a script-local ``--name value``
    flag that is NOT an ``Args`` field — shared by ``serve_tpu.py`` and
    ``bench.py --serve`` so the extraction behavior can't drift.  The
    returned argv is a new list; the input is not mutated."""
    argv = list(argv)
    if name in argv:
        i = argv.index(name)
        if i + 1 >= len(argv):
            raise SystemExit(f"{name} requires a value")
        value = cast(argv[i + 1])
        return argv[:i] + argv[i + 2:], value
    return argv, default


def parse_cli(argv=None, base: Optional[Args] = None) -> Args:
    """``--key value`` CLI overrides onto an ``Args`` (argparse analog of
    ``multi-gpu-distributed-cls.py:374-381``)."""
    import argparse

    p = argparse.ArgumentParser()
    add_dataclass_args(p, Args, defaults=base or Args())
    # short alias for the kernel escape hatch (README "Kernels" section);
    # SUPPRESS keeps the primary --attention_impl default authoritative
    p.add_argument("--attn_impl", dest="attention_impl", type=str,
                   default=argparse.SUPPRESS,
                   help="alias for --attention_impl (auto|xla|pallas)")
    ns = p.parse_args(argv)
    args = Args(**vars(ns))
    enable_compilation_cache(args)
    return args
