"""pdnlp_tpu — a TPU-native (JAX/XLA/pjit/Pallas) distributed-NLP training
framework with the capabilities of ``mosscc/pytorch-distributed-NLP``.

The reference is a matrix of ~10 CUDA/torch training strategies for a Chinese
BERT emotion classifier (see ``/root/reference/README.md:10-20``).  This
package re-designs that capability matrix TPU-first:

- NCCL collectives            -> XLA collectives over the ICI mesh
  (``jax.lax.psum`` / ``all_gather``), see :mod:`pdnlp_tpu.parallel`.
- ``DistributedSampler``      -> per-host shards of a seeded global
  permutation, see :mod:`pdnlp_tpu.data.sampler`.
- ``torch.cuda.amp``          -> XLA bfloat16 compute policy
  (:mod:`pdnlp_tpu.train.precision`) — no loss scaling needed on TPU.
- DeepSpeed ZeRO-3            -> parameter/grad/optimizer-state sharding
  along the data axis via ``NamedSharding`` (:mod:`pdnlp_tpu.parallel.sharding`).
- HF ``BertForSequenceClassification`` -> an in-repo pure-functional JAX
  BERT (:mod:`pdnlp_tpu.models.bert`: pytree params, ``lax.scan`` over
  stacked layers) with the attention op in :mod:`pdnlp_tpu.ops`.
"""

__version__ = "0.1.0"
