"""Long-lived inference engine: checkpoint -> jitted sharded forward.

``predict_tpu.py``'s original inline ``@jax.jit`` forward re-traced on every
new input shape and re-assembled the model per process.  The engine keeps
one process-lifetime forward instead:

- **checkpoint load** goes through ``pdnlp_tpu.train.checkpoint`` —
  shape-validated against the model template, so a ``bert-tiny`` file into a
  ``bert-base`` engine fails loudly at load, not as an XLA error mid-request
  (``load_raw`` pre-checks the embedding shape before any device transfer);
- **placement** rides the existing ``parallel.mesh``/``sharding`` machinery:
  params replicated over the data axis, batches split along it — inference
  is embarrassingly data-parallel, so the DDP layout is the right one (pass
  ``mesh=None`` for plain single-device jit, bitwise-identical to the old
  ``predict_tpu.py`` forward);
- **compile cache**: ``jax.jit`` already caches traces by shape, but
  silently — the engine tracks every ``(bucket_seq_len, batch_rows)`` shape
  it has served and counts hits/misses, and a counter INSIDE the traced
  function counts actual retraces (the Python body only runs when XLA
  traces), so "steady-state serving never retraces" is a measured property,
  not a hope.  ``warmup()`` pre-traces every bucket shape so the first real
  request never pays a compile.

Params can be swapped (``load_checkpoint``) without invalidating the cache:
the trace depends on shapes only, and every strategy checkpoint shares the
template's shapes — that is what lets ``predict_tpu.py`` sweep N checkpoints
through ONE compiled forward.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from pdnlp_tpu.data.collate import pad_ids_to_bucket
from pdnlp_tpu.data.tokenizer import WordPieceTokenizer, get_or_build_vocab
from pdnlp_tpu.models import bert, get_config
from pdnlp_tpu.models.config import args_overrides
from pdnlp_tpu.serve.metrics import ServeMetrics
from pdnlp_tpu.train import checkpoint as ckpt
from pdnlp_tpu.train.precision import resolve_dtype


class InferenceEngine:
    def __init__(self, args, tokenizer: Optional[WordPieceTokenizer] = None,
                 *, mesh=None, metrics: Optional[ServeMetrics] = None,
                 tracer=None):
        """``args`` supplies model/dtype/vocab knobs (an ``utils.config.Args``).

        ``mesh=None`` means plain ``jax.jit`` on the default device — the
        exact forward ``predict_tpu.py`` always ran.  With a mesh, batches
        shard along ``data`` and batch rows are padded up to a multiple of
        the axis size (``rows_multiple``).

        ``tracer`` (``pdnlp_tpu.obs``): the engine emits one span per
        executed batch — ``compile`` for a first-seen ``(seq, rows)`` shape
        (the trace shows exactly when/where retraces happen), ``forward``
        for a cache hit; both carry the serve ``dtype`` (and resolved
        ``attn_impl``) as span attrs so kernel/precision adoption is
        visible in ``trace_tpu.py summarize``/``diff``.  Defaults to the
        process-global tracer, configured from ``args`` so
        ``serve_tpu.py --trace true`` just works.

        ``args.serve_dtype`` picks the forward precision independently of
        the training dtype: ``"auto"`` follows ``args.dtype`` (the legacy
        behavior), ``"bf16"`` forces bfloat16 compute, ``"int8"`` serves
        per-channel int8 weights with bf16 activations (``serve.quant``) —
        ``load_checkpoint`` quantizes a float checkpoint on the fly or
        loads a prebuilt ``scripts/quantize_ckpt.py`` artifact directly.
        """
        from pdnlp_tpu.obs.trace import configure_from_args

        self.tracer = tracer if tracer is not None \
            else configure_from_args(args)
        self.args = args
        self.tokenizer = tokenizer or WordPieceTokenizer(get_or_build_vocab(args))
        self.cfg = get_config(args.model, vocab_size=self.tokenizer.vocab_size,
                              num_labels=args.num_labels, dropout=args.dropout,
                              attn_dropout=args.attn_dropout,
                              **args_overrides(args))
        self.serve_dtype = getattr(args, "serve_dtype", "auto") or "auto"
        if self.serve_dtype not in ("auto", "bf16", "int8"):
            raise ValueError("serve_dtype must be 'auto', 'bf16' or 'int8', "
                             f"got {self.serve_dtype!r}")
        if self.serve_dtype == "auto":
            self.dtype = resolve_dtype(args.dtype)
        else:  # int8 weights compute against bf16 activations
            self.dtype = resolve_dtype("bfloat16")
        # the impl the jitted forward routes to at the engine's max width
        # (deterministic serve: no dropout) — the headline the bench JSONs
        # report.  Routing is PER BUCKET WIDTH (sub-128 buckets fall back
        # to XLA), so spans stamp :meth:`routed_attn` of their actual seq,
        # never this attribute.
        from pdnlp_tpu.ops.attention import routed_impl_cached

        self._attn_requested = args.attention_impl
        self._impl_by_seq: Dict[int, str] = {}
        # routed directly (not via routed_attn) so _impl_by_seq records
        # only widths actually served, never the construction-time headline
        self.attn_impl = routed_impl_cached(self._attn_requested,
                                            args.max_seq_len)
        self.mesh = mesh
        self.metrics = metrics or ServeMetrics()
        self.rows_multiple = int(mesh.shape.get("data", 1)) if mesh else 1
        # the template: init-shaped params every checkpoint must match
        # (predict/test sweep semantics — setup_model's init, minus the
        # optimizer state serving never needs).  int8 mode quantizes the
        # template too, so the params' pytree STRUCTURE is identical before
        # and after every load — checkpoint swap stays retrace-free.
        self._template = bert.init_params(jax.random.key(args.seed), self.cfg)
        # the serving-form template is also the int8 swap template — built
        # once here, not re-quantized on every load_checkpoint
        self._serving_template = self._serving_form(self._template)
        self.params = self._put(self._serving_template)
        self.checkpoint_path: Optional[str] = None
        self._seen_shapes: set = set()
        # extra attrs stamped on every forward/compile span — the replica
        # router labels each engine with its rank here, so per-replica
        # phase tables (obs.phases) can attribute engine time per replica
        self.span_attrs: Dict[str, object] = {}
        # HBM accounting over THIS engine's device slice (mesh devices, or
        # every local device for plain jit): sampled per executed batch
        # when tracing is on, and on demand for serve snapshots /
        # /metrics.  Graceful no-op (one flag read per call) on backends
        # without memory_stats — CPU tests run unchanged.
        from pdnlp_tpu.obs.memory import MemorySampler

        self.memory = MemorySampler(
            devices=list(mesh.devices.flat) if mesh is not None else None)

        metrics_ref = self.metrics
        attn_impl = args.attention_impl

        def _forward(params, batch):
            # Python body only executes while tracing: this IS the retrace
            # counter (jax.jit replays the compiled program otherwise)
            metrics_ref.retraces.inc()
            return bert.classify(params, self.cfg, batch, dtype=self.dtype,
                                 deterministic=True, attn_impl=attn_impl)

        if mesh is not None:
            from pdnlp_tpu.parallel.sharding import batch_sharding, replicated

            self._jit_forward = jax.jit(
                _forward,
                in_shardings=(replicated(mesh),
                              batch_sharding(mesh)),
                out_shardings=replicated(mesh),
            )
        else:
            self._jit_forward = jax.jit(_forward)

    # ------------------------------------------------------------ params
    def _put(self, host_params):
        if self.mesh is not None:
            from pdnlp_tpu.parallel.sharding import replicated

            return jax.device_put(host_params, replicated(self.mesh))
        return jax.device_put(host_params)

    def _serving_form(self, host_params):
        """Host params -> what this engine actually serves: quantized
        (``serve.quant``) under ``--serve_dtype int8``, unchanged
        otherwise."""
        if self.serve_dtype != "int8":
            return host_params
        from pdnlp_tpu.serve.quant import quantize_params

        return quantize_params(host_params)

    def load_checkpoint(self, path: str) -> None:
        """Swap in a strategy checkpoint (shape-validated; cache survives).

        ``ckpt.load_params`` validates every leaf shape against the model
        template and raises a per-leaf ``ValueError`` on mismatch — all
        before any device transfer, so a wrong ``--model`` fails fast with
        one file parse (``ckpt.load_raw`` exists for template-free
        inspection when the error message isn't enough).

        Under ``--serve_dtype int8`` both artifact kinds load: a float
        checkpoint is quantized on the fly (identical math to the offline
        pass), and a ``scripts/quantize_ckpt.py`` artifact — recognized by
        its ``qscale`` leaves — is shape-validated against the QUANTIZED
        template and served as-is.  A quantized artifact into a float
        engine fails loudly (it cannot be de-quantized back to the
        training dtype losslessly; point ``--serve_dtype int8`` at it).
        """
        from pdnlp_tpu.serve.quant import is_quantized, quantize_params

        # ONE file read + msgpack decode: the raw tree feeds both the
        # quantization probe and the template-validated restore
        raw = ckpt.load_raw(path)
        if self.serve_dtype == "int8":
            if is_quantized(raw):
                host = ckpt.from_restored(
                    raw, self._serving_template, path=path)
            else:
                host = quantize_params(
                    ckpt.from_restored(raw, self._template, path=path))
        else:
            if is_quantized(raw):
                raise ValueError(
                    f"checkpoint {path!r} is an int8 artifact "
                    "(quantize_ckpt.py) but this engine serves "
                    f"{self.serve_dtype!r} — start it with --serve_dtype "
                    "int8, or point it at the float checkpoint")
            host = ckpt.from_restored(raw, self._template, path=path)
        self.params = self._put(host)
        self.checkpoint_path = path

    def _telemetry_attrs(self, request_ids) -> Dict:
        """Per-batch span extras: bounded ``request_ids`` exemplars (the
        join key from a slow batch back to concrete request hop chains)
        and the device slice's peak HBM — sampled BEFORE the span opens
        (a pure allocator-counter read, no sync), only while tracing."""
        extra: Dict[str, object] = {}
        if not self.tracer.enabled:
            return extra
        if request_ids:
            from pdnlp_tpu.obs.request import EXEMPLAR_CAP

            extra["request_ids"] = list(request_ids)[:EXEMPLAR_CAP]
        mem = self.memory.sample()
        if mem is not None:
            extra["hbm_peak"] = mem["device_peak_bytes"]
        return extra

    def memory_snapshot(self) -> Dict:
        """JSON-ready HBM state of this engine's device slice (serve
        snapshots / the live exporter); ``{"supported": False}`` on CPU."""
        return self.memory.snapshot()

    def beat_memory(self) -> Dict:
        """The ``hbm``/``hbm_peak`` heartbeat fields (replica workers fold
        these into their watchdog beats)."""
        return self.memory.beat_payload()

    # ----------------------------------------------------------- forward
    def infer(self, batch: Dict[str, np.ndarray],
              request_ids=None) -> np.ndarray:
        """Fixed-shape batch -> host logits ``[rows, num_labels]`` (fp32).

        Tracks the compiled-shape cache: key is the batch's
        ``(seq_len, rows)``; a first-seen key is a miss (and will trace),
        every later one a hit that replays the compiled program.
        ``request_ids``: optional riding-request IDs, stamped (bounded)
        on the span as exemplars.
        """
        rows, seq = batch["input_ids"].shape
        key = (int(seq), int(rows))
        if key in self._seen_shapes:
            self.metrics.cache_hits.inc()
            span_name = "forward"
        else:
            self.metrics.cache_misses.inc()
            self._seen_shapes.add(key)
            span_name = "compile"  # first call at this shape traces
        fwd = {k: batch[k] for k in ("input_ids", "attention_mask",
                                     "token_type_ids")}
        # token-level occupancy: the padded path's honest waste number —
        # real tokens over the rows x width slots this forward pays for.
        # Compile (= warmup) batches are dummies at ~0.002 fill and are
        # excluded — every fill surface (these histograms, the replica
        # metrics, the phases fill column) must report steady state
        fill = float(batch["attention_mask"].sum()) / float(rows * seq)
        if span_name == "forward":
            self.metrics.fill_ratio.observe(fill)
            self.metrics.padding_waste.observe(1.0 - fill)
        if self.mesh is not None:
            from pdnlp_tpu.parallel.sharding import batch_sharding

            sh = batch_sharding(self.mesh)
            fwd = {k: jax.make_array_from_process_local_data(sh, v)
                   for k, v in fwd.items()}
        # the device_get fetch inside the span IS the completion barrier:
        # serve spans measure request-visible latency, dispatch + compute.
        # dtype/attn_impl attrs make int8/pallas adoption visible in
        # trace_tpu.py summarize and the trace-diff gate.
        with self.tracer.span(span_name, seq=int(seq), rows=int(rows),
                              dtype=self.dtype_label, fill=round(fill, 4),
                              attn_impl=self.routed_attn(int(seq)),
                              **self._telemetry_attrs(request_ids),
                              **self.span_attrs):
            logits = self._jit_forward(self.params, fwd)
            out = np.asarray(jax.device_get(logits))
        return out

    #: the channels a packed serve batch carries into the jitted forward —
    #: ``data.packing.pack_id_lists``'s layout, and exactly what
    #: ``models.bert.classify`` keys its packed (per-segment) program on
    PACKED_CHANNELS = ("input_ids", "attention_mask", "token_type_ids",
                       "segment_ids", "position_ids", "cls_positions")

    def infer_packed(self, batch: Dict[str, np.ndarray],
                     segments: int = 0, request_ids=None) -> np.ndarray:
        """Packed batch (``data.packing.pack_id_lists``) -> host logits
        ``[rows, max_segments, num_labels]`` (fp32) — one forward serving
        many requests per row.

        The compile-cache key is ``(seq, rows, "packed")``: every packed
        batch the batcher emits has the SAME fixed shape (rows x the pack
        width, segment capacity included), so the packed path holds exactly
        one compiled program and is retrace-free by construction once
        :meth:`warmup_packed` has traced it.  Spans carry ``packed``/
        ``fill``/``segments`` attrs so per-replica fill is visible in
        ``trace_tpu.py summarize``; ``segments`` is the number of real
        requests riding the batch.
        """
        rows, seq = batch["input_ids"].shape
        key = (int(seq), int(rows), "packed")
        if key in self._seen_shapes:
            self.metrics.cache_hits.inc()
            span_name = "forward"
        else:
            self.metrics.cache_misses.inc()
            self._seen_shapes.add(key)
            span_name = "compile"
        fill = float(batch["attention_mask"].sum()) / float(rows * seq)
        if span_name == "forward":  # warmup dummies stay out of steady state
            self.metrics.fill_ratio.observe(fill)
            self.metrics.padding_waste.observe(1.0 - fill)
        fwd = {k: batch[k] for k in self.PACKED_CHANNELS}
        if self.mesh is not None:
            from pdnlp_tpu.parallel.sharding import batch_sharding

            sh = batch_sharding(self.mesh)
            fwd = {k: jax.make_array_from_process_local_data(sh, v)
                   for k, v in fwd.items()}
        with self.tracer.span(span_name, seq=int(seq), rows=int(rows),
                              packed=True, fill=round(fill, 4),
                              segments=int(segments),
                              dtype=self.dtype_label,
                              attn_impl=self.routed_attn(int(seq),
                                                         segmented=True),
                              **self._telemetry_attrs(request_ids),
                              **self.span_attrs):
            logits = self._jit_forward(self.params, fwd)
            out = np.asarray(jax.device_get(logits))
        return out

    def infer_ids(self, id_lists: Sequence[Sequence[int]], seq_len: int,
                  rows: int = 0, request_ids=None) -> np.ndarray:
        """Ragged id-lists -> logits for the REAL rows only (filler dropped)."""
        rows = self.pad_rows(max(rows, len(id_lists)))
        batch = pad_ids_to_bucket(id_lists, seq_len, rows,
                                  pad_id=self.tokenizer.pad_id)
        return self.infer(batch, request_ids=request_ids)[: len(id_lists)]

    def classify_texts(self, texts: Sequence[str],
                       seq_len: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """(preds, logits) for a list of texts at one padded length —
        the single-call surface ``predict_tpu.py`` uses (``seq_len`` defaults
        to ``args.max_seq_len``, the exact legacy padding)."""
        seq_len = seq_len or self.args.max_seq_len
        ids = self.tokenizer.encode_ragged(texts, seq_len)
        logits = self.infer_ids(ids, seq_len)
        return np.argmax(logits, axis=-1), logits

    def routed_attn(self, seq: int, segmented: bool = False) -> str:
        """The attention impl a forward at this bucket width actually
        routes to (``ops.attention.routed_impl_cached``) — a requested
        pallas falls back to XLA below the 128-wide kernel blocks, so
        per-seq routing is what spans and per-bucket reporting must carry,
        not the max-width :attr:`attn_impl`.  ``segmented=True`` is the
        packed forward's route (block-diagonal mask from segment IDs —
        the segment-native pallas kernel where it applies).
        ``_impl_by_seq`` records the widths THIS engine served
        (:attr:`attn_impl_by_seq`); the memoization itself lives at the
        routing point."""
        from pdnlp_tpu.ops.attention import routed_impl_cached

        impl = routed_impl_cached(self._attn_requested, seq,
                                  segmented=segmented)
        self._impl_by_seq.setdefault(seq, impl)
        return impl

    @property
    def attn_impl_by_seq(self) -> Dict[int, str]:
        """{bucket width: routed impl} for every width this engine has
        routed so far — the honest per-bucket adoption record the bench
        JSONs embed alongside the max-width headline."""
        return dict(self._impl_by_seq)

    @property
    def dtype_label(self) -> str:
        """The serving precision as a span/JSON label: ``"int8"`` for
        weight-quantized serving, else the activation dtype name."""
        if self.serve_dtype == "int8":
            return "int8"
        import numpy as _np

        return _np.dtype(self.dtype).name

    # ------------------------------------------------------------ shapes
    def pad_rows(self, n: int) -> int:
        """Round a row count up to the mesh's data-axis multiple."""
        m = self.rows_multiple
        return max(m, ((n + m - 1) // m) * m)

    def warmup(self, buckets: Sequence[int], rows: int) -> None:
        """Pre-trace one dummy batch per bucket so live traffic never
        compiles.  The warmup calls count as the cache's misses; everything
        after is expected to hit."""
        rows = self.pad_rows(rows)
        for seq in buckets:
            self.infer_ids([[self.tokenizer.cls_id, self.tokenizer.sep_id]],
                           seq, rows)

    def warmup_packed(self, seq_len: int, rows: int,
                      max_segments: int) -> None:
        """Pre-trace the ONE packed shape (``(seq_len, rows, "packed")``):
        every packed batch the online path emits reuses this compiled
        program, so after this call the packed path cannot retrace."""
        from pdnlp_tpu.data.packing import pack_id_lists

        batch, _ = pack_id_lists(
            [[self.tokenizer.cls_id, self.tokenizer.sep_id]], seq_len,
            self.pad_rows(rows), max_segments, pad_id=self.tokenizer.pad_id)
        self.infer_packed(batch, segments=1)
