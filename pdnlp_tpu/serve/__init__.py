"""Batched inference serving — the subsystem ``predict_tpu.py`` lacked.

Training in this repo already kills the two costs that dominate BERT-class
serving (XLA retraces on ragged shapes; idle accelerator time between
requests) — this package applies the same treatment to inference:

- :mod:`pdnlp_tpu.serve.engine` — a long-lived jitted sharded forward over
  the existing mesh/sharding stack, with a compiled-function cache keyed on
  ``(bucket_seq_len, batch_rows)`` so steady-state serving never retraces;
- :mod:`pdnlp_tpu.serve.batcher` — bounded request queue with dynamic
  micro-batching (flush on size or ``max_wait_ms``), sequence-length
  bucketing, backpressure and per-request deadlines; ``serve_pack``
  bin-packs requests many-per-row into fixed token-budget packed batches
  (throughput scales with tokens, not requests);
- :mod:`pdnlp_tpu.serve.router` — N engine replicas behind tiered admission
  (backpressure -> shed -> reject), least-loaded dispatch, heartbeat-based
  health ejection with requeue/retry, warmup-gated reintegration, and
  rolling checkpoint hot-swap (``serve_tpu.py --replicas N``);
- :mod:`pdnlp_tpu.serve.metrics` — latency/occupancy/cache observability
  (plus router/per-replica instruments), JSON-snapshot compatible with the
  ``results/`` artifacts;
- :mod:`pdnlp_tpu.serve.offline` — high-throughput whole-file scoring over
  the same bucketing (the deterministic surface tests and ``bench.py`` use);
- :mod:`pdnlp_tpu.serve.controller` — the feedback control plane: a
  :class:`ServeController` thread that closes the telemetry loop, auto-
  tuning replica count (warm-standby scaling), ``hedge_ms``, the flush age
  and the admission thresholds through one decision-recording, auto-
  reverting ``_actuate`` choke point (``serve_tpu.py --controller on``);
- :mod:`pdnlp_tpu.serve.replay` — trace-driven load replay: recorded
  request-hop chains reconstructed into arrival schedules, reshaped
  (steady / diurnal ramp / flash crowd) and re-driven at 1x/5x/20x speed
  (``bench.py --replay``);
- :mod:`pdnlp_tpu.serve.decode` — generative decoding: a paged (default)
  or slot-indexed donated KV cache (optionally int8 against calibrated
  per-channel scale tables), bucketed prefill / one fixed-shape decode
  step, continuous batching with streaming responses, a declared KV HBM
  budget (``--kv_hbm_mb``), a decode replica router whose
  kill-recovery re-prefills orphan streams on survivors
  (``serve_tpu.py --decode``), and a :class:`DisaggDecodeRouter` that
  splits a paged fleet into prefill-role and decode-role engine pools
  with an audited KV page handoff and a live controller-driven pool
  split (``--disagg local|socket``);
- :mod:`pdnlp_tpu.serve.handoff` — the handoff wire: length-prefixed,
  CRC-checked socket framing (:class:`HandoffServer` /
  :class:`HandoffChannel`, per-frame acks, torn frames NACKed) moving
  exported page payloads between the disaggregated pools — the
  single-host rehearsal of a cross-process serving tier;
- :mod:`pdnlp_tpu.serve.kvpage` — the paged KV memory subsystem behind
  ``--kv_layout paged``: refcounted fixed-size page allocator with a
  free list, loud :class:`KVPagesExhausted` refusals, a leak-check
  ledger audit, and an LRU prefix index that shares repeated prompt
  prefixes across requests at page granularity (copy-on-write at the
  divergence page).

Entry point: ``serve_tpu.py`` at the repo root.
"""
from pdnlp_tpu.serve.batcher import (  # noqa: F401
    DEFAULT_BUCKETS, AdmissionControl, DeadlineExceeded, DynamicBatcher,
    LoadShedError, QueueFullError, pick_bucket, resolve_serve_pack,
)
from pdnlp_tpu.serve.controller import KnobSpec, ServeController  # noqa: F401
from pdnlp_tpu.serve.decode import (  # noqa: F401
    DecodeBatcher, DecodeEngine, DecodeRouter, DecodeStream,
    DisaggDecodeRouter, PagedDecodeEngine, PrefillWorker,
)
from pdnlp_tpu.serve.engine import InferenceEngine  # noqa: F401
from pdnlp_tpu.serve.kvpage import (  # noqa: F401
    KVPagesExhausted, PageAllocator, PrefixIndex,
)
from pdnlp_tpu.serve.fleet import (  # noqa: F401
    FleetRouter, ModelSpec, RolloutPlan, ShadowReport, drafter_spec,
    parse_fleet_spec, parse_speculate_spec,
)
from pdnlp_tpu.serve.metrics import (  # noqa: F401
    DecodeMetrics, FleetMetrics, ReplicaMetrics, RouterMetrics,
    ServeMetrics,
)
from pdnlp_tpu.serve.offline import score_texts  # noqa: F401
from pdnlp_tpu.serve.router import (  # noqa: F401
    ReplicaFailedError, ReplicaRouter,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "AdmissionControl",
    "DeadlineExceeded",
    "DecodeBatcher",
    "DecodeEngine",
    "DecodeMetrics",
    "DecodeRouter",
    "DecodeStream",
    "DynamicBatcher",
    "FleetMetrics",
    "FleetRouter",
    "InferenceEngine",
    "KVPagesExhausted",
    "KnobSpec",
    "LoadShedError",
    "ModelSpec",
    "PageAllocator",
    "PagedDecodeEngine",
    "PrefixIndex",
    "QueueFullError",
    "ReplicaFailedError",
    "ReplicaMetrics",
    "ReplicaRouter",
    "RolloutPlan",
    "RouterMetrics",
    "ServeController",
    "ServeMetrics",
    "ShadowReport",
    "drafter_spec",
    "parse_fleet_spec",
    "parse_speculate_spec",
    "pick_bucket",
    "resolve_serve_pack",
    "score_texts",
]
