"""Generative decode engine: sharded slot KV cache, prefill/decode split,
continuous batching.

The serving tier built since PR 6 scales a SCORER — one forward, one logit
row.  This module turns it into a text service, built on the observation
that autoregressive decode is memory-bandwidth-bound: tokens/s/chip is won
or lost on (a) never recomputing the prompt (the KV cache), (b) never
retracing (fixed shapes, donated buffers), and (c) never running the
decode batch partially empty (continuous batching).

- **slot-indexed KV cache**: one preallocated pair of ``[L, slots,
  max_len, N, D]`` buffers per engine (``models.decoder`` layout note),
  DONATED across steps — steady-state decode allocates nothing.  A slot
  is the unit of admission: a stream claims one at prefill, writes
  forward as it decodes, and frees it between steps when it finishes —
  slot reuse is ``form_packed_batch``'s row-reuse idea made stateful.
  On a mesh the slot axis shards over ``data`` like every serve batch.
- **prefill/decode split**: prompts execute as bucketed ``[prefill_rows,
  bucket]`` causal forwards riding the same compile-cache discipline as
  the classifier engine (one trace per bucket, warmup pre-traces all);
  their K/V scatter into claimed slots (``.at[slots].set`` with
  out-of-bounds filler rows DROPPED — filler never touches a live slot).
  Decode is ONE ``[slots, 1]`` program — retrace-free by the same
  construction as ``infer_packed``: after :meth:`DecodeEngine.warmup`
  there is exactly one compiled decode step and nothing live traffic
  does can create another.
- **continuous batching** (:class:`DecodeBatcher`): between decode steps,
  finished streams leave and waiting streams claim freed slots (prefill
  rides the same worker, so the decode batch is re-filled before the
  next step).  The batcher is the online analogue of the token-packing
  PR 9 shipped: capacity is measured in slots and tokens, occupancy is
  ``live/slots`` per step, and freed-slot reuse latency is a first-class
  metric.
- **int8 KV** rides the PR-6 per-channel machinery: the cache stores
  int8 against calibrated ``[L, N, D]`` scale tables
  (``models.decoder.calibrate_kv_scales``; offline artifact via
  ``scripts/quantize_ckpt.py --kv_calib``, self-calibration at warmup
  otherwise) — half (vs bf16) to a quarter (vs fp32) the cache traffic,
  which is the decode roofline.
- **KV HBM budget** (``--kv_hbm_mb``, ``obs.memory.KVBudget``): the
  declared budget caps the preallocation loudly at construction and
  refuses oversized streams at admission with the budget math
  (:class:`~pdnlp_tpu.obs.memory.KVBudgetExceeded`) — never an OOM three
  layers deep; live occupancy is a ``/metrics`` gauge.
- **replica failure** (:class:`DecodeRouter`): a dead decode worker's
  live + waiting streams re-prefill on survivors from ``prompt +
  emitted-so-far`` — greedy decode is deterministic, so the continuation
  emits exactly the tokens the dead replica would have (no duplicates,
  no losses; the chain shows ``requeue`` then a second ``prefill``).

- **speculative decoding** (draft-k / verify-1): a paired CHEAP engine
  (the fleet's ``cheap`` role) drafts k tokens per round with its own
  paged cache via k fixed-shape decode steps, then the primary scores
  all k+1 window positions in ONE prefill-shaped ``verify_ids`` call
  (``models.decoder.paged_verify_step``, compile key ``("verify",
  slots, k+1)`` — retrace-free by construction).  The longest accepted
  greedy prefix commits to both caches: the primary's commit IS the
  verify call's K/V written through the page table (rejected tail
  positions stay invisible behind the position mask and are overwritten
  in place next round), the drafter's rejected pages stay under the
  two-owner draft custody (``kvpage.draft_owner`` + ``transfer``) until
  a later round commits across them.  Greedy verification makes the
  emitted sequence IDENTICAL to primary-only decode — every emitted
  token is a primary argmax — which the bench gates stream-for-stream.
  A drafter death degrades the pair to primary-only decode (loud,
  decision-recorded); parity is unaffected because the primary cache
  already holds every committed token.

- **disaggregated prefill/decode pools** (:class:`PrefillWorker` +
  :class:`DisaggDecodeRouter`): the two phases have opposite compute
  profiles (prefill is FLOP-bound, decode is bandwidth-bound), so one
  interleaving worker lets a long prefill steal inter-token latency
  from every live stream.  The disaggregated pool splits the fleet into
  prefill-role engines (bucketed/chunked prefill only) and decode-role
  engines (steady fixed-shape decode only); a finished prefill's pages
  move to a decode engine via the KV **handoff**: a fixed-shape jitted
  page export (``models.decoder.gather_pages`` over the sentinel-padded
  table row — one compiled program whatever the stream's real page
  count), staged custody on the sender
  (``kvpage.stage_handoff`` — refcounts never blip, both allocators'
  ``leak_check`` reconcile to zero), and a fixed-shape import
  (``scatter_pages``) into the receiver's fresh cold reservation.
  Cross-pool the payload rides ``serve.handoff``'s length-prefixed
  stdlib-socket transport (loopback; the repo's first RPC boundary).
  The pool split is the controller's first STRUCTURAL knob
  (``prefill_share``), actuated through :meth:`DisaggDecodeRouter.
  set_prefill_share` — a retiring unit hands its streams back through
  the front door (greedy determinism keeps tokens identical).

Hop chains (``obs.request``): ``admit → prefill → (decode | draft
verify)* → complete``, with ``decode`` hops carrying
``slot``/``step``/``tokens_out`` and speculation rounds carrying
``draft``/``verify`` pairs (``k``/``accepted``/``drafter_model``) so
``trace_tpu.py request <id>`` reconstructs a stream's whole life.
Disaggregated streams insert a ``handoff`` hop after their prefill
(``admit → prefill → handoff → decode* → complete``) carrying the
custody story (``pages``/``bytes``/``from_replica``/``to_replica``/
``transport``).
"""
from __future__ import annotations

import queue
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pdnlp_tpu.models import decoder
from pdnlp_tpu.obs.decision import mint_decision_id, record_decision
from pdnlp_tpu.obs.memory import KVBudget
from pdnlp_tpu.obs.request import mint_request_id, record_hop
from pdnlp_tpu.serve.batcher import (
    DEFAULT_BUCKETS, DeadlineExceeded, QueueFullError, pick_bucket,
    usable_buckets,
)
from pdnlp_tpu.serve.engine import InferenceEngine
from pdnlp_tpu.serve.handoff import (
    HandoffChannel, HandoffError, HandoffServer,
)
from pdnlp_tpu.serve.kvpage import (
    INDEX_OWNER, KVPagesExhausted, PageAllocator, PrefixHit, PrefixIndex,
    draft_owner, pages_needed, stage_handoff,
)
from pdnlp_tpu.serve.metrics import DecodeMetrics, ReplicaMetrics
from pdnlp_tpu.train import checkpoint as ckpt
from pdnlp_tpu.utils.metrics import merged_percentiles

#: sentinel closing a stream's token queue
_DONE = object()


def detokenize(tokenizer, ids: Sequence[int]) -> str:
    """Token ids -> text: wordpiece continuations (``##``) rejoin their
    word, CJK pieces concatenate bare, latin words get spaces — the
    inverse of ``data.tokenizer``'s basic+wordpiece split, close enough
    for a streamed response body."""
    out: List[str] = []
    for i in ids:
        piece = tokenizer.vocab_list[int(i)] \
            if 0 <= int(i) < tokenizer.vocab_size else "[UNK]"
        if piece.startswith("##"):
            if out:
                out[-1] += piece[2:]
            else:
                out.append(piece[2:])
        else:
            out.append(piece)
    return " ".join(out)


class DecodeEngine(InferenceEngine):
    """The classifier engine's checkpoint/mesh/metrics machinery with a
    generative decode path on top: LM head, slot KV cache, jitted
    prefill / cache-insert / decode-step programs, and the KV budget.

    The inherited pieces carry over unchanged: template-validated
    checkpoint swap (trunk only — the LM head is its own small tree),
    int8 weight serving (``--serve_dtype int8`` quantizes trunk AND head
    through ``serve.quant``), per-batch HBM sampling, span conventions
    (``compile`` on a first-seen shape, the steady-state name after).
    Single-dispatcher contract: all decode/prefill calls come from ONE
    worker thread (:class:`DecodeBatcher`)."""

    def __init__(self, args, tokenizer=None, *, mesh=None, metrics=None,
                 tracer=None, slots: Optional[int] = None,
                 max_len: Optional[int] = None,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 prefill_rows: Optional[int] = None):
        super().__init__(args, tokenizer, mesh=mesh, metrics=metrics,
                         tracer=tracer)
        cfg = self.cfg
        self.max_len = int(max_len or getattr(args, "decode_max_len", 0)
                           or args.max_seq_len)
        if self.max_len > cfg.max_position:
            raise ValueError(
                f"decode_max_len {self.max_len} exceeds {args.model}'s "
                f"{cfg.max_position}-position table — generated positions "
                "would gather garbage embeddings; use a long-position "
                "model or shrink it")
        # KV precision: auto follows the serve compute dtype; int8 stores
        # the cache against calibrated per-channel scale tables
        kv_req = getattr(args, "kv_dtype", "auto") or "auto"
        if kv_req not in ("auto", "fp32", "bf16", "int8"):
            raise ValueError(f"kv_dtype must be auto|fp32|bf16|int8, "
                             f"got {kv_req!r}")
        self.kv_int8 = kv_req == "int8"
        self.kv_dtype = (jnp.int8 if self.kv_int8
                         else {"fp32": jnp.float32,
                               "bf16": jnp.bfloat16}.get(kv_req, self.dtype))
        self._kv_scales = None  # (k_scale, v_scale) [L, N, D] once known

        # the declared HBM budget gates the PREALLOCATION (loud refusal at
        # construction, never an allocator OOM) and caps slots to what it
        # covers; admission re-checks per stream (KVBudgetExceeded)
        self.budget = KVBudget(getattr(args, "kv_hbm_mb", 0))
        requested = int(slots or getattr(args, "decode_slots", 8))
        self.token_bytes = decoder.kv_cache_bytes(cfg, 1, 1, self.kv_dtype)
        self.slots = self._resolve_capacity(requested)
        self.prefill_rows = self.pad_rows(
            min(self.slots, int(prefill_rows or 8)))
        # prompt buckets: the serve bucket ladder capped at max_len, with
        # max_len always present so a requeue continuation (prompt +
        # emitted, bounded by admission at max_len) always has a bucket
        bk = usable_buckets(buckets, min(args.max_seq_len, self.max_len))
        if bk[-1] < self.max_len:
            bk = bk + (self.max_len,)
        self.prefill_buckets = bk

        # LM head: MLM-shaped, seeded beside the trunk template; a
        # trained head loads via load_lm_head.  int8 weight serving
        # quantizes it through the same serving-form door as the trunk.
        self._head_template = decoder.init_lm_head(
            jax.random.key(args.seed + 1), cfg)
        self.head = self._put(self._serving_form(self._head_template))
        self.head_path: Optional[str] = None

        self._cache_k = self._cache_v = None
        self._alloc_cache()

        metrics_ref = self.metrics
        dtype = self.dtype

        def _prefill_fn(params, head, ids, mask, last_pos):
            metrics_ref.retraces.inc()  # body runs only while tracing
            return decoder.prefill(params, head, cfg, ids, mask, last_pos,
                                   dtype=dtype)

        if self.kv_int8:
            def _insert_fn(ck, cv, k, v, slot_ids, ks, vs):
                metrics_ref.retraces.inc()
                k = decoder.quantize_kv(k, ks[:, None, None])
                v = decoder.quantize_kv(v, vs[:, None, None])
                S = k.shape[2]
                ck = ck.at[:, slot_ids, :S].set(k, mode="drop")
                cv = cv.at[:, slot_ids, :S].set(v, mode="drop")
                return ck, cv

            def _decode_fn(params, head, ck, cv, tokens, pos, ks, vs):
                metrics_ref.retraces.inc()
                return decoder.decode_step(params, head, cfg, tokens, ck,
                                           cv, pos, kv_scales=(ks, vs),
                                           dtype=dtype)
        else:
            def _insert_fn(ck, cv, k, v, slot_ids):
                metrics_ref.retraces.inc()
                S = k.shape[2]
                ck = ck.at[:, slot_ids, :S].set(k.astype(ck.dtype),
                                                mode="drop")
                cv = cv.at[:, slot_ids, :S].set(v.astype(cv.dtype),
                                                mode="drop")
                return ck, cv

            def _decode_fn(params, head, ck, cv, tokens, pos):
                metrics_ref.retraces.inc()
                return decoder.decode_step(params, head, cfg, tokens, ck,
                                           cv, pos, dtype=dtype)

        self._jit_prefill = jax.jit(_prefill_fn)
        self._jit_insert = jax.jit(_insert_fn, donate_argnums=(0, 1))
        self._jit_decode = jax.jit(_decode_fn, donate_argnums=(2, 3))

    #: layout marker — :class:`PagedDecodeEngine` flips it; the batcher
    #: and router branch on behavior hooks, never on this flag, but
    #: snapshots and bench reports name the layout through it
    paged = False

    def _resolve_capacity(self, requested: int) -> int:
        """How many decode slots this engine runs: the ``--kv_hbm_mb``
        budget caps the SLOT count here (the slot layout's capacity
        unit); the paged engine overrides this to cap PAGES instead and
        leave slots as pure batch rows."""
        slot_bytes = self.token_bytes * self.max_len
        capped = self.budget.cap_slots(requested, slot_bytes)
        # slots must tile the mesh's data axis; FLOOR so the cap holds
        m = self.rows_multiple
        slots_n = max(m, (capped // m) * m)
        if slots_n * slot_bytes > (self.budget.budget_bytes or
                                   slots_n * slot_bytes):
            raise ValueError(
                f"kv_hbm_mb cannot cover the {m}-slot mesh minimum "
                f"({m * slot_bytes / 2**20:.1f} MB)")
        if slots_n < requested:
            print(f"[serve.decode] kv_hbm_mb caps decode slots "
                  f"{requested} -> {slots_n} "
                  f"({slot_bytes / 2**20:.1f} MB/slot)", file=sys.stderr)
        return slots_n

    # ---------------------------------------------------- paging hooks
    # The batcher drives BOTH layouts through these; on the slot layout
    # they are no-ops (a slot IS the reservation), on the paged engine
    # they are the allocator/prefix-index transaction per stream.
    def peek_prefix(self, ids: Sequence[int]) -> Optional[str]:
        """Admission-time prefix peek for the ``admit`` hop's
        ``prefix_hit`` attr (None = layout has no prefix sharing)."""
        return None

    def attach_stream(self, slot: int, stream: "DecodeStream", *,
                      share: bool = True):
        """Reserve cache capacity for ``stream`` in ``slot``; returns a
        claim descriptor (None on the slot layout — the slot claim
        already IS the reservation).  ``share=False`` forces a COLD
        claim even when the prefix index would hit: the KV-handoff
        import path scatters a payload into the reservation, which must
        never write into shared prefix pages."""
        return None

    def detach_slot(self, slot: int) -> None:
        """Release ``slot``'s cache reservation (no-op on slots)."""

    def register_slot(self, slot: int, first_token: int) -> None:
        """Index ``slot``'s freshly prefilled prompt for later sharing
        (no-op on the slot layout)."""

    def leak_check(self) -> Optional[Dict]:
        """Allocator ledger audit (None on the slot layout)."""
        return None

    # ----------------------------------------------------------- lifecycle
    def _alloc_cache(self) -> None:
        """(Re)allocate the slot cache — construction, and
        :meth:`reset_cache` after tests/chaos; never on the hot path."""
        cfg = self.cfg
        shape = (cfg.num_layers, self.slots, self.max_len,
                 cfg.num_heads, cfg.head_dim)
        sh = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            sh = NamedSharding(self.mesh,
                               PartitionSpec(None, "data", None, None, None))

        def alloc():
            # two SEPARATE buffers: device_put of one shared zeros array
            # would alias K and V, and the donated insert/decode calls
            # would then donate the same buffer twice
            z = jnp.zeros(shape, self.kv_dtype)
            return jax.device_put(z, sh) if sh is not None \
                else jax.device_put(z)

        self._cache_k = alloc()
        self._cache_v = alloc()

    def reset_cache(self) -> None:
        self._alloc_cache()

    @property
    def prompt_limit(self) -> int:
        """Longest admissible prompt (the widest prefill bucket)."""
        return int(self.prefill_buckets[-1])

    def check_stream_admissible(self, prompt_len: int,
                                max_new: int) -> None:
        """The admission door's capacity + budget math, in one place.
        On a BUDGETED engine an oversized stream refuses in the budget's
        own units (:class:`~pdnlp_tpu.obs.memory.KVBudgetExceeded` with
        the MB math) — the refusal that replaces a mid-decode OOM; an
        unbudgeted engine reports plain slot capacity."""
        total = int(prompt_len) + int(max_new)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt_len > self.prompt_limit:
            raise ValueError(
                f"prompt of {prompt_len} tokens exceeds the "
                f"{self.prompt_limit}-token prefill limit")
        # (no separate budget.check_stream call: construction guarantees
        # budget >= one slot = max_len positions, so any stream the
        # budget would refuse also exceeds max_len — ONE door below, in
        # the budget's units when a budget is declared)
        if total > self.max_len:
            if self.budget.budget_bytes is not None:
                from pdnlp_tpu.obs.memory import KVBudgetExceeded

                raise KVBudgetExceeded(
                    f"stream needs {total} KV positions "
                    f"({total * self.token_bytes / 2**20:.1f} MB) but "
                    f"the budgeted slot holds {self.max_len} "
                    f"({self.max_len * self.token_bytes / 2**20:.1f} MB "
                    "under --kv_hbm_mb)")
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the "
                f"{self.max_len}-position KV slot (--decode_max_len)")

    # ------------------------------------------------------------ KV int8
    def load_kv_scales(self, path: str) -> None:
        """Load the manifest-verified int8 KV scale tables
        (``scripts/quantize_ckpt.py --kv_calib`` sidecar)."""
        if not self.kv_int8:
            raise ValueError("KV scale tables only apply to --kv_dtype "
                             "int8 engines")
        raw = ckpt.load_raw(path)
        cfg = self.cfg
        want = (cfg.num_layers, cfg.num_heads, cfg.head_dim)
        for key in ("k_scale", "v_scale"):
            got = tuple(np.asarray(raw[key]).shape)
            if got != want:
                raise ValueError(f"KV scale table {key} has shape {got}, "
                                 f"expected {want} for {self.args.model}")
        self._kv_scales = (
            self._put(jnp.asarray(np.asarray(raw["k_scale"], np.float32))),
            self._put(jnp.asarray(np.asarray(raw["v_scale"], np.float32))))

    def calibrate_kv(self) -> None:
        """Self-calibrate the int8 KV scale tables from the SERVED params
        (the seeded synthetic forward ``models.decoder.calibrate_kv_scales``
        — byte-identical to the offline ``--kv_calib`` artifact for the
        same params).  Idempotent; a checkpoint swap clears the tables so
        the next warmup recalibrates."""
        if not self.kv_int8 or self._kv_scales is not None:
            return
        # default calibration width (NOT max_len): the table must be
        # byte-identical to the offline --kv_calib artifact for the same
        # params, whatever cache geometry this engine runs
        ks, vs = decoder.calibrate_kv_scales(self.params, self.cfg,
                                             dtype=self.dtype)
        self._kv_scales = (self._put(jnp.asarray(ks)),
                          self._put(jnp.asarray(vs)))

    def load_checkpoint(self, path: str) -> None:
        super().load_checkpoint(path)
        if self.kv_int8:
            self._kv_scales = None  # stale for the new weights
            import os

            stem = path.rsplit(".msgpack", 1)[0]
            for cand in (stem, stem.rsplit(".int8", 1)[0]):
                sidecar = cand + ".kvscales.msgpack"
                if os.path.exists(sidecar):
                    self.load_kv_scales(sidecar)
                    break

    def load_lm_head(self, path: str) -> None:
        """Swap the LM head (template-validated like the trunk; an int8
        artifact validates against the quantized template)."""
        from pdnlp_tpu.serve.quant import is_quantized, quantize_params

        raw = ckpt.load_raw(path)
        if self.serve_dtype == "int8":
            if is_quantized(raw):
                host = ckpt.from_restored(
                    raw, self._serving_form(self._head_template), path=path)
            else:
                host = quantize_params(
                    ckpt.from_restored(raw, self._head_template, path=path))
        else:
            if is_quantized(raw):
                raise ValueError(
                    f"LM head {path!r} is an int8 artifact but this engine "
                    f"serves {self.serve_dtype!r} — use --serve_dtype int8")
            host = ckpt.from_restored(raw, self._head_template, path=path)
        self.head = self._put(host)
        self.head_path = path

    def _scale_args(self) -> tuple:
        if not self.kv_int8:
            return ()
        if self._kv_scales is None:
            self.calibrate_kv()
        return self._kv_scales

    # ------------------------------------------------------------ forward
    def _shard_batch(self, arrays: Dict[str, np.ndarray]) -> Dict:
        if self.mesh is None:
            return arrays
        from pdnlp_tpu.parallel.sharding import batch_sharding

        sh = batch_sharding(self.mesh)
        return {k: jax.make_array_from_process_local_data(sh, v)
                for k, v in arrays.items()}

    def prefill_ids(self, id_lists: Sequence[Sequence[int]],
                    slot_ids: Sequence[int],
                    request_ids=None) -> np.ndarray:
        """Prefill up to ``prefill_rows`` prompts into their claimed slots:
        bucketed causal forward + K/V scatter; returns each prompt's
        FIRST-token logits ``[n, vocab]`` (fp32, host).

        Filler rows carry slot id ``self.slots`` — out of bounds, so the
        scatter DROPS them and a filler row can never touch a live slot.
        The compile-cache key is ``(bucket, rows, "prefill")``; warmup
        pre-traces every bucket so steady traffic never compiles."""
        n = len(id_lists)
        assert n and n <= self.prefill_rows
        bucket = pick_bucket(max(len(x) for x in id_lists),
                             self.prefill_buckets)
        rows = self.prefill_rows
        ids = np.zeros((rows, bucket), np.int32)
        mask = np.zeros((rows, bucket), np.int32)
        last = np.zeros((rows,), np.int32)
        slot_arr = np.full((rows,), self.slots, np.int32)  # OOB = dropped
        for i, (x, s) in enumerate(zip(id_lists, slot_ids)):
            ids[i, :len(x)] = x
            mask[i, :len(x)] = 1
            last[i] = len(x) - 1
            slot_arr[i] = s
        key = (int(bucket), int(rows), "prefill")
        if key in self._seen_shapes:
            self.metrics.cache_hits.inc()
            span_name = "prefill"
        else:
            self.metrics.cache_misses.inc()
            self._seen_shapes.add(key)
            span_name = "compile"
        sharded = self._shard_batch({"ids": ids, "mask": mask})
        tokens_in = int(mask.sum())
        with self.tracer.span(span_name, seq=int(bucket), rows=int(rows),
                              streams=int(n), prefill=True,
                              tokens=tokens_in, dtype=self.dtype_label,
                              **self._telemetry_attrs(request_ids),
                              **self.span_attrs):
            logits, ks, vs = self._jit_prefill(
                self.params, self.head, sharded["ids"], sharded["mask"],
                last)
            self._cache_k, self._cache_v = self._jit_insert(
                self._cache_k, self._cache_v, ks, vs, slot_arr,
                *self._scale_args())
            out = np.asarray(jax.device_get(logits))
        return out[:n]

    def decode_batch(self, tokens: np.ndarray, pos: np.ndarray,
                     live: int, request_ids=None) -> np.ndarray:
        """One fixed-shape decode step over the whole slot block: tokens
        ``[slots]`` (current token per slot; dead slots ride with junk),
        ``pos`` ``[slots]`` write positions.  Returns next-token logits
        ``[slots, vocab]`` (fp32, host).  The ONE compile-cache key is
        ``("decode", slots)`` — retrace-free after warmup by
        construction."""
        key = ("decode", int(self.slots))
        if key in self._seen_shapes:
            self.metrics.cache_hits.inc()
            span_name = "decode"
        else:
            self.metrics.cache_misses.inc()
            self._seen_shapes.add(key)
            span_name = "compile"
        tok = np.asarray(tokens, np.int32).reshape(self.slots, 1)
        p = np.clip(np.asarray(pos, np.int32), 0, self.max_len - 1)
        with self.tracer.span(span_name, rows=int(self.slots),
                              live=int(live), decode=True,
                              dtype=self.dtype_label,
                              kv=("int8" if self.kv_int8
                                  else np.dtype(self.kv_dtype).name),
                              **self._telemetry_attrs(request_ids),
                              **self.span_attrs):
            logits, self._cache_k, self._cache_v = self._jit_decode(
                self.params, self.head, self._cache_k, self._cache_v,
                tok, p, *self._scale_args())
            out = np.asarray(jax.device_get(logits))
        return out

    def infill_ids(self, id_lists: Sequence[Sequence[int]],
                   request_ids=None) -> np.ndarray:
        """MLM-infilling scoring: the BIDIRECTIONAL trunk + LM head over
        bucketed prompts — ``[n, bucket, vocab]`` fp32 logits (the caller
        reads its ``[MASK]`` positions).  Rides the prefill bucket ladder
        and compile cache (key ``(bucket, rows, "infill")``)."""
        n = len(id_lists)
        assert n and n <= self.prefill_rows
        bucket = pick_bucket(max(len(x) for x in id_lists),
                             self.prefill_buckets)
        rows = self.prefill_rows
        ids = np.zeros((rows, bucket), np.int32)
        mask = np.zeros((rows, bucket), np.int32)
        for i, x in enumerate(id_lists):
            ids[i, :len(x)] = x
            mask[i, :len(x)] = 1
        key = (int(bucket), int(rows), "infill")
        if key in self._seen_shapes:
            self.metrics.cache_hits.inc()
            span_name = "forward"
        else:
            self.metrics.cache_misses.inc()
            self._seen_shapes.add(key)
            span_name = "compile"
        if not hasattr(self, "_jit_infill"):
            metrics_ref = self.metrics
            cfg, dtype = self.cfg, self.dtype

            def _infill_fn(params, head, ids, mask):
                metrics_ref.retraces.inc()
                return decoder.infill_logits(params, head, cfg, ids, mask,
                                             dtype=dtype)

            self._jit_infill = jax.jit(_infill_fn)
        sharded = self._shard_batch({"ids": ids, "mask": mask})
        with self.tracer.span(span_name, seq=int(bucket), rows=int(rows),
                              infill=True, dtype=self.dtype_label,
                              **self._telemetry_attrs(request_ids),
                              **self.span_attrs):
            logits = self._jit_infill(self.params, self.head,
                                      sharded["ids"], sharded["mask"])
            out = np.asarray(jax.device_get(logits))
        return out[:n]

    def warmup_decode(self) -> None:
        """Pre-trace every reachable decode-path shape: one prefill +
        insert per bucket (filler slot ids — the cache is untouched), the
        ONE decode step, and the int8 calibration if pending.  After this
        call live traffic cannot compile."""
        self._scale_args()  # int8: calibrate before anything traces
        for b in self.prefill_buckets:
            # a bucket-FILLING dummy, so each bucket traces ITS shape
            # (prefill_ids picks the smallest covering bucket from the
            # ids' length); the OOB slot id drops the cache write
            self.prefill_ids([[self.tokenizer.cls_id] * b], [self.slots])
        tok = np.zeros((self.slots,), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        self.decode_batch(tok, pos, live=0)

    def kv_snapshot(self) -> Dict:
        """JSON-ready KV/budget block for snapshots and ``/metrics``."""
        return {
            **self.budget.snapshot(),
            "layout": "slots",
            "slots": int(self.slots),
            "max_len": int(self.max_len),
            "kv_dtype": ("int8" if self.kv_int8
                         else str(np.dtype(self.kv_dtype).name)),
            "cache_bytes": decoder.kv_cache_bytes(
                self.cfg, self.slots, self.max_len, self.kv_dtype),
        }


class _PageClaim:
    """One stream's page reservation (``PagedDecodeEngine`` slot state):
    which kind of prefix hit it attached with, the continuation tokens it
    covers, and what the prefill phase still owes it (nothing for a full
    hit; the divergent suffix for a partial one)."""

    __slots__ = ("owner", "kind", "tokens", "n_prompt_pages",
                 "first_token", "suffix", "start", "draft_from")

    def __init__(self, owner: str, kind: str, tokens: List[int],
                 n_prompt_pages: int, first_token: Optional[int] = None,
                 suffix: Optional[List[int]] = None, start: int = 0):
        self.owner = owner
        self.kind = kind                    # "cold" | "partial" | "full"
        self.tokens = tokens                # prompt + emitted at attach
        self.n_prompt_pages = n_prompt_pages
        self.first_token = first_token      # full hits: stored token 0
        self.suffix = suffix or []          # partial hits: the chunk
        self.start = start                  # partial hits: suffix offset
        self.draft_from = None              # drafter engines: first page
        #                                     index under draft custody


class PagedDecodeEngine(DecodeEngine):
    """:class:`DecodeEngine` rebased onto the paged KV subsystem
    (``serve.kvpage``): storage is ``[L, n_pages, page_sz, N, D]`` pages,
    a per-stream page table drives the decode-step gather
    (``models.decoder.paged_decode_step`` — still ONE fixed-shape jitted
    program, pages donated across steps), and capacity is PAGES, not
    slots: slots become pure decode-batch rows while ``--kv_hbm_mb`` caps
    the page pool, so short streams stop paying for ``max_len`` stripes
    and admitted concurrency scales with what streams actually use.

    Prefix sharing rides the :class:`~pdnlp_tpu.serve.kvpage.PrefixIndex`:
    a repeated prompt maps the indexed pages at refcount+1 and skips its
    prefill entirely (**full hit** — the stored first token is emitted
    straight from the index, so TTFT is bounded by one decode-step
    latency); a shared-prefix prompt maps the matching full pages and
    runs only the divergent suffix (**partial hit** —
    ``paged_chunk_step``); copy-on-write duplicates a full hit's trailing
    partial page before the stream writes into it.  Full pages are
    immutable once written, which is what makes sharing safe without
    copies.

    Bitwise contract: a COLD paged stream runs the exact slot-engine
    prefill program and a decode step that gathers to the same
    ``[B, max_len]`` attention extent with identical values at every
    visible position — token-identical continuations (the bench storm
    gates paged-vs-slot equality stream by stream).  Shared-prefix
    streams reuse K/V that is bitwise what their own prefill would have
    produced (same program, same inputs), so greedy continuations match
    the cold baseline the same way re-prefilled kill survivors always
    have.

    Pages replicate on a mesh (no ``NamedSharding`` axis): the page ->
    stream mapping is dynamic, so there is no static batch axis to shard
    the way slot stripes sharded; decode pools run per-replica meshes,
    which keeps each pool device-local anyway."""

    paged = True
    #: fixed copy-on-write batch rows — one compiled ``copy_pages``
    #: program per engine; unused rows ride the OOB sentinel
    COW_ROWS = 4

    def __init__(self, args, tokenizer=None, *, mesh=None, metrics=None,
                 tracer=None, slots: Optional[int] = None,
                 max_len: Optional[int] = None,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 prefill_rows: Optional[int] = None,
                 page_sz: Optional[int] = None, prefix_share: bool = True,
                 index_entries: int = 4096):
        # consumed by _resolve_capacity / _alloc_cache, which the base
        # constructor calls — set before super().__init__
        self._req_page_sz = int(page_sz
                                or getattr(args, "kv_page_sz", 0) or 16)
        self.prefix_share = bool(prefix_share)
        self._index_entries = int(index_entries)
        super().__init__(args, tokenizer, mesh=mesh, metrics=metrics,
                         tracer=tracer, slots=slots, max_len=max_len,
                         buckets=buckets, prefill_rows=prefill_rows)
        cfg = self.cfg
        dtype = self.dtype
        metrics_ref = self.metrics

        if self.kv_int8:
            def _pinsert_fn(pk, pv, ks_new, vs_new, flat_pos, ks, vs):
                metrics_ref.retraces.inc()
                return decoder.paged_insert(pk, pv, ks_new, vs_new,
                                            flat_pos, kv_scales=(ks, vs))

            def _pdecode_fn(params, head, pk, pv, tokens, table, pos,
                            ks, vs):
                metrics_ref.retraces.inc()
                return decoder.paged_decode_step(
                    params, head, cfg, tokens, pk, pv, table, pos,
                    kv_scales=(ks, vs), dtype=dtype)

            def _pchunk_fn(params, head, pk, pv, tokens, table, start,
                           nreal, ks, vs):
                metrics_ref.retraces.inc()
                return decoder.paged_chunk_step(
                    params, head, cfg, tokens, pk, pv, table, start,
                    nreal, kv_scales=(ks, vs), dtype=dtype)

            def _pverify_fn(params, head, pk, pv, tokens, table, start,
                            nreal, ks, vs):
                metrics_ref.retraces.inc()
                return decoder.paged_verify_step(
                    params, head, cfg, tokens, pk, pv, table, start,
                    nreal, kv_scales=(ks, vs), dtype=dtype)
        else:
            def _pinsert_fn(pk, pv, ks_new, vs_new, flat_pos):
                metrics_ref.retraces.inc()
                return decoder.paged_insert(pk, pv, ks_new, vs_new,
                                            flat_pos)

            def _pdecode_fn(params, head, pk, pv, tokens, table, pos):
                metrics_ref.retraces.inc()
                return decoder.paged_decode_step(
                    params, head, cfg, tokens, pk, pv, table, pos,
                    dtype=dtype)

            def _pchunk_fn(params, head, pk, pv, tokens, table, start,
                           nreal):
                metrics_ref.retraces.inc()
                return decoder.paged_chunk_step(
                    params, head, cfg, tokens, pk, pv, table, start,
                    nreal, dtype=dtype)

            def _pverify_fn(params, head, pk, pv, tokens, table, start,
                            nreal):
                metrics_ref.retraces.inc()
                return decoder.paged_verify_step(
                    params, head, cfg, tokens, pk, pv, table, start,
                    nreal, dtype=dtype)

        def _pcow_fn(pk, pv, src, dst):
            metrics_ref.retraces.inc()
            return decoder.copy_pages(pk, pv, src, dst)

        def _pexport_fn(pk, pv, src):
            metrics_ref.retraces.inc()
            return decoder.gather_pages(pk, pv, src)

        def _pimport_fn(pk, pv, payload_k, payload_v, dst):
            metrics_ref.retraces.inc()
            return decoder.scatter_pages(pk, pv, payload_k, payload_v,
                                         dst)

        self._jit_pinsert = jax.jit(_pinsert_fn, donate_argnums=(0, 1))
        self._jit_pdecode = jax.jit(_pdecode_fn, donate_argnums=(2, 3))
        self._jit_pchunk = jax.jit(_pchunk_fn, donate_argnums=(2, 3))
        self._jit_pverify = jax.jit(_pverify_fn, donate_argnums=(2, 3))
        self._jit_pcow = jax.jit(_pcow_fn, donate_argnums=(0, 1))
        # export reads the pool (no donation — the sender keeps serving
        # from it); import donates like every other cache writer
        self._jit_pexport = jax.jit(_pexport_fn)
        self._jit_pimport = jax.jit(_pimport_fn, donate_argnums=(0, 1))

    # --------------------------------------------------------- capacity
    def _resolve_capacity(self, requested: int) -> int:
        """Pages, not slots, are the budgeted unit: ``--kv_hbm_mb`` caps
        the page pool (floor: one maximum-length stream) and the slot
        count stays the requested batch width — admitted concurrency is
        then bounded by what streams actually RESERVE, which is the
        whole capacity story of paging."""
        ps = max(1, min(self._req_page_sz, self.max_len))
        self.page_sz = ps
        self.pages_per_stream = pages_needed(self.max_len, ps)
        self.page_bytes = self.token_bytes * ps
        req_pages = int(requested) * self.pages_per_stream
        self.n_pages = self.budget.cap_pages(
            req_pages, self.page_bytes, min_pages=self.pages_per_stream)
        if self.n_pages < req_pages:
            print(f"[serve.decode] kv_hbm_mb caps KV pages "
                  f"{req_pages} -> {self.n_pages} "
                  f"({self.page_bytes / 2**20:.2f} MB/page, "
                  f"{self.pages_per_stream}/stream worst case)",
                  file=sys.stderr)
        m = self.rows_multiple
        return max(m, (int(requested) // m) * m)

    def _alloc_cache(self) -> None:
        """(Re)allocate the page pool + a fresh allocator/index/table —
        construction and post-chaos :meth:`reset_cache`, never hot."""
        cfg = self.cfg
        shape = (cfg.num_layers, self.n_pages, self.page_sz,
                 cfg.num_heads, cfg.head_dim)

        def alloc():
            # two SEPARATE buffers (donation aliasing — base note)
            return jax.device_put(jnp.zeros(shape, self.kv_dtype))

        self._cache_k = alloc()
        self._cache_v = alloc()
        self.allocator = PageAllocator(self.n_pages, self.page_sz,
                                       self.page_bytes)
        self.prefix = PrefixIndex(self.allocator, self.page_sz,
                                  max_entries=self._index_entries)
        if self.prefix_share:
            self.allocator.reclaimer = self.prefix.evict
        # per-slot page tables, host-resident and updated IN PLACE at
        # attach/detach (never rebuilt per step — jaxlint R16 polices
        # the rebuild-by-concatenate idiom); sentinel n_pages = dead row
        self._table = np.full((self.slots, self.pages_per_stream),
                              self.n_pages, np.int32)
        self._slot_state: List[Optional[_PageClaim]] = [None] * self.slots
        self._pending_cow: List[tuple] = []

    # -------------------------------------------------------- admission
    def check_stream_admissible(self, prompt_len: int,
                                max_new: int) -> None:
        """Base capacity rules, with the budgeted refusal in PAGE units
        (the admission door the router quotes)."""
        total = int(prompt_len) + int(max_new)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt_len > self.prompt_limit:
            raise ValueError(
                f"prompt of {prompt_len} tokens exceeds the "
                f"{self.prompt_limit}-token prefill limit")
        if total > self.max_len:
            need = pages_needed(total, self.page_sz)
            if self.budget.budget_bytes is not None:
                from pdnlp_tpu.obs.memory import KVBudgetExceeded

                raise KVBudgetExceeded(
                    f"stream needs {need} KV pages ({total} positions, "
                    f"{need * self.page_bytes / 2**20:.2f} MB) but a "
                    f"stream's page table holds {self.pages_per_stream} "
                    f"pages ({self.max_len} positions) under --kv_hbm_mb")
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the "
                f"{self.max_len}-position page-table extent "
                "(--decode_max_len)")

    # ----------------------------------------------------- paging hooks
    def peek_prefix(self, ids: Sequence[int]) -> Optional[str]:
        if not self.prefix_share:
            return None
        return self.prefix.lookup(ids, count=False).kind

    def attach_stream(self, slot: int, stream: "DecodeStream", *,
                      share: bool = True):
        """The per-stream allocator/index transaction: reserve EVERY
        page the stream can ever touch (``ceil((prompt + max_new) /
        page_sz)`` — full reservation, so decode never page-faults),
        sharing the indexed prefix pages at refcount+1 and allocating
        the rest fresh.  Raises
        :class:`~pdnlp_tpu.serve.kvpage.KVPagesExhausted` (after index
        eviction) when the pool cannot cover it — the batcher leaves the
        stream queued and retries as live streams drain.
        ``share=False``: cold claim regardless of the index (the
        KV-handoff import scatters into the reservation — writing into
        shared prefix pages would corrupt every other holder)."""
        tokens = list(stream.prompt_ids) + list(stream.emitted)
        total = min(len(stream.prompt_ids) + stream.max_new_tokens,
                    self.max_len)
        ps = self.page_sz
        need = pages_needed(total, ps)
        owner = stream.rid
        n_full = len(tokens) // ps
        hit = (self.prefix.lookup(tokens)
               if (self.prefix_share and share) else PrefixHit("miss"))
        row = np.full((self.pages_per_stream,), self.n_pages, np.int32)
        if hit.kind == "full" and hit.first_token is not None:
            shared = [int(p) for p in hit.pages[:n_full]]
            partial_src = (int(hit.pages[n_full])
                           if len(hit.pages) > n_full else None)
            # pin the shared pages (and the COW source) BEFORE the
            # private alloc: the alloc's index eviction may drop the
            # entries we just matched, and only the stream's own
            # references keep their pages from returning to the free
            # list mid-transaction
            pin = shared + ([partial_src] if partial_src is not None
                            else [])
            self.allocator.share(pin, owner)
            # ANY failure between the acquire and the page-table commit
            # below must hand the reservation back, or the pages leak:
            # KVPagesExhausted from the private alloc is just the common
            # case (hence BaseException, not a named tuple of "expected"
            # errors)
            try:
                private = self.allocator.alloc(need - n_full, owner)
                row[:n_full] = shared
                row[n_full:need] = private
                claim = _PageClaim(owner, "full", tokens,
                                   pages_needed(len(tokens), ps),
                                   first_token=int(hit.first_token))
                if partial_src is not None and len(tokens) % ps and private:
                    self._pending_cow.append((partial_src, private[0]))
                    self.allocator.count_cow()
            except BaseException:
                self.allocator.release_owner(owner)
                raise
        else:
            n_shared = len(hit.pages) if hit.kind == "partial" else 0
            if n_shared and n_shared * ps >= len(tokens):
                # keep at least one suffix token so the chunk forward
                # has a last-token logit row to emit from
                n_shared -= 1
            if n_shared:
                shared = [int(p) for p in hit.pages[:n_shared]]
                self.allocator.share(shared, owner)
                try:
                    private = self.allocator.alloc(need - n_shared,
                                                   owner)
                    row[:n_shared] = shared
                    row[n_shared:need] = private
                    claim = _PageClaim(owner, "partial", tokens,
                                       pages_needed(len(tokens), ps),
                                       suffix=tokens[n_shared * ps:],
                                       start=n_shared * ps)
                except BaseException:
                    self.allocator.release_owner(owner)
                    raise
            else:
                private = self.allocator.alloc(need, owner)
                try:
                    row[:need] = private
                    claim = _PageClaim(owner, "cold", tokens,
                                       pages_needed(len(tokens), ps))
                except BaseException:
                    self.allocator.release_owner(owner)
                    raise
        self._table[slot] = row
        self._slot_state[slot] = claim
        return claim

    def detach_slot(self, slot: int) -> None:
        if not (0 <= slot < self.slots):
            return
        st = self._slot_state[slot]
        if st is None:
            return
        held = set(int(p) for p in self._table[slot]
                   if p < self.n_pages)
        # a stream that finished before its COW flushed (EOS on the
        # stored first token) must take its pending copies with it —
        # both sides of each pair were pinned by this owner only
        self._pending_cow = [(s, d) for (s, d) in self._pending_cow
                             if d not in held and s not in held]
        self._slot_state[slot] = None
        self._table[slot, :] = self.n_pages
        self.allocator.release_owner(st.owner)
        # drafter engines: tentative (uncommitted) pages live under the
        # draft owner — release them too or a drained audit reports the
        # "#draft" alias as a leak
        self.allocator.release_owner(draft_owner(st.owner))

    # ---------------------------------------------- draft page custody
    # Two-owner custody for speculative decoding (DRAFTER-side engine):
    # pages wholly beyond the committed cache length hold only tentative
    # drafted K/V, so they belong to ``draft_owner(rid)`` — the ledger
    # then names exactly which pages a rejection would strand, and
    # ``transfer`` (a leaklint-recognised releaser) moves each page to
    # the stream owner the moment a verify round commits across it.
    def split_draft_custody(self, slot: int, committed_len: int) -> None:
        """Move the reservation's pages wholly beyond ``committed_len``
        positions to the slot's draft owner (post-attach, pre-draft)."""
        st = self._slot_state[slot] if 0 <= slot < self.slots else None
        if st is None:
            return
        n_commit = pages_needed(committed_len, self.page_sz)
        pages = [int(p) for p in self._table[slot] if p < self.n_pages]
        tail = pages[n_commit:]
        if tail:
            self.allocator.transfer(tail, st.owner,
                                    draft_owner(st.owner))
        st.draft_from = n_commit

    def commit_draft(self, slot: int, committed_len: int) -> None:
        """A verify round accepted tokens through ``committed_len``
        positions: transfer every boundary-crossed page back to the
        stream owner.  Rejected pages simply stay under draft custody —
        the next round overwrites them in place."""
        st = self._slot_state[slot] if 0 <= slot < self.slots else None
        if st is None or st.draft_from is None:
            return
        n_commit = pages_needed(committed_len, self.page_sz)
        if n_commit <= st.draft_from:
            return
        pages = [int(p) for p in self._table[slot] if p < self.n_pages]
        crossed = pages[st.draft_from:n_commit]
        if crossed:
            self.allocator.transfer(crossed, draft_owner(st.owner),
                                    st.owner)
        st.draft_from = n_commit

    # ------------------------------------------------------- KV handoff
    # Disaggregated serving: a prefill-role engine exports one stream's
    # pages as a dense payload and a decode-role engine imports them
    # into its own fresh reservation.  Both programs are FIXED shape —
    # the src/dst rows are ALWAYS the ``pages_per_stream`` table extent,
    # sentinel-padded (jaxlint R18 polices the per-stream-count
    # retrace spelling), so one compiled export and one compiled import
    # serve every stream.
    def export_pages(self, slot: int, request_ids=None):
        """Export ``slot``'s pages as a host ``[L, pages_per_stream,
        page_sz, N, D]`` payload pair (K, V) — raw cache bytes (int8
        cache exports int8; both pools calibrate identical scale tables
        from the same params, so no rescaling crosses the wire).  An
        out-of-range ``slot`` exports the sentinel row (zero payload) —
        the warmup path.  Compile key ``("export", pages_per_stream)``."""
        self._flush_cow()
        if 0 <= slot < self.slots:
            src = np.asarray(self._table[slot], np.int32)
        else:
            src = np.full((self.pages_per_stream,), self.n_pages,
                          np.int32)
        key = ("export", int(self.pages_per_stream))
        if key in self._seen_shapes:
            self.metrics.cache_hits.inc()
            span_name = "handoff"
        else:
            self.metrics.cache_misses.inc()
            self._seen_shapes.add(key)
            span_name = "compile"
        with self.tracer.span(span_name, export=True, paged=True,
                              pages=int(self.pages_per_stream),
                              **self._telemetry_attrs(request_ids),
                              **self.span_attrs):
            k, v = self._jit_pexport(self._cache_k, self._cache_v, src)
            out_k = np.asarray(jax.device_get(k))
            out_v = np.asarray(jax.device_get(v))
        return out_k, out_v

    def import_pages(self, slot: int, payload_k, payload_v,
                     request_ids=None) -> None:
        """Scatter a handoff payload into ``slot``'s (cold, freshly
        allocated) reservation.  Rows past the stream's real page count
        carry the sentinel and are dropped; geometry is validated
        loudly BEFORE anything writes.  An out-of-range ``slot``
        scatters against the sentinel row (all dropped) — the warmup
        path.  Compile key ``("import", pages_per_stream)``."""
        cfg = self.cfg
        want = (cfg.num_layers, self.pages_per_stream, self.page_sz,
                cfg.num_heads, cfg.head_dim)
        got = tuple(int(s) for s in np.shape(payload_k))
        if got != want or tuple(int(s)
                                for s in np.shape(payload_v)) != want:
            raise HandoffError(
                f"handoff payload shape {got} does not match this "
                f"engine's page geometry {want} — pools must share one "
                "model config and page size")
        self._flush_cow()
        if 0 <= slot < self.slots:
            dst = np.asarray(self._table[slot], np.int32)
        else:
            dst = np.full((self.pages_per_stream,), self.n_pages,
                          np.int32)
        key = ("import", int(self.pages_per_stream))
        if key in self._seen_shapes:
            self.metrics.cache_hits.inc()
            span_name = "handoff"
        else:
            self.metrics.cache_misses.inc()
            self._seen_shapes.add(key)
            span_name = "compile"
        with self.tracer.span(span_name, import_=True, paged=True,
                              pages=int(self.pages_per_stream),
                              **self._telemetry_attrs(request_ids),
                              **self.span_attrs):
            self._cache_k, self._cache_v = self._jit_pimport(
                self._cache_k, self._cache_v, jnp.asarray(payload_k),
                jnp.asarray(payload_v), dst)

    def begin_handoff(self, slot: int):
        """Stage ``slot``'s stream for handoff: move its page refs to
        the staging owner (:func:`~pdnlp_tpu.serve.kvpage.
        stage_handoff` — the custody acquire the caller must discharge
        with ``allocator.release_owner(staged)`` once the dispatch
        settles, success or failure) and clear the slot WITHOUT
        releasing anything — the slot row is immediately reusable while
        the pages stay pinned under the staged owner.  Returns
        ``(staged_owner, pages)``."""
        st = self._slot_state[slot] if 0 <= slot < self.slots else None
        if st is None:
            raise ValueError(f"begin_handoff on empty slot {slot}")
        pages = [int(p) for p in self._table[slot] if p < self.n_pages]
        # pending COW pairs rooted in this slot's pages travel with the
        # stream — but the payload was already exported post-flush, so
        # by construction none are pending here; drop defensively
        held = set(pages)
        self._pending_cow = [(s, d) for (s, d) in self._pending_cow
                             if d not in held and s not in held]
        self._slot_state[slot] = None
        self._table[slot, :] = self.n_pages
        staged = stage_handoff(self.allocator, pages, st.owner)
        # a full prefix hit with a partial tail page pinned the COW
        # SOURCE under the stream owner (attach's pin list); that page
        # is not in the table row, so the stage above left the pin
        # behind — and the payload was exported post-flush, so its job
        # is done.  Discharge the stream owner's leftovers here, or a
        # handed-off full-hit stream leaks its pin forever.
        self.allocator.release_owner(st.owner)
        return staged, pages

    def warmup_handoff(self) -> None:
        """Pre-trace the export and import programs (sentinel rows: the
        export reads zero-fill, the import drops every row — no live
        page is touched).  After this a handoff never compiles."""
        pk, pv = self.export_pages(self.slots)
        self.import_pages(self.slots, pk, pv)

    def register_slot(self, slot: int, first_token: int) -> None:
        if not self.prefix_share:
            return
        st = self._slot_state[slot] if 0 <= slot < self.slots else None
        if st is None:
            return
        pages = [int(p) for p in self._table[slot][:st.n_prompt_pages]]
        self.prefix.register(st.tokens, pages,
                             first_token=int(first_token))

    def leak_check(self) -> Dict:
        """Allocator ledger audit + who still holds pages — the chaos
        tests and the bench storm call this after drain (every non-index
        owner must be gone, the refcount ledger must reconcile)."""
        audit = self.allocator.leak_check()
        audit["stream_owners"] = [o for o in self.allocator.owners()
                                  if o != INDEX_OWNER]
        audit["index_entries"] = len(self.prefix)
        audit["ok"] = bool(audit["ok"]) and not audit["stream_owners"]
        return audit

    # ----------------------------------------------------------- forward
    def _flush_cow(self, force: bool = False) -> None:
        """Execute pending copy-on-write page copies (fixed
        :data:`COW_ROWS`-row program; sentinel-padded).  Runs before any
        program that could read or write the copied pages — the paged
        prefill/chunk/decode entry points all call it first."""
        if not self._pending_cow and not force:
            return
        P = self.n_pages
        pend = self._pending_cow
        self._pending_cow = []
        rows = self.COW_ROWS
        for i in range(0, max(len(pend), 1), rows):
            batch = pend[i:i + rows]
            src = np.full((rows,), P, np.int32)
            dst = np.full((rows,), P, np.int32)
            for j, (s, d) in enumerate(batch):
                src[j] = s
                dst[j] = d
            key = ("cow", rows)
            if key in self._seen_shapes:
                self.metrics.cache_hits.inc()
                span_name = "prefill"
            else:
                self.metrics.cache_misses.inc()
                self._seen_shapes.add(key)
                span_name = "compile"
            with self.tracer.span(span_name, cow=True,
                                  cow_pages=len(batch),
                                  **self.span_attrs):
                self._cache_k, self._cache_v = self._jit_pcow(
                    self._cache_k, self._cache_v, src, dst)

    def prefill_ids(self, id_lists: Sequence[Sequence[int]],
                    slot_ids: Sequence[int],
                    request_ids=None) -> np.ndarray:
        """Cold-path prefill: the SAME bucketed causal forward as the
        slot engine (bitwise-identical K/V for identical prompts — the
        sharing contract rests on this), scattered into pages through
        each claimed slot's table.  Filler rows and padding carry the
        OOB flat sentinel, so they can never touch a live page."""
        self._flush_cow()
        n = len(id_lists)
        assert n and n <= self.prefill_rows
        bucket = pick_bucket(max(len(x) for x in id_lists),
                             self.prefill_buckets)
        rows = self.prefill_rows
        ps = self.page_sz
        oob = self.n_pages * ps
        ids = np.zeros((rows, bucket), np.int32)
        mask = np.zeros((rows, bucket), np.int32)
        last = np.zeros((rows,), np.int32)
        flat = np.full((rows, bucket), oob, np.int32)
        for i, (x, s) in enumerate(zip(id_lists, slot_ids)):
            ids[i, :len(x)] = x
            mask[i, :len(x)] = 1
            last[i] = len(x) - 1
            if 0 <= s < self.slots and self._slot_state[s] is not None:
                p = np.arange(len(x))
                row = self._table[s]
                flat[i, :len(x)] = row[p // ps] * ps + p % ps
        key = (int(bucket), int(rows), "prefill")
        if key in self._seen_shapes:
            self.metrics.cache_hits.inc()
            span_name = "prefill"
        else:
            self.metrics.cache_misses.inc()
            self._seen_shapes.add(key)
            span_name = "compile"
        sharded = self._shard_batch({"ids": ids, "mask": mask})
        tokens_in = int(mask.sum())
        with self.tracer.span(span_name, seq=int(bucket), rows=int(rows),
                              streams=int(n), prefill=True, paged=True,
                              tokens=tokens_in, dtype=self.dtype_label,
                              **self._telemetry_attrs(request_ids),
                              **self.span_attrs):
            logits, ks, vs = self._jit_prefill(
                self.params, self.head, sharded["ids"], sharded["mask"],
                last)
            self._cache_k, self._cache_v = self._jit_pinsert(
                self._cache_k, self._cache_v, ks, vs, flat,
                *self._scale_args())
            out = np.asarray(jax.device_get(logits))
        return out[:n]

    def prefill_chunk(self, suffixes: Sequence[Sequence[int]],
                      slot_ids: Sequence[int], starts: Sequence[int],
                      request_ids=None) -> np.ndarray:
        """Partial-hit prefill: only the divergent SUFFIX runs
        (``decoder.paged_chunk_step`` — the chunk attends to the shared
        prefix pages through the table), bucketed over the same ladder
        as prompts (compile key ``(bucket, rows, "chunk")``; warmup
        pre-traces every bucket).  Returns each suffix's last-token
        logits ``[n, vocab]``."""
        self._flush_cow()
        n = len(suffixes)
        assert n and n <= self.prefill_rows
        bucket = pick_bucket(max(len(x) for x in suffixes),
                             self.prefill_buckets)
        rows = self.prefill_rows
        tokens = np.zeros((rows, bucket), np.int32)
        start = np.zeros((rows,), np.int32)
        nreal = np.zeros((rows,), np.int32)
        table = np.full((rows, self.pages_per_stream), self.n_pages,
                        np.int32)
        for i, (x, s, st) in enumerate(zip(suffixes, slot_ids, starts)):
            tokens[i, :len(x)] = x
            start[i] = int(st)
            nreal[i] = len(x)
            if 0 <= s < self.slots:
                table[i] = self._table[s]
        key = (int(bucket), int(rows), "chunk")
        if key in self._seen_shapes:
            self.metrics.cache_hits.inc()
            span_name = "prefill"
        else:
            self.metrics.cache_misses.inc()
            self._seen_shapes.add(key)
            span_name = "compile"
        tokens_in = int(nreal.sum())
        with self.tracer.span(span_name, seq=int(bucket), rows=int(rows),
                              streams=int(n), prefill=True, paged=True,
                              chunk=True, tokens=tokens_in,
                              cached=int(sum(int(s) for s in starts)),
                              dtype=self.dtype_label,
                              **self._telemetry_attrs(request_ids),
                              **self.span_attrs):
            logits, self._cache_k, self._cache_v = self._jit_pchunk(
                self.params, self.head, self._cache_k, self._cache_v,
                tokens, table, start, nreal, *self._scale_args())
            out = np.asarray(jax.device_get(logits))
        return out[:n]

    def decode_batch(self, tokens: np.ndarray, pos: np.ndarray,
                     live: int, request_ids=None) -> np.ndarray:
        """One fixed-shape decode step over the slot block, gathering
        through the per-slot page tables.  Same ONE compile key
        ``("decode", slots)`` as the slot layout — the table is data,
        not shape, so paging cannot retrace."""
        self._flush_cow()
        key = ("decode", int(self.slots))
        if key in self._seen_shapes:
            self.metrics.cache_hits.inc()
            span_name = "decode"
        else:
            self.metrics.cache_misses.inc()
            self._seen_shapes.add(key)
            span_name = "compile"
        tok = np.asarray(tokens, np.int32).reshape(self.slots, 1)
        p = np.clip(np.asarray(pos, np.int32), 0, self.max_len - 1)
        with self.tracer.span(span_name, rows=int(self.slots),
                              live=int(live), decode=True, paged=True,
                              pages_live=self.allocator.used_pages,
                              dtype=self.dtype_label,
                              kv=("int8" if self.kv_int8
                                  else np.dtype(self.kv_dtype).name),
                              **self._telemetry_attrs(request_ids),
                              **self.span_attrs):
            logits, self._cache_k, self._cache_v = self._jit_pdecode(
                self.params, self.head, self._cache_k, self._cache_v,
                tok, jnp.asarray(self._table), p, *self._scale_args())
            out = np.asarray(jax.device_get(logits))
        return out

    def verify_ids(self, window: np.ndarray, pos: np.ndarray,
                   nreal: np.ndarray, live: int,
                   request_ids=None) -> np.ndarray:
        """Speculative verify-1: score a fixed ``[slots, k+1]`` token
        window (pending token + k drafts per live row) in ONE
        prefill-shaped call against the paged cache
        (``models.decoder.paged_verify_step``).  Returns ``[slots, k+1,
        vocab]`` fp32 logits — the greedy target at every window offset.
        The call IS the primary-side commit: accepted positions' K/V is
        already written through the table when it returns, and rejected
        tail writes are invisible behind the position mask (overwritten
        in place next round).  Compile key ``("verify", slots, k+1)`` —
        one program per k, retrace-free once warmed
        (:meth:`warmup_verify`); rows with ``nreal == 0`` are dead and
        write nothing (sentinel table rows)."""
        self._flush_cow()
        k1 = int(window.shape[1])
        key = ("verify", int(self.slots), k1)
        if key in self._seen_shapes:
            self.metrics.cache_hits.inc()
            span_name = "verify"
        else:
            self.metrics.cache_misses.inc()
            self._seen_shapes.add(key)
            span_name = "compile"
        tok = np.asarray(window, np.int32).reshape(self.slots, k1)
        start = np.asarray(pos, np.int32)
        nr = np.asarray(nreal, np.int32)
        with self.tracer.span(span_name, rows=int(self.slots),
                              seq=k1, live=int(live), verify=True,
                              paged=True,
                              pages_live=self.allocator.used_pages,
                              dtype=self.dtype_label,
                              kv=("int8" if self.kv_int8
                                  else np.dtype(self.kv_dtype).name),
                              **self._telemetry_attrs(request_ids),
                              **self.span_attrs):
            logits, self._cache_k, self._cache_v = self._jit_pverify(
                self.params, self.head, self._cache_k, self._cache_v,
                tok, jnp.asarray(self._table), start, nr,
                *self._scale_args())
            out = np.asarray(jax.device_get(logits))
        return out

    def warmup_verify(self, k1: int) -> None:
        """Pre-trace the ``("verify", slots, k1)`` program (all-dead
        window: sentinel tables, zero ``nreal`` — no live page is
        touched).  The speculating batcher warms its configured
        ``draft_k + 1``; adapting k at runtime compiles the new width
        exactly once."""
        window = np.zeros((self.slots, int(k1)), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        nreal = np.zeros((self.slots,), np.int32)
        self.verify_ids(window, pos, nreal, live=0)

    def warmup_decode(self) -> None:
        """Pre-trace every reachable paged shape: per-bucket prefill +
        paged insert, per-bucket suffix chunk, the ONE decode step, the
        fixed COW copy, and the int8 calibration if pending."""
        self._scale_args()
        for b in self.prefill_buckets:
            # OOB slot id: filler tables/flat sentinels — no live page
            # is touched, exactly like the slot engine's warmup
            self.prefill_ids([[self.tokenizer.cls_id] * b], [self.slots])
            self.prefill_chunk([[self.tokenizer.cls_id] * b],
                               [self.slots], [0])
        self._flush_cow(force=True)
        tok = np.zeros((self.slots,), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        self.decode_batch(tok, pos, live=0)

    def kv_snapshot(self) -> Dict:
        """Budget block + the paged story: allocator occupancy/free
        depth/COW and the prefix index's hit accounting — the leaves the
        Prometheus exporter flattens into gauges."""
        return {
            **self.budget.snapshot(),
            "layout": "paged",
            "slots": int(self.slots),
            "max_len": int(self.max_len),
            "kv_dtype": ("int8" if self.kv_int8
                         else str(np.dtype(self.kv_dtype).name)),
            "cache_bytes": decoder.kv_cache_bytes(
                self.cfg, self.n_pages, self.page_sz, self.kv_dtype),
            "pages": self.allocator.snapshot(),
            "prefix": self.prefix.snapshot(),
        }


class DecodeStream:
    """A caller's handle on one generative request — future AND iterator:
    :meth:`result` blocks for the full generation, :meth:`tokens` yields
    token ids as they are produced (the streaming-response surface
    ``serve_tpu.py --decode`` prints from)."""

    __slots__ = ("rid", "prompt_ids", "max_new_tokens", "deadline",
                 "submitted", "born", "first_token_at", "last_token_at",
                 "emitted", "replica", "slot", "spec_accepted",
                 "_q", "_event", "_error")

    def __init__(self, prompt_ids: List[int], max_new_tokens: int,
                 deadline: Optional[float] = None):
        self.rid = mint_request_id()
        self.prompt_ids = list(prompt_ids)
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = deadline
        self.submitted = time.monotonic()
        self.born = self.submitted
        self.first_token_at: Optional[float] = None
        self.last_token_at: Optional[float] = None
        self.emitted: List[int] = []
        self.replica: Optional[int] = None
        self.slot: Optional[int] = None
        self.spec_accepted = 0  # cumulative accepted drafts (monotone)
        self._q: "queue.Queue" = queue.Queue()
        self._event = threading.Event()
        self._error: Optional[BaseException] = None

    # --- worker half ---
    def _push(self, token: int) -> float:
        """Record one generated token; returns the inter-token gap in
        seconds (0.0 for the first — the caller observes ttft instead)."""
        now = time.monotonic()
        gap = 0.0 if self.last_token_at is None \
            else now - self.last_token_at
        if self.first_token_at is None:
            self.first_token_at = now
        self.last_token_at = now
        self.emitted.append(int(token))
        self._q.put(int(token))
        return gap

    def _finish(self, error: Optional[BaseException] = None) -> bool:
        if self._event.is_set():
            return False
        self._error = error
        self._event.set()
        self._q.put(_DONE)
        return True

    # --- caller half ---
    def done(self) -> bool:
        return self._event.is_set()

    def tokens(self, timeout: Optional[float] = 60.0):
        """Yield generated token ids as they arrive; raises the stream's
        error (if any) after the last token."""
        while True:
            item = self._q.get(timeout=timeout)
            if item is _DONE:
                break
            yield item
        if self._error is not None:
            raise self._error

    def result(self, timeout: Optional[float] = 60.0) -> List[int]:
        """Block until the stream finishes; returns ALL generated ids."""
        if not self._event.wait(timeout):
            raise TimeoutError("stream still generating")
        if self._error is not None:
            raise self._error
        return list(self.emitted)


class _Slot:
    __slots__ = ("stream", "pos", "next_token")

    def __init__(self, stream: DecodeStream, pos: int, next_token: int):
        self.stream = stream
        self.pos = pos              # write position of next_token
        self.next_token = next_token


class DecodeBatcher:
    """Continuous batching over one :class:`DecodeEngine`: a single
    worker owns the engine (the repo's one-dispatcher contract) and loops
    claim → prefill → decode-step, with streams joining freed slots and
    finished streams leaving BETWEEN steps — the decode batch shape never
    changes, only which rows are live.

    ``on_death(replica, orphans, error)``: installed by
    :class:`DecodeRouter`; a worker that loses its engine hands over its
    live + waiting streams instead of failing them."""

    #: declared safe range for the ``draft_k`` knob (the controller
    #: clamps inside it; ``0`` = speculation off)
    DRAFT_K_MAX = 8

    def __init__(self, engine: DecodeEngine, *, max_waiting: int = 256,
                 default_max_new: Optional[int] = None, replica: int = 0,
                 on_death: Optional[Callable] = None,
                 rmetrics: Optional[ReplicaMetrics] = None,
                 dmetrics: Optional[DecodeMetrics] = None,
                 drafter: Optional[DecodeEngine] = None,
                 draft_k: int = 4):
        self.engine = engine
        self.tracer = engine.tracer
        self.replica = int(replica)
        engine.span_attrs.setdefault("replica", self.replica)
        # --- speculative decoding: a paired cheap drafter engine ---
        self.drafter: Optional[DecodeEngine] = None
        self.drafter_model = ""
        self.draft_k = max(0, min(int(draft_k), self.DRAFT_K_MAX))
        self._drafter_poison: Optional[BaseException] = None
        self._spec_rounds = 0
        self._spec_drafted = 0
        self._spec_accepted = 0
        if drafter is not None:
            if not (engine.paged and drafter.paged):
                raise ValueError(
                    "speculative decoding needs PAGED engines on both "
                    "sides (--kv_layout paged): the verify commit and "
                    "the draft-page custody both write through page "
                    "tables")
            if (drafter.slots != engine.slots
                    or drafter.max_len != engine.max_len):
                raise ValueError(
                    f"drafter geometry (slots={drafter.slots}, "
                    f"max_len={drafter.max_len}) must match the "
                    f"primary (slots={engine.slots}, "
                    f"max_len={engine.max_len}) — the pair shares slot "
                    "indices and write positions")
            if drafter.prefix_share:
                raise ValueError(
                    "drafter engine must run prefix_share=False: its "
                    "cold prefill rewrites each stream's pages in "
                    "place, which would corrupt shared prefix pages")
            if drafter.tokenizer.vocab_size != engine.tokenizer.vocab_size:
                raise ValueError(
                    "drafter and primary must share one tokenizer: "
                    "drafted token ids are verified (and committed) "
                    "against the primary's vocab")
            drafter.span_attrs.setdefault("replica", self.replica)
            drafter.span_attrs.setdefault("role", "drafter")
            self.drafter = drafter
            self.drafter_model = str(getattr(drafter.args, "model",
                                             "drafter"))
        self.max_waiting = int(max_waiting)
        self.default_max_new = int(
            default_max_new
            or getattr(engine.args, "max_new_tokens", 32))
        self.eos_id = engine.tokenizer.sep_id
        self.on_death = on_death
        self.metrics = dmetrics or DecodeMetrics()
        self.rmetrics = rmetrics or ReplicaMetrics()
        self._slots: List[Optional[_Slot]] = [None] * engine.slots
        self._free: deque = deque(range(engine.slots))
        self._freed_at: Dict[int, float] = {}
        self._waiting: deque = deque()
        #: streams arriving by KV handoff (disaggregated pools): already
        #: prefilled elsewhere, seated here with their imported payload
        self._handoffs: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._stop = False
        self._poison: Optional[BaseException] = None
        self.dead = False
        self._worker: Optional[threading.Thread] = None
        self._peak_live = 0  # high-water concurrent live streams

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "DecodeBatcher":
        if self._worker is None and not self.dead:
            self._stop = False
            self._worker = threading.Thread(
                target=self._run, daemon=True,
                name=f"pdnlp-decode-{self.replica}")
            self._worker.start()
        return self

    def stop(self, drain: bool = True) -> None:
        if self._worker is None:
            return
        if drain:
            with self._lock:
                while (not self.dead and not self._stop
                       and (self._waiting or self._handoffs
                            or self._live_count())):
                    self._wake.wait(timeout=0.05)
        with self._lock:
            self._stop = True
            self._wake.notify_all()
        self._worker.join(timeout=30)
        self._worker = None
        leftovers = []
        with self._lock:
            leftovers += [s for s in self._waiting]
            leftovers += [h[0] for h in self._handoffs]
            still_live = [i for i, sl in enumerate(self._slots)
                          if sl is not None]
            leftovers += [self._slots[i].stream for i in still_live]
            self._waiting.clear()
            self._handoffs.clear()
            self._slots = [None] * self.engine.slots
            self._free = deque(range(self.engine.slots))
        for i in still_live:
            self.engine.detach_slot(i)  # pages back; leak_check clean
            if self.drafter is not None:
                self.drafter.detach_slot(i)
        for s in leftovers:
            if s._finish(RuntimeError("decode batcher stopped")):
                record_hop(self.tracer, s.rid, "failed",
                           error="batcher stopped")

    def __enter__(self) -> "DecodeBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def kill(self, error: Optional[BaseException] = None) -> None:
        """Chaos hook (tests / ``bench.py --decode``): the worker raises
        ``error`` before its next step — exactly the path a real engine
        failure takes."""
        with self._lock:
            self._poison = error or RuntimeError("injected replica kill")
            self._wake.notify_all()

    # ------------------------------------------------------------- submit
    def _live_count(self) -> int:
        return sum(1 for sl in self._slots if sl is not None)

    @property
    def load(self) -> int:
        with self._lock:
            return (self._live_count() + len(self._waiting)
                    + len(self._handoffs))

    def submit_ids(self, ids: Sequence[int],
                   max_new_tokens: Optional[int] = None,
                   deadline_ms: Optional[float] = None) -> DecodeStream:
        """Admit one generative stream; returns its
        :class:`DecodeStream`.  Refusals are LOUD and typed: capacity
        (``ValueError``), KV budget
        (:class:`~pdnlp_tpu.obs.memory.KVBudgetExceeded`), queue bound
        (:class:`~pdnlp_tpu.serve.batcher.QueueFullError`)."""
        ids = list(ids)
        if not ids:
            raise ValueError("empty prompt: submit at least one token id")
        max_new = int(self.default_max_new if max_new_tokens is None
                      else max_new_tokens)  # an explicit 0 must REFUSE,
        #                                     not silently take the default
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        stream = DecodeStream(ids, max_new, deadline)
        tr = self.tracer
        try:
            self.engine.check_stream_admissible(len(ids), max_new)
        except BaseException as e:
            self.metrics.rejected_total.inc()
            record_hop(tr, stream.rid, "rejected",
                       reason=type(e).__name__)
            raise
        # admission-time peek (no side effects: LRU untouched, no hit
        # counters) — the admit hop advertises what sharing will buy
        peek = self.engine.peek_prefix(ids)
        extra = {} if peek is None else {"prefix_hit": peek}
        with self._lock:
            if self.dead or self._stop or self._worker is None:
                raise RuntimeError("decode batcher is not running")
            if len(self._waiting) >= self.max_waiting:
                self.metrics.rejected_total.inc()
                record_hop(tr, stream.rid, "rejected")
                raise QueueFullError(
                    f"decode queue full ({len(self._waiting)}"
                    f"/{self.max_waiting} waiting streams)")
            stream.replica = self.replica
            self._waiting.append(stream)
            self.metrics.streams_total.inc()
            self.metrics.waiting.set(len(self._waiting))
            record_hop(tr, stream.rid, "admit", streamed=True,
                       tokens=len(ids), max_new=max_new,
                       replica=self.replica, **extra)
            self._wake.notify()
        return stream

    def _adopt(self, stream: DecodeStream) -> bool:
        """Router re-home: enqueue an orphan stream's CONTINUATION
        (prompt + emitted-so-far re-prefills here; greedy decode then
        emits exactly the tokens the dead replica would have).  Bypasses
        admission — the stream was already accepted once."""
        with self._lock:
            if self.dead or self._stop or self._worker is None:
                return False
            stream.replica = self.replica
            self._waiting.append(stream)
            self.metrics.waiting.set(len(self._waiting))
            self.rmetrics.requeued_in.inc()
            self._wake.notify()
        return True

    def accept_handoff(self, stream: DecodeStream, pos: int,
                       next_token: int, payload_k, payload_v) -> bool:
        """Disaggregated pools: enqueue a stream whose prefill (and
        first token) already happened on a prefill-role engine.  The
        worker seats it on a cold reservation and scatters the payload
        in (:meth:`PagedDecodeEngine.import_pages`) — no prefill runs
        here, the next step is a plain decode.  Bypasses admission (the
        front door admitted it); ``False`` when this batcher cannot
        take it (dead/stopping), so the dispatcher tries the next
        decode engine — the payload is engine-agnostic."""
        if not self.engine.paged:
            return False  # handoff needs page custody on the receiver
        with self._lock:
            if self.dead or self._stop or self._worker is None:
                return False
            stream.replica = self.replica
            self._handoffs.append((stream, int(pos), int(next_token),
                                   payload_k, payload_v))
            self._wake.notify()
        return True

    # ------------------------------------------------------------- worker
    def _run(self) -> None:
        try:
            while True:
                claims: List[tuple] = []
                # the drafter is WORKER-CONFINED, not lock-guarded: the
                # ctor pairs it before start() and only _degrade_drafter
                # (this thread) ever clears it — a local read outside
                # the lock keeps it out of the lock's footprint
                dr = self.drafter
                imports: List[tuple] = []
                with self._lock:
                    if self._poison is not None:
                        raise self._poison
                    if self._stop:
                        return
                    self._expire_waiting_locked()
                    # handed-off streams seat FIRST: their prefill cost
                    # is already sunk on the prefill pool, and their
                    # payload pins host memory until imported
                    while self._free and self._handoffs:
                        slot = self._free.popleft()
                        ho = self._handoffs.popleft()
                        stream = ho[0]
                        try:
                            # cold reservation (share=False): the import
                            # scatters raw bytes into these pages
                            self.engine.attach_stream(slot, stream,
                                                      share=False)
                        except KVPagesExhausted:
                            self._free.appendleft(slot)
                            self._handoffs.appendleft(ho)
                            break
                        freed = self._freed_at.pop(slot, None)
                        if freed is not None:
                            self.rmetrics.slot_reuse_ms.observe(
                                (time.monotonic() - freed) * 1e3)
                        stream.slot = slot
                        # the seat carries the pending first token and
                        # its write position — the next decode step
                        # continues exactly where the prefill pool left
                        # the stream
                        self._slots[slot] = _Slot(stream, ho[1], ho[2])
                        imports.append((slot,) + ho)
                    while self._free and self._waiting:
                        slot = self._free.popleft()
                        stream = self._waiting.popleft()
                        try:
                            # paged engines reserve the stream's pages
                            # here (sharing any indexed prefix); slot
                            # engines no-op.  Exhausted pool = put both
                            # back and wait for live streams to drain —
                            # head-of-line order is preserved, and the
                            # pool floor (>= one max-length stream)
                            # guarantees an empty batch can always seat
                            # the head, so this cannot deadlock.
                            claim = self.engine.attach_stream(slot,
                                                              stream)
                        except KVPagesExhausted:
                            self._free.appendleft(slot)
                            self._waiting.appendleft(stream)
                            break
                        if dr is not None:
                            try:
                                dr.attach_stream(slot, stream)
                            except KVPagesExhausted:
                                # the PAIR seats together or not at all:
                                # hand the primary reservation back and
                                # wait for live streams to drain (same
                                # no-deadlock floor argument as above,
                                # on the drafter's pool)
                                self.engine.detach_slot(slot)
                                self._free.appendleft(slot)
                                self._waiting.appendleft(stream)
                                break
                            except BaseException as e:  # noqa: BLE001
                                # drafter-side failure must not strand
                                # the stream: poison the drafter (the
                                # next speculate step degrades loudly
                                # to primary-only) and seat the stream
                                # without a draft cache
                                self._drafter_poison = e
                        freed = self._freed_at.pop(slot, None)
                        if freed is not None:
                            self.rmetrics.slot_reuse_ms.observe(
                                (time.monotonic() - freed) * 1e3)
                        stream.slot = slot
                        # placeholder NOW: if the prefill below dies, the
                        # claimed stream is already in _slots and the
                        # death path re-homes it instead of losing it
                        self._slots[slot] = _Slot(stream, 0, 0)
                        claims.append((slot, stream, claim))
                    self.metrics.waiting.set(len(self._waiting))
                    live = self._live_count()
                    if not claims and live == 0:
                        if self._stop:
                            return
                        self._wake.notify_all()  # unblock stop(drain)
                        self._wake.wait(timeout=0.05)
                        continue
                if imports:
                    self._import_handoffs(imports)
                if claims:
                    self._prefill(claims)
                with self._lock:
                    # _slots is mutated under the lock from stop()/kill()
                    # callers — the between-steps liveness peek must not
                    # read it bare (threadlint T1)
                    any_live = self._live_count() > 0
                if any_live:
                    if self.drafter is not None and self.draft_k > 0:
                        self._speculate_step()
                    else:
                        self._decode_step()
                with self._lock:
                    self._wake.notify_all()
        except BaseException as e:  # noqa: BLE001 — a dead engine must
            self._die(e)           # never strand callers or streams

    def _expire_waiting_locked(self) -> None:
        now = time.monotonic()
        keep: deque = deque()
        for s in self._waiting:
            if s.deadline is not None and now >= s.deadline:
                self.metrics.deadline_expired_total.inc()
                if s._finish(DeadlineExceeded(
                        "deadline passed while waiting for a slot")):
                    record_hop(self.tracer, s.rid, "deadline")
            else:
                keep.append(s)
        self._waiting = keep

    def _import_handoffs(self, imports: List[tuple]) -> None:
        """Scatter each seated handoff's payload into its fresh
        reservation (worker-only, engine call off-lock).  No hop is
        recorded here — the SENDER records the ``handoff`` hop when the
        dispatch acks, and no token is emitted — the first token rode
        the payload and was already pushed by the prefill pool."""
        for slot, stream, _pos, _tok, pk, pv in imports:
            self.engine.import_pages(slot, pk, pv,
                                     request_ids=[stream.rid])
        self._update_kv_gauge()

    def retire(self) -> List[DecodeStream]:
        """Stop this worker WITHOUT failing its streams: detach every
        reservation and hand back live + waiting + queued-handoff
        streams.  The pool-resplit path
        (:meth:`DisaggDecodeRouter.set_prefill_share`) re-homes them
        through the front door — a live stream re-prefills ``prompt +
        emitted`` elsewhere, and greedy determinism keeps its remaining
        tokens identical."""
        with self._lock:
            self._stop = True
            self._wake.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=30)
            self._worker = None
        leftovers: List[DecodeStream] = []
        with self._lock:
            leftovers += list(self._waiting)
            leftovers += [h[0] for h in self._handoffs]
            still_live = [i for i, sl in enumerate(self._slots)
                          if sl is not None]
            leftovers += [self._slots[i].stream for i in still_live]
            self._waiting.clear()
            self._handoffs.clear()
            self._slots = [None] * self.engine.slots
            self._free = deque(range(self.engine.slots))
        for i in still_live:
            self.engine.detach_slot(i)
            if self.drafter is not None:
                self.drafter.detach_slot(i)
        return leftovers

    def _prefill(self, claims: List[tuple]) -> None:
        """Prefill claimed streams and emit each stream's FIRST token.

        Paged claims split three ways by prefix-hit kind: **full** hits
        run NO forward at all — the index stored the prompt's first
        greedy token, so it is emitted right here (``prefills_total``
        does not move: the bench's zero-prefill gate is structural);
        **partial** hits forward only the divergent suffix
        (:meth:`PagedDecodeEngine.prefill_chunk`); **cold** claims (and
        every slot-engine claim, whose attach hook returns ``None``)
        take the classic bucketed prefill, chunked to the engine's fixed
        prefill rows.  Every stream still records a ``prefill`` hop —
        the chain contract (no ``decode`` before ``prefill``) holds for
        hits too, with ``prefix_hit``/``cached_tokens`` telling the
        story."""
        rows = self.engine.prefill_rows
        if self.drafter is not None:
            # the drafter's cache needs the SAME prompt K/V before it
            # can draft: always the cold path (prefix_share is off on
            # drafter engines), chunked to the drafter's fixed rows,
            # then the reservation's uncommitted tail moves to the
            # draft owner.  Any failure here degrades the pair to
            # primary-only decode — the primary prefill below still
            # seats every stream.
            try:
                if self._drafter_poison is not None:
                    raise self._drafter_poison
                rows_d = self.drafter.prefill_rows
                for i in range(0, len(claims), rows_d):
                    ch = claims[i:i + rows_d]
                    self.drafter.prefill_ids(
                        [s.prompt_ids + s.emitted for _, s, _ in ch],
                        [slot for slot, _, _ in ch],
                        request_ids=[s.rid for _, s, _ in ch])
                    for slot, s, _ in ch:
                        self.drafter.split_draft_custody(
                            slot, len(s.prompt_ids) + len(s.emitted))
            except BaseException as e:  # noqa: BLE001
                self._degrade_drafter(e)
        full = [c for c in claims
                if c[2] is not None and c[2].kind == "full"]
        part = [c for c in claims
                if c[2] is not None and c[2].kind == "partial"]
        cold = [c for c in claims
                if c[2] is None or c[2].kind == "cold"]
        now = time.monotonic()
        for slot, stream, claim in full:
            ntok = len(claim.tokens)
            record_hop(self.tracer, stream.rid, "prefill", slot=slot,
                       tokens_in=ntok, replica=self.replica,
                       prefix_hit="full", cached_tokens=ntok)
            self.metrics.ttft_ms.observe((now - stream.born) * 1e3)
            # refresh the index entry's LRU standing (register of an
            # existing key is a touch, not a re-insert)
            self.engine.register_slot(slot, claim.first_token)
            self._advance(slot, stream, int(claim.first_token), pos=ntok)
        for i in range(0, len(cold), rows):
            chunk = cold[i:i + rows]
            prompts = [s.prompt_ids + s.emitted for _, s, _ in chunk]
            logits = self.engine.prefill_ids(
                prompts, [slot for slot, _, _ in chunk],
                request_ids=[s.rid for _, s, _ in chunk])
            self.metrics.prefills_total.inc()
            self.metrics.prefill_tokens_total.inc(
                sum(len(p) for p in prompts))
            now = time.monotonic()
            for j, (slot, stream, claim) in enumerate(chunk):
                extra = {"prefix_hit": "miss"} if claim is not None else {}
                record_hop(self.tracer, stream.rid, "prefill", slot=slot,
                           tokens_in=len(prompts[j]),
                           replica=self.replica, **extra)
                self.metrics.ttft_ms.observe((now - stream.born) * 1e3)
                tok = int(np.argmax(logits[j]))
                self.engine.register_slot(slot, tok)
                self._advance(slot, stream, tok, pos=len(prompts[j]))
        for i in range(0, len(part), rows):
            chunk = part[i:i + rows]
            suffixes = [c.suffix for _, _, c in chunk]
            logits = self.engine.prefill_chunk(
                suffixes, [slot for slot, _, _ in chunk],
                [c.start for _, _, c in chunk],
                request_ids=[s.rid for _, s, _ in chunk])
            self.metrics.prefills_total.inc()
            self.metrics.prefill_tokens_total.inc(
                sum(len(x) for x in suffixes))
            now = time.monotonic()
            for j, (slot, stream, claim) in enumerate(chunk):
                record_hop(self.tracer, stream.rid, "prefill", slot=slot,
                           tokens_in=len(suffixes[j]),
                           replica=self.replica, prefix_hit="partial",
                           cached_tokens=claim.start)
                self.metrics.ttft_ms.observe((now - stream.born) * 1e3)
                tok = int(np.argmax(logits[j]))
                self.engine.register_slot(slot, tok)
                self._advance(slot, stream, tok,
                              pos=len(claim.tokens))
        self._update_kv_gauge()

    def _advance(self, slot: int, stream: DecodeStream, tok: int, *,
                 pos: int) -> None:
        """Handle one newly produced token for ``stream``: emit it (or
        the EOS/stop decision), and either keep the slot live with the
        token as the next decode input or finish + free the slot.
        ``pos`` = the write position the NEXT decode step would use."""
        remaining = stream.max_new_tokens - len(stream.emitted)
        finish = False
        if tok == self.eos_id or remaining <= 0:
            finish = True       # EOS is a stop decision, not an emission
        else:
            gap = stream._push(tok)
            if gap > 0.0:
                self.metrics.intertoken_ms.observe(gap * 1e3)
            self.metrics.tokens_out_total.inc()
            if (len(stream.emitted) >= stream.max_new_tokens
                    or pos >= self.engine.max_len):
                finish = True
        with self._lock:
            if finish:
                self._slots[slot] = None
                self._free.append(slot)
                self._freed_at[slot] = time.monotonic()
            else:
                self._slots[slot] = _Slot(stream, pos, tok)
        if finish:
            # release the stream's pages (refcount decrement — shared
            # prefix pages stay live under the index / other streams);
            # worker-only, so after the lock is fine
            self.engine.detach_slot(slot)
            if self.drafter is not None:
                self.drafter.detach_slot(slot)  # draft custody included
            if stream._finish():
                record_hop(self.tracer, stream.rid, "complete",
                           replica=self.replica, slot=slot,
                           tokens_out=len(stream.emitted))

    def _decode_step(self) -> None:
        """ONE fixed-shape decode step over the slot block; live rows
        advance their streams, dead rows ride as junk."""
        tokens = np.zeros((self.engine.slots,), np.int32)
        pos = np.zeros((self.engine.slots,), np.int32)
        with self._lock:
            live = [(i, sl) for i, sl in enumerate(self._slots)
                    if sl is not None]
            for i, sl in live:
                tokens[i] = sl.next_token
                pos[i] = sl.pos
        if not live:
            return
        logits = self.engine.decode_batch(
            tokens, pos, live=len(live),
            request_ids=[sl.stream.rid for _, sl in live])
        self.metrics.decode_steps_total.inc()
        self.rmetrics.slot_occupancy.observe(
            len(live) / float(self.engine.slots))
        self.rmetrics.batches_total.inc()
        for i, sl in live:
            tok = int(np.argmax(logits[i]))
            # hop BEFORE _advance so a completing stream's terminal stays
            # last; tokens_out = cumulative emissions including this step
            # (EOS is a stop decision, not an emission)
            emitted = len(sl.stream.emitted)
            record_hop(self.tracer, sl.stream.rid, "decode", slot=i,
                       step=emitted,
                       tokens_out=emitted + (tok != self.eos_id),
                       replica=self.replica)
            self._advance(i, sl.stream, tok, pos=sl.pos + 1)
        self._update_kv_gauge()

    # ------------------------------------------------------- speculation
    def _speculate_step(self) -> None:
        """One draft-k / verify-1 round over the slot block.

        The drafter runs k FIXED-shape decode steps against its own
        paged cache (feeding each argmax back in — the classic decode
        loop, just on the cheap model), then the primary scores the
        whole ``[slots, k+1]`` window ``[pending, draft_1..draft_k]`` in
        ONE :meth:`PagedDecodeEngine.verify_ids` call.  Row ``i``'s
        greedy targets ``t_0..t_k`` satisfy: ``t_j`` is the primary's
        next token after window position ``j``.  The longest prefix with
        ``draft_j == t_{j-1}`` (length ``a``) is accepted, and the round
        emits ``t_0..t_a`` — a+1 tokens, every one a PRIMARY argmax, so
        the emitted sequence is identical to primary-only greedy decode
        whatever the drafter says (worst case a=0 still emits ``t_0``,
        the plain decode step's token).  The verify call already wrote
        the accepted positions' K/V (primary commit); the drafter's
        boundary-crossed pages transfer to the stream owner
        (:meth:`PagedDecodeEngine.commit_draft`) and its rejected tail
        is overwritten in place next round.  A drafter failure anywhere
        degrades to :meth:`_decode_step` — loudly, decision-recorded —
        and the round re-runs primary-only."""
        k = self.draft_k
        eng, dr = self.engine, self.drafter
        tokens = np.zeros((eng.slots,), np.int32)
        pos = np.zeros((eng.slots,), np.int32)
        with self._lock:
            live = [(i, sl) for i, sl in enumerate(self._slots)
                    if sl is not None]
            for i, sl in live:
                tokens[i] = sl.next_token
                pos[i] = sl.pos
        if not live:
            return
        rids = [sl.stream.rid for _, sl in live]
        window = np.zeros((eng.slots, k + 1), np.int32)
        window[:, 0] = tokens
        try:
            if self._drafter_poison is not None:
                raise self._drafter_poison
            cur = tokens.copy()
            for j in range(k):
                dlogits = dr.decode_batch(cur, pos + j, live=len(live),
                                          request_ids=rids)
                cur = np.argmax(dlogits, axis=-1).astype(np.int32)
                window[:, j + 1] = cur
        except BaseException as e:  # noqa: BLE001 — drafter death must
            self._degrade_drafter(e)  # never take the primary with it
            self._decode_step()
            return
        self.metrics.draft_tokens_total.inc(k * len(live))
        self.metrics.spec_rounds_total.inc()
        self._spec_rounds += 1
        nreal = np.zeros((eng.slots,), np.int32)
        for i, _ in live:
            nreal[i] = k + 1
        vlogits = eng.verify_ids(window, pos, nreal, live=len(live),
                                 request_ids=rids)
        self.metrics.verify_calls_total.inc()
        self.metrics.decode_steps_total.inc()
        targets = np.argmax(vlogits, axis=-1)        # [slots, k+1]
        self.rmetrics.slot_occupancy.observe(
            len(live) / float(eng.slots))
        self.rmetrics.batches_total.inc()
        for i, sl in live:
            a = 0
            while a < k and window[i, a + 1] == targets[i, a]:
                a += 1
            stream = sl.stream
            stream.spec_accepted += a
            self._spec_drafted += k
            self._spec_accepted += a
            self.metrics.accepted_tokens_total.inc(a)
            # hops BEFORE advancing, so a completing stream's terminal
            # stays last; accepted is CUMULATIVE per stream (the chain
            # rule pins it monotone)
            record_hop(self.tracer, stream.rid, "draft", slot=i, k=k,
                       drafter_model=self.drafter_model,
                       replica=self.replica)
            record_hop(self.tracer, stream.rid, "verify", slot=i, k=k,
                       matched=a, accepted=stream.spec_accepted,
                       replica=self.replica)
            base = sl.pos
            for m in range(a + 1):
                self._advance(i, stream, int(targets[i, m]),
                              pos=base + m + 1)
                with self._lock:
                    freed = self._slots[i] is None
                if freed:
                    break
            else:
                # stream survived the round: its committed cache length
                # is the new pending write position — move any
                # boundary-crossed draft pages to the stream owner
                dr.commit_draft(i, base + a + 1)
        if self._spec_drafted:
            self.metrics.accept_rate.set(
                self._spec_accepted / float(self._spec_drafted))
        self._update_kv_gauge()

    def _degrade_drafter(self, error: BaseException) -> None:
        """Drafter death mid-storm: degrade the pair to primary-only
        decode — LOUD, decision-recorded, streams keep flowing.  Parity
        is unaffected: the primary cache holds every committed token, so
        plain decode continues the exact greedy sequence.  Worker-only
        (like every engine call); must NOT be called with ``_lock``
        held."""
        dr, k_old = self.drafter, self.draft_k
        if dr is None:
            return
        self.drafter = None
        self._drafter_poison = None
        print(f"[serve.decode] replica {self.replica}: drafter "
              f"{self.drafter_model!r} died "
              f"({type(error).__name__}: {error}) — degrading to "
              "primary-only decode", file=sys.stderr)
        self.metrics.drafter_deaths_total.inc()
        did = mint_decision_id()
        record_decision(self.tracer, did, "action", knob="draft_k",
                        old=k_old, new=0, forced=True,
                        replica=self.replica,
                        cause={"kind": "drafter_death",
                               "error": type(error).__name__,
                               "drafter_model": self.drafter_model})
        record_decision(self.tracer, did, "outcome", knob="draft_k",
                        result="degraded", kept=True,
                        replica=self.replica)
        with self._lock:
            live = [i for i, sl in enumerate(self._slots)
                    if sl is not None]
        for i in live:
            try:
                dr.detach_slot(i)  # draft custody released with it
            except BaseException:  # noqa: BLE001 — best-effort: the
                pass               # engine may be the thing that died

    def kill_drafter(self, error: Optional[BaseException] = None) -> None:
        """Chaos hook (tests / ``bench.py --decode``): the next
        speculation round sees the drafter raise — exactly the path a
        real drafter engine failure takes."""
        self._drafter_poison = error or RuntimeError(
            "injected drafter kill")

    def set_draft_k(self, k: int) -> int:
        """Actuate the ``draft_k`` knob (controller/router door): clamp
        into the declared safe range and apply before the next round.
        ``0`` pauses speculation (plain decode steps; the drafter cache
        goes stale, so acceptance restarts low if re-enabled — the
        controller's revert law owns that call).  A new k's verify
        width compiles exactly once."""
        k = max(0, min(int(k), self.DRAFT_K_MAX))
        with self._lock:
            self.draft_k = k
        return k

    def spec_snapshot(self) -> Dict:
        """Speculation accounting for ``control_snapshot``/``healthz``:
        configured k, live acceptance, and the per-model split the
        exporter renders with ``{model=...}`` labels."""
        drafted, accepted = self._spec_drafted, self._spec_accepted
        rate = accepted / float(drafted) if drafted else 0.0
        out = {
            "enabled": int(self.drafter is not None),
            "draft_k": int(self.draft_k),
            "draft_tokens": int(drafted),
            "accepted_tokens": int(accepted),
            "accept_rate": rate,
            "rounds": int(self._spec_rounds),
        }
        if self.drafter_model:
            primary = str(getattr(self.engine.args, "model", "primary"))
            # a same-architecture drafter (distilled checkpoint) shares
            # the primary's model name — suffix its label so the two
            # Prometheus series never collapse into one
            dm = self.drafter_model if self.drafter_model != primary \
                else self.drafter_model + "-draft"
            out["by_model"] = {
                dm: {"draft_tokens": int(drafted), "role": "drafter"},
                primary: {"accepted_tokens": int(accepted),
                          "accept_rate": rate},
            }
        return out

    def _update_kv_gauge(self) -> None:
        with self._lock:
            live_tokens = sum(sl.pos for sl in self._slots
                              if sl is not None)
            live_slots = self._live_count()
        nbytes = live_tokens * self.engine.token_bytes
        self.engine.budget.set_live(nbytes)
        self.metrics.kv_bytes_live.set(nbytes)
        self.metrics.kv_slots_live.set(live_slots)
        if live_slots > self._peak_live:
            self._peak_live = live_slots
            self.metrics.peak_live_streams.set(live_slots)
        if self.engine.paged:
            alloc = self.engine.allocator
            self.metrics.kv_pages_live.set(alloc.used_pages)
            self.metrics.kv_pages_free.set(alloc.free_pages)

    def _die(self, error: BaseException) -> None:
        """Worker death: collect every stream this replica owes an answer
        (live slots + waiting) and hand them to the router — or fail them
        loudly when there is no router to re-home onto."""
        with self._lock:
            self.dead = True
            orphans = [sl.stream for sl in self._slots if sl is not None]
            orphans += [h[0] for h in self._handoffs]
            orphans += list(self._waiting)
            self._waiting.clear()
            self._handoffs.clear()
            self._slots = [None] * self.engine.slots
            self._free = deque(range(self.engine.slots))
            self.rmetrics.ejections.inc()
            self._wake.notify_all()
        if self.on_death is not None:
            self.on_death(self.replica, orphans, error)
        else:
            for s in orphans:
                if s._finish(error):
                    record_hop(self.tracer, s.rid, "failed",
                               error=type(error).__name__)

    # ------------------------------------------------------------ surface
    def warmup(self) -> None:
        self.engine.warmup_decode()
        if self.drafter is not None:
            # drafter decode + the primary's verify width: after this,
            # a full speculation round compiles nothing
            self.drafter.warmup_decode()
            self.engine.warmup_verify(self.draft_k + 1)

    def snapshot(self) -> Dict:
        out = {
            "decode": self.metrics.snapshot(),
            "replica": self.rmetrics.snapshot(),
            "kv": self.engine.kv_snapshot(),
            "engine": self.engine.metrics.snapshot(),
        }
        if self.drafter is not None or self._spec_rounds:
            out["speculation"] = self.spec_snapshot()
            if self.drafter is not None:
                out["drafter"] = {
                    "model": self.drafter_model,
                    "kv": self.drafter.kv_snapshot(),
                    "engine": self.drafter.metrics.snapshot(),
                }
        return out


class PrefillWorker:
    """Prefill-role half of a disaggregated pool: one worker owns one
    PAGED engine and runs ONLY the prefill phase — bucketed cold
    forwards, prefix full/partial hits, chunked suffixes — then moves
    each stream's pages to a decode-role engine through the KV handoff.
    Decode-role engines never see a prefill after warmup, so a prefill
    burst cannot steal inter-token latency from live streams (the
    disaggregation argument: the two phases have opposite compute
    profiles, DistServe OSDI'24 / Splitwise ISCA'24).

    Custody per handoff, in order: **export** (fixed-shape page gather
    to a host payload) → **stage** (:meth:`PagedDecodeEngine.
    begin_handoff` — the page refs move to the staging owner and the
    slot frees for the next prompt) → **dispatch** (the router
    callback: local seat or socket frame + ack) → **release** the
    staged owner — exactly ONE discharge point whatever the outcome,
    so both allocators' ``leak_check`` reconcile to zero after drain.
    A failed dispatch re-queues the stream for re-prefill (the payload
    is disposable: ``prompt + emitted`` regenerates it bitwise).

    A stream whose FIRST token already finishes it (EOS, budget 1)
    completes right here and never hands off — same ``complete``
    semantics as the interleaved batcher's prefill-time finish."""

    def __init__(self, engine: DecodeEngine, *,
                 dispatch: Callable, max_waiting: int = 256,
                 default_max_new: Optional[int] = None, replica: int = 0,
                 on_death: Optional[Callable] = None,
                 rmetrics: Optional[ReplicaMetrics] = None,
                 dmetrics: Optional[DecodeMetrics] = None):
        if not engine.paged:
            raise ValueError(
                "disaggregated prefill needs a PAGED engine "
                "(--kv_layout paged): the handoff exports page custody")
        self.engine = engine
        self.tracer = engine.tracer
        self.replica = int(replica)
        engine.span_attrs.setdefault("replica", self.replica)
        engine.span_attrs["pool"] = "prefill"
        self.dispatch = dispatch
        self.max_waiting = int(max_waiting)
        self.default_max_new = int(
            default_max_new
            or getattr(engine.args, "max_new_tokens", 32))
        self.eos_id = engine.tokenizer.sep_id
        self.on_death = on_death
        self.metrics = dmetrics or DecodeMetrics()
        self.rmetrics = rmetrics or ReplicaMetrics()
        self._slots: List[Optional[_Slot]] = [None] * engine.slots
        self._free: deque = deque(range(engine.slots))
        self._freed_at: Dict[int, float] = {}
        self._waiting: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._stop = False
        self._poison: Optional[BaseException] = None
        self.dead = False
        self._worker: Optional[threading.Thread] = None
        self._peak_live = 0

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "PrefillWorker":
        if self._worker is None and not self.dead:
            self._stop = False
            self._worker = threading.Thread(
                target=self._run, daemon=True,
                name=f"pdnlp-prefill-{self.replica}")
            self._worker.start()
        return self

    def stop(self, drain: bool = True) -> None:
        if self._worker is None:
            return
        if drain:
            with self._lock:
                while (not self.dead and not self._stop
                       and (self._waiting or self._live_count())):
                    self._wake.wait(timeout=0.05)
        with self._lock:
            self._stop = True
            self._wake.notify_all()
        self._worker.join(timeout=30)
        self._worker = None
        leftovers = []
        with self._lock:
            leftovers += list(self._waiting)
            still_live = [i for i, sl in enumerate(self._slots)
                          if sl is not None]
            leftovers += [self._slots[i].stream for i in still_live]
            self._waiting.clear()
            self._slots = [None] * self.engine.slots
            self._free = deque(range(self.engine.slots))
        for i in still_live:
            self.engine.detach_slot(i)
        for s in leftovers:
            if s._finish(RuntimeError("prefill worker stopped")):
                record_hop(self.tracer, s.rid, "failed",
                           error="worker stopped")

    def retire(self) -> List[DecodeStream]:
        """Stop WITHOUT failing streams (pool re-split): detach every
        reservation and hand back waiting + mid-prefill streams for the
        router to re-home through the front door."""
        with self._lock:
            self._stop = True
            self._wake.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=30)
            self._worker = None
        leftovers: List[DecodeStream] = []
        with self._lock:
            leftovers += list(self._waiting)
            still_live = [i for i, sl in enumerate(self._slots)
                          if sl is not None]
            leftovers += [self._slots[i].stream for i in still_live]
            self._waiting.clear()
            self._slots = [None] * self.engine.slots
            self._free = deque(range(self.engine.slots))
        for i in still_live:
            self.engine.detach_slot(i)
        return leftovers

    def __enter__(self) -> "PrefillWorker":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def kill(self, error: Optional[BaseException] = None) -> None:
        """Chaos hook: the worker raises before its next batch."""
        with self._lock:
            self._poison = error or RuntimeError("injected replica kill")
            self._wake.notify_all()

    # ------------------------------------------------------------- submit
    def _live_count(self) -> int:
        return sum(1 for sl in self._slots if sl is not None)

    @property
    def load(self) -> int:
        with self._lock:
            return self._live_count() + len(self._waiting)

    def submit_ids(self, ids: Sequence[int],
                   max_new_tokens: Optional[int] = None,
                   deadline_ms: Optional[float] = None) -> DecodeStream:
        """Admit one generative stream (the disaggregated front door —
        same typed refusals as :meth:`DecodeBatcher.submit_ids`)."""
        ids = list(ids)
        if not ids:
            raise ValueError("empty prompt: submit at least one token id")
        max_new = int(self.default_max_new if max_new_tokens is None
                      else max_new_tokens)
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        stream = DecodeStream(ids, max_new, deadline)
        tr = self.tracer
        try:
            self.engine.check_stream_admissible(len(ids), max_new)
        except BaseException as e:
            self.metrics.rejected_total.inc()
            record_hop(tr, stream.rid, "rejected",
                       reason=type(e).__name__)
            raise
        peek = self.engine.peek_prefix(ids)
        extra = {} if peek is None else {"prefix_hit": peek}
        with self._lock:
            if self.dead or self._stop or self._worker is None:
                raise RuntimeError("prefill worker is not running")
            if len(self._waiting) >= self.max_waiting:
                self.metrics.rejected_total.inc()
                record_hop(tr, stream.rid, "rejected")
                raise QueueFullError(
                    f"prefill queue full ({len(self._waiting)}"
                    f"/{self.max_waiting} waiting streams)")
            stream.replica = self.replica
            self._waiting.append(stream)
            self.metrics.streams_total.inc()
            self.metrics.waiting.set(len(self._waiting))
            record_hop(tr, stream.rid, "admit", streamed=True,
                       tokens=len(ids), max_new=max_new,
                       replica=self.replica, pool="prefill", **extra)
            self._wake.notify()
        return stream

    def _adopt(self, stream: DecodeStream) -> bool:
        """Router re-home (replica death / pool re-split): enqueue an
        orphan's continuation — ``prompt + emitted`` re-prefills here
        and hands off again; greedy determinism keeps the remaining
        tokens identical.  Bypasses admission."""
        with self._lock:
            if self.dead or self._stop or self._worker is None:
                return False
            stream.replica = self.replica
            self._waiting.append(stream)
            self.metrics.waiting.set(len(self._waiting))
            self.rmetrics.requeued_in.inc()
            self._wake.notify()
        return True

    # ------------------------------------------------------------- worker
    def _run(self) -> None:
        try:
            while True:
                claims: List[tuple] = []
                with self._lock:
                    if self._poison is not None:
                        raise self._poison
                    if self._stop:
                        return
                    self._expire_waiting_locked()
                    # at most ONE prefill group per iteration: claiming
                    # every free slot would serialize several prefill
                    # forwards ahead of _dispatch_all, and an earlier
                    # group's staged streams would sit undispatched —
                    # their first decode-pool gap eating a later group's
                    # prefill cost (the stall disaggregation deletes)
                    rows = self.engine.prefill_rows
                    while self._free and self._waiting \
                            and len(claims) < rows:
                        slot = self._free.popleft()
                        stream = self._waiting.popleft()
                        try:
                            claim = self.engine.attach_stream(slot,
                                                              stream)
                        except KVPagesExhausted:
                            # retry as in-flight handoffs release their
                            # staged pages (same iteration, below) —
                            # the pool floor argument the interleaved
                            # batcher makes, on the staging ledger
                            self._free.appendleft(slot)
                            self._waiting.appendleft(stream)
                            break
                        freed = self._freed_at.pop(slot, None)
                        if freed is not None:
                            self.rmetrics.slot_reuse_ms.observe(
                                (time.monotonic() - freed) * 1e3)
                        stream.slot = slot
                        self._slots[slot] = _Slot(stream, 0, 0)
                        claims.append((slot, stream, claim))
                    self.metrics.waiting.set(len(self._waiting))
                    live = self._live_count()
                    if live > self._peak_live:
                        self._peak_live = live
                        self.metrics.peak_live_streams.set(live)
                    if not claims:
                        if self._stop:
                            return
                        self._wake.notify_all()  # unblock stop(drain)
                        self._wake.wait(timeout=0.05)
                        continue
                self._prefill(claims)  # dispatches per staged stream
                with self._lock:
                    self._wake.notify_all()
        except BaseException as e:  # noqa: BLE001 — a dead engine must
            self._die(e)           # never strand callers or streams

    def _expire_waiting_locked(self) -> None:
        now = time.monotonic()
        keep: deque = deque()
        for s in self._waiting:
            if s.deadline is not None and now >= s.deadline:
                self.metrics.deadline_expired_total.inc()
                if s._finish(DeadlineExceeded(
                        "deadline passed while waiting for a slot")):
                    record_hop(self.tracer, s.rid, "deadline")
            else:
                keep.append(s)
        self._waiting = keep

    def _prefill(self, claims: List[tuple]) -> None:
        """Prefill the claimed batch (full/partial/cold — the
        interleaved batcher's exact three-way split), STAGE every
        surviving stream for handoff, and dispatch each the moment its
        export lands: a staged payload held back while a LATER stream's
        prefill forward runs would charge that forward to the earlier
        stream's first decode-pool gap — the exact stall the pool split
        exists to delete."""
        rows = self.engine.prefill_rows
        full = [c for c in claims if c[2].kind == "full"]
        part = [c for c in claims if c[2].kind == "partial"]
        cold = [c for c in claims if c[2].kind == "cold"]
        now = time.monotonic()
        for slot, stream, claim in full:
            ntok = len(claim.tokens)
            record_hop(self.tracer, stream.rid, "prefill", slot=slot,
                       tokens_in=ntok, replica=self.replica,
                       prefix_hit="full", cached_tokens=ntok)
            self.metrics.ttft_ms.observe((now - stream.born) * 1e3)
            self.engine.register_slot(slot, claim.first_token)
            h = self._emit_first(slot, stream, int(claim.first_token),
                                 pos=ntok)
            if h is not None:
                self._dispatch_all([h])
        for i in range(0, len(cold), rows):
            chunk = cold[i:i + rows]
            prompts = [s.prompt_ids + s.emitted for _, s, _ in chunk]
            logits = self.engine.prefill_ids(
                prompts, [slot for slot, _, _ in chunk],
                request_ids=[s.rid for _, s, _ in chunk])
            self.metrics.prefills_total.inc()
            self.metrics.prefill_tokens_total.inc(
                sum(len(p) for p in prompts))
            now = time.monotonic()
            for j, (slot, stream, claim) in enumerate(chunk):
                record_hop(self.tracer, stream.rid, "prefill",
                           slot=slot, tokens_in=len(prompts[j]),
                           replica=self.replica, prefix_hit="miss")
                self.metrics.ttft_ms.observe((now - stream.born) * 1e3)
                tok = int(np.argmax(logits[j]))
                self.engine.register_slot(slot, tok)
                h = self._emit_first(slot, stream, tok,
                                     pos=len(prompts[j]))
                if h is not None:
                    self._dispatch_all([h])
        for i in range(0, len(part), rows):
            chunk = part[i:i + rows]
            suffixes = [c.suffix for _, _, c in chunk]
            logits = self.engine.prefill_chunk(
                suffixes, [slot for slot, _, _ in chunk],
                [c.start for _, _, c in chunk],
                request_ids=[s.rid for _, s, _ in chunk])
            self.metrics.prefills_total.inc()
            self.metrics.prefill_tokens_total.inc(
                sum(len(x) for x in suffixes))
            now = time.monotonic()
            for j, (slot, stream, claim) in enumerate(chunk):
                record_hop(self.tracer, stream.rid, "prefill",
                           slot=slot, tokens_in=len(suffixes[j]),
                           replica=self.replica, prefix_hit="partial",
                           cached_tokens=claim.start)
                self.metrics.ttft_ms.observe((now - stream.born) * 1e3)
                tok = int(np.argmax(logits[j]))
                self.engine.register_slot(slot, tok)
                h = self._emit_first(slot, stream, tok,
                                     pos=len(claim.tokens))
                if h is not None:
                    self._dispatch_all([h])
        self._update_kv_gauge()

    def _emit_first(self, slot: int, stream: DecodeStream, tok: int, *,
                    pos: int) -> Optional[tuple]:
        """Emit (or stop on) the prefill's first token.  A stream that
        completes AT prefill never hands off; every other stream
        exports its payload, stages custody, frees the slot, and
        returns the handoff tuple for :meth:`_dispatch_all`."""
        remaining = stream.max_new_tokens - len(stream.emitted)
        finish = False
        if tok == self.eos_id or remaining <= 0:
            finish = True       # EOS is a stop decision, not an emission
        else:
            stream._push(tok)   # first token: ttft already observed
            self.metrics.tokens_out_total.inc()
            if (len(stream.emitted) >= stream.max_new_tokens
                    or pos >= self.engine.max_len):
                finish = True
        if finish:
            with self._lock:
                self._slots[slot] = None
                self._free.append(slot)
                self._freed_at[slot] = time.monotonic()
            self.engine.detach_slot(slot)
            if stream._finish():
                record_hop(self.tracer, stream.rid, "complete",
                           replica=self.replica, slot=slot,
                           tokens_out=len(stream.emitted))
            return None
        pk, pv = self.engine.export_pages(slot,
                                          request_ids=[stream.rid])
        staged, pages = self.engine.begin_handoff(slot)
        with self._lock:
            self._slots[slot] = None
            self._free.append(slot)
            self._freed_at[slot] = time.monotonic()
        return (stream, pos, tok, staged, pages, pk, pv)

    def _dispatch_all(self, handoffs: List[tuple]) -> None:
        """Move each staged payload to a decode engine via the router's
        dispatch callback and settle its custody: the staged owner is
        released at exactly ONE point whatever happened (the payload is
        self-contained once exported; a failed dispatch regenerates it
        by re-prefill).  The ``handoff`` hop is recorded by the
        dispatcher per placement attempt (before the seat — the
        requeue-hop ordering precedent), so only metrics land here."""
        alloc = self.engine.allocator
        for stream, pos, tok, staged, pages, pk, pv in handoffs:
            t0 = time.monotonic()
            meta = {"rid": stream.rid, "pos": int(pos),
                    "next_token": int(tok),
                    "prompt_len": len(stream.prompt_ids),
                    "n_pages": len(pages)}
            placed = None
            try:
                placed = self.dispatch(stream, meta, pk, pv)
            except BaseException:  # noqa: BLE001 — a dispatch crash is
                placed = None      # a failed placement, not worker death
            finally:
                alloc.release_owner(staged)
            if placed is None:
                self.metrics.handoff_failures_total.inc()
                with self._lock:
                    if self._stop or self.dead:
                        lost = stream
                    else:
                        lost = None
                        self._waiting.appendleft(stream)  # re-prefill
                if lost is not None and lost._finish(RuntimeError(
                        "handoff dispatch failed")):
                    record_hop(self.tracer, lost.rid, "failed",
                               error="handoff dispatch failed")
                continue
            nbytes = int(pk.nbytes) + int(pv.nbytes)
            self.metrics.handoffs_total.inc()
            self.metrics.handoff_pages_total.inc(len(pages))
            self.metrics.handoff_bytes_total.inc(nbytes)
            self.metrics.handoff_ms.observe(
                (time.monotonic() - t0) * 1e3)

    def _update_kv_gauge(self) -> None:
        with self._lock:
            live_slots = self._live_count()
        self.metrics.kv_slots_live.set(live_slots)
        alloc = self.engine.allocator
        self.metrics.kv_pages_live.set(alloc.used_pages)
        self.metrics.kv_pages_free.set(alloc.free_pages)

    def _die(self, error: BaseException) -> None:
        with self._lock:
            self.dead = True
            orphans = [sl.stream for sl in self._slots
                       if sl is not None]
            orphans += list(self._waiting)
            self._waiting.clear()
            self._slots = [None] * self.engine.slots
            self._free = deque(range(self.engine.slots))
            self.rmetrics.ejections.inc()
            self._wake.notify_all()
        if self.on_death is not None:
            self.on_death(self.replica, orphans, error)
        else:
            for s in orphans:
                if s._finish(error):
                    record_hop(self.tracer, s.rid, "failed",
                               error=type(error).__name__)

    # ------------------------------------------------------------ surface
    def warmup(self) -> None:
        self.engine.warmup_decode()
        self.engine.warmup_handoff()

    def snapshot(self) -> Dict:
        return {
            "pool": "prefill",
            "decode": self.metrics.snapshot(),
            "replica": self.rmetrics.snapshot(),
            "kv": self.engine.kv_snapshot(),
            "engine": self.engine.metrics.snapshot(),
        }


class DecodeRouter:
    """N decode engines behind one door: least-loaded stream placement,
    and on a replica death the orphan streams RE-PREFILL on survivors
    from ``prompt + emitted`` — greedy decode is deterministic, so the
    continuation yields exactly the tokens the dead replica would have
    produced (the ``--decode`` bench gates no-duplicate/no-loss through a
    mid-storm kill).  Deliberately lean next to :class:`ReplicaRouter`:
    decode streams are long-lived and slot-bound, so health is the
    worker's own liveness (an engine failure IS the worker dying), not a
    heartbeat sidecar."""

    def __init__(self, engines: Sequence[DecodeEngine], *,
                 max_waiting: int = 256,
                 default_max_new: Optional[int] = None,
                 drafters: Optional[Sequence[DecodeEngine]] = None,
                 draft_k: int = 4):
        assert engines
        self.tracer = engines[0].tracer
        drafters = list(drafters or [])
        self.batchers = [
            DecodeBatcher(e, max_waiting=max_waiting,
                          default_max_new=default_max_new, replica=i,
                          on_death=self._on_death,
                          drafter=(drafters[i] if i < len(drafters)
                                   else None),
                          draft_k=draft_k)
            for i, e in enumerate(engines)]

    def start(self) -> "DecodeRouter":
        for b in self.batchers:
            b.start()
        return self

    def warmup(self) -> None:
        for b in self.batchers:
            b.warmup()

    def wait_ready(self) -> bool:
        return any(not b.dead for b in self.batchers)

    def stop(self, drain: bool = True) -> None:
        for b in self.batchers:
            b.stop(drain=drain)

    def engine(self, i: int = 0) -> DecodeEngine:
        return self.batchers[i].engine

    def alive(self) -> List[DecodeBatcher]:
        return [b for b in self.batchers
                if not b.dead and b._worker is not None]

    def submit_ids(self, ids: Sequence[int],
                   max_new_tokens: Optional[int] = None,
                   deadline_ms: Optional[float] = None) -> DecodeStream:
        alive = self.alive()
        if not alive:
            raise RuntimeError("no live decode replica")
        target = min(alive, key=lambda b: b.load)
        return target.submit_ids(ids, max_new_tokens=max_new_tokens,
                                 deadline_ms=deadline_ms)

    def kill(self, replica: int,
             error: Optional[BaseException] = None) -> None:
        self.batchers[replica].kill(error)

    def kill_drafter(self, replica: int,
                     error: Optional[BaseException] = None) -> None:
        """Chaos hook: kill replica's DRAFTER only — the pair must
        degrade to primary-only decode, not stall."""
        self.batchers[replica].kill_drafter(error)

    # ------------------------------------------------- controller surface
    def knob_values(self) -> Dict:
        """The tuning surface the :class:`ServeController` senses (its
        ``router.knob_values()`` quack): ``draft_k`` is the one decode
        knob so far — present only when some pair actually speculates,
        so the controller's speculation law stays dormant on plain
        pools."""
        ks = [b.draft_k for b in self.batchers if b.drafter is not None]
        return {"draft_k": int(ks[0])} if ks else {}

    def apply_knob(self, knob: str, value) -> None:
        """Controller actuation door (``ServeController._actuate`` ->
        ``_apply``): fan the knob to every speculating pair."""
        if knob != "draft_k":
            raise ValueError(f"unknown decode knob {knob!r}")
        for b in self.batchers:
            if b.drafter is not None or b.draft_k != int(value):
                b.set_draft_k(int(value))

    def health_summary(self) -> Dict:
        """Compact ``/healthz`` block (exporter ``health_sources``):
        liveness + the speculation story at a glance."""
        spec = [b for b in self.batchers
                if b.drafter is not None or b._spec_rounds]
        drafted = sum(b._spec_drafted for b in spec)
        accepted = sum(b._spec_accepted for b in spec)
        return {
            "alive": len(self.alive()),
            "replicas": len(self.batchers),
            "speculating": sum(1 for b in self.batchers
                               if b.drafter is not None),
            "draft_k": self.knob_values().get("draft_k", 0),
            "accept_rate": (accepted / float(drafted) if drafted
                            else 0.0),
            "drafter_deaths": sum(
                int(b.metrics.drafter_deaths_total.value)
                for b in self.batchers),
        }

    def _on_death(self, replica: int, orphans: List[DecodeStream],
                  error: BaseException) -> None:
        alive = self.alive()
        for stream in orphans:
            homed = False
            for target in sorted(alive, key=lambda b: b.load):
                # hop BEFORE the adopt: once adopted, the target's worker
                # may prefill (even complete) the stream immediately, and
                # a requeue hop landing after the terminal would fail
                # chain validation.  If the target died in the window the
                # hop names a replica that never took the stream — rare,
                # benign (non-terminal), and the next attempt records its
                # own hop; the requeued_out counter stays truthful by
                # incrementing only on a successful re-home.
                record_hop(self.tracer, stream.rid, "requeue",
                           from_replica=replica,
                           to_replica=target.replica, streamed=True,
                           tokens_emitted=len(stream.emitted))
                if target._adopt(stream):
                    self.batchers[replica].rmetrics.requeued_out.inc()
                    homed = True
                    break
            if not homed:
                if stream._finish(error):
                    record_hop(self.tracer, stream.rid, "failed",
                               error=type(error).__name__)

    def snapshot(self) -> Dict:
        return {
            "replicas": {str(b.replica): b.snapshot()
                         for b in self.batchers},
            "alive": len(self.alive()),
        }

    def control_snapshot(self) -> Dict:
        """Fleet-level paging view (the ops door next to
        :meth:`snapshot`'s per-replica firehose): page occupancy, free
        depth, COW/eviction counts and the prefix index's hit accounting,
        aggregated across replicas — every numeric leaf flattens into a
        Prometheus gauge via ``obs.prom.prometheus_lines``."""
        reps: Dict[str, Dict] = {}
        agg = {"pages_total": 0, "pages_live": 0, "free_depth": 0,
               "cow_copies": 0, "evictions": 0, "alloc_failures": 0,
               "hits_full": 0, "hits_partial": 0, "misses": 0,
               "index_entries": 0}
        spec_agg = {"enabled": 0, "draft_tokens": 0,
                    "accepted_tokens": 0, "rounds": 0,
                    "drafter_deaths": 0}
        spec_models: Dict[str, Dict] = {}
        for b in self.batchers:
            kv = b.engine.kv_snapshot()
            rep: Dict = {"alive": int(not b.dead), "load": b.load,
                         "peak_live_streams": b._peak_live,
                         "layout": kv.get("layout", "slots")}
            if b.drafter is not None or b._spec_rounds:
                sp = b.spec_snapshot()
                rep["speculation"] = sp
                spec_agg["enabled"] += sp["enabled"]
                spec_agg["draft_tokens"] += sp["draft_tokens"]
                spec_agg["accepted_tokens"] += sp["accepted_tokens"]
                spec_agg["rounds"] += sp["rounds"]
                for m, leaf in (sp.get("by_model") or {}).items():
                    dst = spec_models.setdefault(m, {})
                    for lk, lv in leaf.items():
                        if isinstance(lv, (int, float)) \
                                and not isinstance(lv, bool):
                            dst[lk] = dst.get(lk, 0) + lv
            spec_agg["drafter_deaths"] += int(
                b.metrics.drafter_deaths_total.value)
            pages = kv.get("pages")
            prefix = kv.get("prefix")
            if pages:
                rep["pages"] = pages
                agg["pages_total"] += pages["total_pages"]
                agg["pages_live"] += pages["pages_live"]
                agg["free_depth"] += pages["free_depth"]
                agg["cow_copies"] += pages["cow_copies"]
                agg["evictions"] += pages["evictions"]
                agg["alloc_failures"] += pages["alloc_failures"]
            if prefix:
                rep["prefix"] = prefix
                agg["hits_full"] += prefix["hits_full"]
                agg["hits_partial"] += prefix["hits_partial"]
                agg["misses"] += prefix["misses"]
                agg["index_entries"] += prefix["entries"]
            reps[str(b.replica)] = rep
        looked = agg["hits_full"] + agg["hits_partial"] + agg["misses"]
        agg["prefix_hit_rate"] = (
            (agg["hits_full"] + agg["hits_partial"]) / looked
            if looked else 0.0)
        agg["page_occupancy"] = (agg["pages_live"] / agg["pages_total"]
                                 if agg["pages_total"] else 0.0)
        spec_agg["accept_rate"] = (
            spec_agg["accepted_tokens"] / float(spec_agg["draft_tokens"])
            if spec_agg["draft_tokens"] else 0.0)
        if spec_models:
            spec_agg["by_model"] = spec_models
        return {"alive": len(self.alive()), "pages": agg,
                "knobs": self.knob_values(),
                "speculation": spec_agg,
                "replicas": reps}


class DisaggDecodeRouter:
    """Disaggregated prefill/decode engine pools behind one front door
    (ROADMAP item 4: DistServe OSDI'24 / Splitwise ISCA'24).

    All engines are PAGED and share one geometry; each is wrapped in a
    role unit — :class:`PrefillWorker` or :class:`DecodeBatcher` — with
    the engine index as its replica id.  Submissions land least-loaded
    on the prefill pool; a finished prefill hands its pages off
    least-loaded onto the decode pool.  ``transport="local"`` seats the
    exported payload in-process; ``"socket"`` pushes every payload
    through :mod:`pdnlp_tpu.serve.handoff`'s length-prefixed loopback
    framing (one :class:`HandoffServer` per decode unit, one connected
    :class:`HandoffChannel` per target) — the process-split rehearsal.

    The pool split is LIVE: :meth:`set_prefill_share` (the controller's
    ``prefill_share`` knob) retires units on the shrinking side,
    rebuilds them in the other role, and re-homes their streams through
    the front door (re-prefill; greedy decode is deterministic, so the
    continuation is bitwise unchanged).  Engines keep their jit caches
    across re-roles and :meth:`warmup` pre-traces EVERY program on
    EVERY engine, so neither a re-role nor a handoff ever compiles
    post-warmup — the bench's zero-retrace gate covers both pools."""

    def __init__(self, engines: Sequence[DecodeEngine], *,
                 prefill_engines: int = 1, max_waiting: int = 256,
                 default_max_new: Optional[int] = None,
                 transport: str = "local"):
        if len(engines) < 2:
            raise ValueError(
                "disaggregated serving needs >= 2 engines (at least "
                "one per role); use DecodeRouter for a single engine")
        for e in engines:
            if not e.paged:
                raise ValueError(
                    "disaggregated serving needs PAGED engines "
                    "(--kv_layout paged): the handoff moves page "
                    "custody between allocators")
        if transport not in ("local", "socket"):
            raise ValueError(f"unknown handoff transport {transport!r}")
        self.engines = list(engines)
        self.transport = transport
        self.tracer = engines[0].tracer
        self.max_waiting = int(max_waiting)
        self.default_max_new = default_max_new
        self._lock = threading.Lock()
        self._started = False
        n = len(self.engines)
        k = max(1, min(n - 1, int(prefill_engines)))
        self._servers: Dict[int, HandoffServer] = {}
        self._channels: Dict[int, HandoffChannel] = {}
        #: rid -> DecodeStream for payloads currently on the wire
        #: (socket transport; the frame carries metadata, the live
        #: stream object is joined back by rid on receive)
        self._inflight: Dict[str, DecodeStream] = {}
        self._units: List[object] = [
            self._build_unit(i, "prefill" if i < k else "decode")
            for i in range(n)]

    # ------------------------------------------------------ unit plumbing
    def _build_unit(self, i: int, role: str):
        """One engine, one role: wrap engine ``i`` as a PrefillWorker or
        DecodeBatcher (socket mode also gives each decode unit its
        receive server + the router's send channel to it)."""
        e = self.engines[i]
        e.span_attrs["pool"] = role  # re-assign: roles flip on re-split
        if role == "prefill":
            return PrefillWorker(
                e, dispatch=self._dispatch, max_waiting=self.max_waiting,
                default_max_new=self.default_max_new, replica=i,
                on_death=self._on_death)
        unit = DecodeBatcher(
            e, max_waiting=self.max_waiting,
            default_max_new=self.default_max_new, replica=i,
            on_death=self._on_death)
        if self.transport == "socket":
            srv = HandoffServer(self._make_receiver(i)).start()
            with self._lock:
                self._servers[i] = srv
                self._channels[i] = HandoffChannel(srv.address)
        return unit

    def _teardown_transport(self, i: int) -> None:
        with self._lock:
            ch = self._channels.pop(i, None)
            srv = self._servers.pop(i, None)
        if ch is not None:
            ch.close()
        if srv is not None:
            srv.stop()

    def _make_receiver(self, i: int) -> Callable:
        """Socket mode: decode unit ``i``'s frame callback.  The wire
        payload carries the stream METADATA; the live DecodeStream
        object (the caller's handle) is joined back by rid from the
        sender's in-flight table.  A raise here is the NACK the sender's
        custody logic keys on."""
        def on_payload(meta: Dict, k: np.ndarray, v: np.ndarray) -> None:
            with self._lock:
                stream = self._inflight.pop(meta["rid"], None)
            if stream is None:
                raise HandoffError(
                    f"no in-flight stream for rid {meta['rid']!r}")
            unit = self._units[i]
            if not isinstance(unit, DecodeBatcher) \
                    or not unit.accept_handoff(
                        stream, meta["pos"], meta["next_token"], k, v):
                raise HandoffError(
                    f"decode unit {i} refused the handoff")
        return on_payload

    def _prefill_units(self) -> List["PrefillWorker"]:
        with self._lock:
            return [u for u in self._units
                    if isinstance(u, PrefillWorker) and not u.dead
                    and u._worker is not None]

    def _decode_units(self) -> List[DecodeBatcher]:
        with self._lock:
            return [u for u in self._units
                    if isinstance(u, DecodeBatcher) and not u.dead
                    and u._worker is not None]

    # ----------------------------------------------------------- dispatch
    def _dispatch(self, stream: DecodeStream, meta: Dict,
                  payload_k, payload_v) -> Optional[tuple]:
        """PrefillWorker callback: place one exported payload on the
        least-loaded live decode unit; returns ``(to_replica,
        transport)`` or ``None`` when no decode unit took it.  The
        ``handoff`` hop is recorded per attempt BEFORE the seat (the
        requeue-hop ordering precedent: once seated, the decode worker
        may finish the stream immediately, and a handoff hop landing
        after the terminal would fail chain validation)."""
        from_replica = stream.replica
        nbytes = int(payload_k.nbytes) + int(payload_v.nbytes)
        for target in sorted(self._decode_units(), key=lambda b: b.load):
            record_hop(self.tracer, stream.rid, "handoff",
                       from_replica=from_replica,
                       to_replica=target.replica,
                       pages=meta["n_pages"], bytes=nbytes,
                       transport=self.transport)
            if self.transport == "local":
                if target.accept_handoff(stream, meta["pos"],
                                         meta["next_token"],
                                         payload_k, payload_v):
                    return (target.replica, "local")
                continue
            with self._lock:
                ch = self._channels.get(target.replica)
                self._inflight[stream.rid] = stream
            if ch is None:
                with self._lock:
                    self._inflight.pop(stream.rid, None)
                continue
            try:
                ch.send(meta, payload_k, payload_v)
                return (target.replica, "socket")
            except HandoffError:
                with self._lock:
                    self._inflight.pop(stream.rid, None)
                continue
        return None

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "DisaggDecodeRouter":
        with self._lock:
            self._started = True
            units = list(self._units)
        for u in units:
            u.start()
        return self

    def warmup(self) -> None:
        """Pre-trace EVERY program on EVERY engine — prefill buckets,
        chunk, decode, COW, export AND import — so a handoff or a pool
        re-split never compiles (both roles run from warm caches)."""
        for e in self.engines:
            e.warmup_decode()
            e.warmup_handoff()

    def wait_ready(self) -> bool:
        return bool(self._prefill_units()) and bool(self._decode_units())

    def stop(self, drain: bool = True) -> None:
        with self._lock:
            units = list(self._units)
        # prefill first: its drain flushes queued streams THROUGH the
        # handoff, decode's drain then finishes them
        for u in units:
            if isinstance(u, PrefillWorker):
                u.stop(drain=drain)
        for u in units:
            if isinstance(u, DecodeBatcher):
                u.stop(drain=drain)
        with self._lock:
            channels = list(self._channels.values())
            servers = list(self._servers.values())
            self._channels.clear()
            self._servers.clear()
        for ch in channels:
            ch.close()
        for srv in servers:
            srv.stop()

    def engine(self, i: int = 0) -> DecodeEngine:
        return self.engines[i]

    def alive(self) -> List[object]:
        with self._lock:
            return [u for u in self._units
                    if not u.dead and u._worker is not None]

    def kill(self, replica: int,
             error: Optional[BaseException] = None) -> None:
        self._units[replica].kill(error)

    # -------------------------------------------------------- front door
    def submit_ids(self, ids: Sequence[int],
                   max_new_tokens: Optional[int] = None,
                   deadline_ms: Optional[float] = None) -> DecodeStream:
        workers = self._prefill_units()
        if not workers:
            raise RuntimeError("no live prefill replica")
        target = min(workers, key=lambda w: w.load)
        return target.submit_ids(ids, max_new_tokens=max_new_tokens,
                                 deadline_ms=deadline_ms)

    def _reintake(self, streams: List[DecodeStream], from_replica: int,
                  error: Optional[BaseException] = None) -> None:
        """Re-home orphans (replica death, pool re-split) through the
        prefill pool: ``prompt + emitted`` re-prefills and hands off
        again — the transfer ledger's recovery story.  The requeue hop
        lands BEFORE the adopt (ordering precedent, see
        :meth:`DecodeRouter._on_death`)."""
        err = error or RuntimeError("no live prefill replica")
        for stream in streams:
            homed = False
            for target in sorted(self._prefill_units(),
                                 key=lambda w: w.load):
                record_hop(self.tracer, stream.rid, "requeue",
                           from_replica=from_replica,
                           to_replica=target.replica, streamed=True,
                           tokens_emitted=len(stream.emitted))
                if target._adopt(stream):
                    unit = self._units[from_replica]
                    if unit is not None:
                        unit.rmetrics.requeued_out.inc()
                    homed = True
                    break
            if not homed:
                if stream._finish(err):
                    record_hop(self.tracer, stream.rid, "failed",
                               error=type(err).__name__)

    def _on_death(self, replica: int, orphans: List[DecodeStream],
                  error: BaseException) -> None:
        self._reintake(orphans, replica, error)

    # ------------------------------------------------- controller surface
    def set_prefill_share(self, value: float) -> float:
        """Actuate the pool split: ``value`` is the FRACTION of engines
        in the prefill role, quantized to whole engines with a floor of
        one per role.  Units on the shrinking side retire (streams
        re-enter the front door), rebuild in the other role, and restart
        from the engine's warm jit caches.  Returns the applied
        (quantized) share — the exact value :meth:`knob_values` will
        report, so the controller's eval-window staleness check holds."""
        n = len(self.engines)
        step = round(1.0 / n, 6)
        k_new = max(1, min(n - 1, int(round(float(value) * n))))
        with self._lock:
            pre_idx = [i for i, u in enumerate(self._units)
                       if isinstance(u, PrefillWorker)]
            dec_idx = [i for i, u in enumerate(self._units)
                       if isinstance(u, DecodeBatcher)]
            started = self._started
        k_old = len(pre_idx)
        if k_new == k_old:
            return round(k_new * step, 6)
        if k_new > k_old:
            flip = sorted(dec_idx,
                          key=lambda i: self._units[i].load)[:k_new - k_old]
            role = "prefill"
        else:
            flip = sorted(pre_idx,
                          key=lambda i: self._units[i].load)[:k_old - k_new]
            role = "decode"
        leftovers: List[DecodeStream] = []
        for i in flip:
            old = self._units[i]
            leftovers += old.retire()
            if isinstance(old, DecodeBatcher):
                self._teardown_transport(i)
            new = self._build_unit(i, role)
            with self._lock:
                self._units[i] = new
            if started:
                new.start()
        for stream in leftovers:
            self._reintake([stream], stream.replica
                           if stream.replica is not None else flip[0])
        return round(k_new * step, 6)

    def knob_values(self) -> Dict:
        """Controller sense surface: the live split plus its quantum.
        The share is reported as ``k * step`` (both rounded the same
        way the split law composes them), so an actuated target and the
        re-sensed value compare EQUAL — the eval window's staleness
        check must not see ghosts."""
        n = len(self.engines)
        step = round(1.0 / n, 6)
        with self._lock:
            k = sum(1 for u in self._units
                    if isinstance(u, PrefillWorker))
        return {"prefill_share": round(k * step, 6),
                "prefill_share_step": step}

    def apply_knob(self, knob: str, value) -> None:
        if knob != "prefill_share":
            raise ValueError(f"unknown disagg knob {knob!r}")
        self.set_prefill_share(float(value))

    def health_summary(self) -> Dict:
        """Compact ``/healthz`` block: liveness + the split + per-pool
        pressure at a glance (``by_pool`` flattens with a ``pool``
        label on ``/metrics``)."""
        with self._lock:
            units = list(self._units)
        pre = [u for u in units if isinstance(u, PrefillWorker)]
        dec = [u for u in units if isinstance(u, DecodeBatcher)]
        return {
            "alive": len(self.alive()),
            "replicas": len(units),
            "transport": self.transport,
            "prefill_share": self.knob_values()["prefill_share"],
            "handoffs": sum(int(u.metrics.handoffs_total.value)
                            for u in pre),
            "handoff_failures": sum(
                int(u.metrics.handoff_failures_total.value)
                for u in pre),
            "by_pool": {
                "prefill": {
                    "engines": len(pre),
                    "alive": sum(1 for u in pre if not u.dead
                                 and u._worker is not None),
                    "backlog": sum(len(u._waiting) for u in pre),
                },
                "decode": {
                    "engines": len(dec),
                    "alive": sum(1 for u in dec if not u.dead
                                 and u._worker is not None),
                    "backlog": sum(len(u._handoffs) for u in dec),
                },
            },
        }

    def snapshot(self) -> Dict:
        with self._lock:
            units = list(self._units)
        return {
            "replicas": {str(u.replica): u.snapshot() for u in units},
            "alive": len(self.alive()),
            "transport": self.transport,
        }

    def control_snapshot(self) -> Dict:
        """The controller's sense surface: fleet paging view (same
        ``pages`` aggregate as :meth:`DecodeRouter.control_snapshot`)
        PLUS the two latency signals the pool-split law trades off —
        ``ttft_p99_ms`` vs ``inter_token_p99_ms``, pooled across every
        unit's own histogram windows (``merged_percentiles``: one
        fleet-level p99, not an average of per-unit p99s) — and a
        ``by_pool`` pressure block."""
        with self._lock:
            units = list(self._units)
        pre = [u for u in units if isinstance(u, PrefillWorker)]
        dec = [u for u in units if isinstance(u, DecodeBatcher)]
        ttft = merged_percentiles(
            [u.metrics.ttft_ms for u in units], (50, 99))
        itok = merged_percentiles(
            [u.metrics.intertoken_ms for u in units], (50, 99))
        agg = {"pages_total": 0, "pages_live": 0, "free_depth": 0,
               "cow_copies": 0, "evictions": 0, "alloc_failures": 0,
               "hits_full": 0, "hits_partial": 0, "misses": 0,
               "index_entries": 0}
        reps: Dict[str, Dict] = {}
        for u in units:
            kv = u.engine.kv_snapshot()
            rep: Dict = {"alive": int(not u.dead), "load": u.load,
                         "pool": ("prefill"
                                  if isinstance(u, PrefillWorker)
                                  else "decode"),
                         "peak_live_streams": u._peak_live}
            pages = kv.get("pages")
            prefix = kv.get("prefix")
            if pages:
                rep["pages"] = pages
                agg["pages_total"] += pages["total_pages"]
                agg["pages_live"] += pages["pages_live"]
                agg["free_depth"] += pages["free_depth"]
                agg["cow_copies"] += pages["cow_copies"]
                agg["evictions"] += pages["evictions"]
                agg["alloc_failures"] += pages["alloc_failures"]
            if prefix:
                rep["prefix"] = prefix
                agg["hits_full"] += prefix["hits_full"]
                agg["hits_partial"] += prefix["hits_partial"]
                agg["misses"] += prefix["misses"]
                agg["index_entries"] += prefix["entries"]
            reps[str(u.replica)] = rep
        looked = agg["hits_full"] + agg["hits_partial"] + agg["misses"]
        agg["prefix_hit_rate"] = (
            (agg["hits_full"] + agg["hits_partial"]) / looked
            if looked else 0.0)
        agg["page_occupancy"] = (agg["pages_live"] / agg["pages_total"]
                                 if agg["pages_total"] else 0.0)
        return {
            "alive": len(self.alive()),
            "pages": agg,
            "knobs": self.knob_values(),
            "latency": {
                "ttft_p50_ms": ttft[0], "ttft_p99_ms": ttft[1],
                "inter_token_p50_ms": itok[0],
                "inter_token_p99_ms": itok[1],
            },
            "by_pool": {
                "prefill": {
                    "engines": len(pre),
                    "alive": sum(1 for u in pre if not u.dead
                                 and u._worker is not None),
                    "backlog": sum(len(u._waiting) for u in pre),
                    "handoffs": sum(
                        int(u.metrics.handoffs_total.value)
                        for u in pre),
                    "handoff_failures": sum(
                        int(u.metrics.handoff_failures_total.value)
                        for u in pre),
                },
                "decode": {
                    "engines": len(dec),
                    "alive": sum(1 for u in dec if not u.dead
                                 and u._worker is not None),
                    "backlog": sum(len(u._handoffs) for u in dec),
                    "live": sum(
                        int(u.metrics.kv_slots_live.value)
                        for u in dec),
                },
            },
            "replicas": reps,
        }
