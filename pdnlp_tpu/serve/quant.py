"""Per-channel symmetric int8 weight quantization for the serve forward.

Serving BERT-base at small batch is weight-bound: every forward streams
~220 MB of bf16 matmul kernels out of HBM while the MXU sits mostly idle.
Storing those kernels as int8 (+ one fp32 scale per output channel) halves
the weight traffic — the throughput lever ``--serve_dtype int8`` pulls —
while activations stay bf16 and the scale multiply folds onto the matmul
OUTPUT (per-column scales commute through the contraction:
``x @ (q * s) == (x @ q) * s``), so no dequantized weight copy ever
materializes.

Scope (the exact ``train.steps.cast_kernels`` rule, restricted to dense
blocks): every ``{"kernel", "bias"}`` dict whose kernel has >= 2 dims —
q/k/v/o, the MLP up/down (incl. the stacked ``[L, ...]`` and MoE
``[L, E, ...]`` layouts), pooler, classifier.  Embeddings (gathers, not
matmuls), LayerNorms, biases, and the bias-less MoE gate (a [H, E] sliver
whose routing is precision-sensitive) stay fp32.

Calibration is weight-only (symmetric max per output channel) — no
activation statistics needed, so ``scripts/quantize_ckpt.py`` can produce
the artifact offline from any committed checkpoint.  Accuracy parity is
gated in ``bench.py --kernels`` and pinned in ``tests/test_kernels.py``.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

#: marker key: a dense dict carrying one is quantized ({kernel: int8,
#: qscale: fp32 per-output-channel, bias: fp32})
QSCALE = "qscale"


def _is_dense(node: Any) -> bool:
    return (isinstance(node, dict) and "kernel" in node and "bias" in node
            and getattr(node["kernel"], "ndim", 0) >= 2)


def quantize_dense(kernel, bias) -> Dict[str, Any]:
    """One dense block -> {kernel int8, qscale fp32, bias} (host numpy).

    Per-OUTPUT-channel symmetric scales: amax over the contraction (input)
    dim, ``axis=-2`` — stacked layouts ([L, in, out], [L, E, in, out]) get
    one scale per (stack..., out) automatically."""
    w = np.asarray(kernel, np.float32)
    amax = np.abs(w).max(axis=-2)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale[..., None, :]), -127, 127).astype(np.int8)
    return {"kernel": q, QSCALE: scale,
            "bias": np.asarray(bias, np.float32)}


def quantize_params(params) -> Dict[str, Any]:
    """Quantize every eligible dense block of a (host or device) param
    tree; everything else passes through as host numpy."""

    def walk(node):
        if _is_dense(node) and QSCALE not in node:
            return quantize_dense(node["kernel"], node["bias"])
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return np.asarray(node)

    return walk(params)


def dequantize_dense(node: Dict[str, Any]) -> np.ndarray:
    """int8 kernel -> fp32 approximation (error reporting / tests)."""
    return (np.asarray(node["kernel"], np.float32)
            * np.asarray(node[QSCALE], np.float32)[..., None, :])


def is_quantized(tree: Any) -> bool:
    """True when any dense block in the tree carries a ``qscale`` — how the
    engine recognizes an offline ``quantize_ckpt.py`` artifact."""
    if isinstance(tree, dict):
        return QSCALE in tree or any(is_quantized(v) for v in tree.values())
    return False


def quant_error_report(params, qparams) -> Dict[str, Tuple[float, float]]:
    """{path: (max_abs_err, rel_err)} per quantized block — the
    ``quantize_ckpt.py`` summary."""
    out: Dict[str, Tuple[float, float]] = {}

    def walk(node, qnode, path):
        if _is_dense(node) and isinstance(qnode, dict) and QSCALE in qnode:
            w = np.asarray(node["kernel"], np.float32)
            dq = dequantize_dense(qnode)
            err = float(np.abs(w - dq).max())
            denom = float(np.abs(w).max()) or 1.0
            out[path or "<root>"] = (err, err / denom)
        elif isinstance(node, dict):
            for k in node:
                walk(node[k], qnode.get(k) if isinstance(qnode, dict) else None,
                     f"{path}/{k}" if path else k)

    walk(params, qparams, "")
    return out
