"""High-throughput offline scoring — the whole-file batch path.

The online batcher optimizes tail latency; this path optimizes throughput
over a corpus that is fully known up front.  Same bucketing, no queueing:
texts are encoded ragged, grouped by covering bucket, chunked into
fixed-shape batches, and results are re-assembled in input order — so it is
deterministic, which makes it the parity surface ``tests/test_serve.py`` and
``bench.py --serve`` drive (and a useful tool in its own right:
``serve_tpu.py --input file.txt``).
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from pdnlp_tpu.serve.batcher import DEFAULT_BUCKETS, pick_bucket, usable_buckets
from pdnlp_tpu.serve.engine import InferenceEngine


def score_texts(
    engine: InferenceEngine,
    texts: Sequence[str],
    *,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    batch_size: int = 8,
) -> Tuple[np.ndarray, np.ndarray]:
    """(preds ``[N]``, logits ``[N, num_labels]``) in input order.

    Bucket-grouping maximizes compile-cache hits exactly like the online
    path: every batch is ``(bucket, padded_rows)``-shaped, so after one
    batch per bucket the engine never retraces.  Batch occupancy lands in
    the shared metrics (a mostly-short-text corpus in big buckets shows up
    as low occupancy, the signal to re-tune the bucket list).
    """
    usable = usable_buckets(buckets, engine.args.max_seq_len)
    # encode truncates to the LARGEST bucket (batcher.submit semantics):
    # every row is guaranteed to fit the bucket pick_bucket assigns it
    ids = engine.tokenizer.encode_ragged(texts, usable[-1])
    by_bucket: dict = {}
    for i, row in enumerate(ids):
        by_bucket.setdefault(pick_bucket(len(row), usable), []).append(i)

    num_labels = engine.cfg.num_labels
    logits = np.zeros((len(texts), num_labels), np.float32)
    rows = engine.pad_rows(batch_size)
    for bucket in sorted(by_bucket):
        order = by_bucket[bucket]
        for start in range(0, len(order), rows):
            chunk = order[start : start + rows]
            engine.metrics.requests_total.inc(len(chunk))
            t0 = time.monotonic()
            out = engine.infer_ids([ids[i] for i in chunk], bucket, rows=rows)
            batch_ms = (time.monotonic() - t0) * 1e3
            engine.metrics.batches_total.inc()
            engine.metrics.batch_occupancy.observe(len(chunk) / rows)
            for j, i in enumerate(chunk):
                # offline "latency" is the batch's execution time: no queue
                # wait exists here, and per-row attribution of a fused
                # dispatch is not meaningful
                engine.metrics.request_latency_ms.observe(batch_ms)
                logits[i] = out[j]
    return np.argmax(logits, axis=-1), logits


def score_file(
    engine: InferenceEngine,
    path: str,
    *,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    batch_size: int = 8,
    limit: Optional[int] = None,
) -> Tuple[List[str], np.ndarray, np.ndarray]:
    """Classify a text file (one UTF-8 text per line, blanks skipped):
    returns (texts, preds, logits)."""
    with open(path, encoding="utf-8") as f:
        texts = [line.strip() for line in f if line.strip()]
    if limit is not None:
        texts = texts[:limit]
    preds, logits = score_texts(engine, texts, buckets=buckets,
                                batch_size=batch_size)
    return texts, preds, logits
