"""Dynamic micro-batching over a bounded queue — the Orca/vLLM idea in its
fixed-shape classifier form.

Requests arrive one at a time; the accelerator wants full fixed-shape
batches.  The batcher bridges the two:

- **bucketing**: each request's true token length picks the smallest
  covering bucket (default 32/64/128/...); per-bucket queues keep batches
  shape-homogeneous so the engine's compile cache stays tiny and hot;
- **flush policy**: a bucket flushes when it holds ``max_batch_size``
  requests (throughput bound) or when its oldest request has waited
  ``max_wait_ms`` (latency bound) — the classic size-or-timeout trigger;
- **backpressure**: ``submit`` raises :class:`QueueFullError` once
  ``max_queue`` requests are pending — reject-with-error beats unbounded
  memory growth and tells the caller to shed load.  The multi-replica
  router replaces this single cliff with the tiered
  :class:`AdmissionControl` ladder defined here (healthy -> bounded-wait
  backpressure -> shed-lowest-deadline-slack -> hard reject);
- **deadlines**: a request whose deadline passes while still queued is
  completed with :class:`DeadlineExceeded` and dropped from its batch, so
  one stuck client degrades gracefully instead of stalling the queue;
  expiry is checked when the flush timer is computed AND again at dequeue
  (a batch formed while the worker was busy must not carry corpses), and
  ``result()`` without an explicit timeout bounds its wait by the
  request's own remaining deadline budget;
- **packing** (``--serve_pack``): instead of padding each request to its
  bucket width, admitted requests bin-pack many-per-row into ONE fixed
  ``[rows, pack_width]`` packed batch (``data.packing.pack_id_lists`` —
  the training packer's segment channels, served online), so throughput
  scales with TOKENS, not requests.  The flush trigger becomes a token
  budget (``rows x width`` real tokens queued, or the age bound), the
  queue bound becomes a token bound, and batch formation is deadline-
  aware: requests pack in lowest-remaining-slack order, so the most
  urgent close the earliest rows and anything that does not fit waits.
  ``auto`` (default) packs only where the segment-native pallas kernel
  routes; ``off`` keeps per-bucket padding (also the permanent path for
  the router's hedged duplicates);
- **chunked prefill** (``long_widths``, ``--serve_long_widths``): a
  request longer than the pack width routes to a per-width LONG packed
  queue and executes as ONE segment of a ``[flush_tokens/w, w]`` packed
  batch — exact whole-request scoring (positions restart per segment,
  attention masked to the request), sized so every long flush costs
  ~the same token budget as a short flush.  Long traffic is consumed in
  those chunks, interleaved BEHIND short flushes (shorts always go
  first; an overdue long — 2x the age bound — takes one chunk slot),
  so one long request never head-of-line-blocks the packed short-query
  traffic; admission is already token-unit, so long requests simply
  cost more of the shared pool.

One worker thread owns the engine (JAX dispatch is not thread-safe-by-
contract here, and a single dispatcher keeps the device busy without lock
churn); submitters block only on their own result.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from pdnlp_tpu.obs.request import exemplar_ids, mint_request_id, record_hop
from pdnlp_tpu.serve.engine import InferenceEngine
from pdnlp_tpu.serve.metrics import ServeMetrics

DEFAULT_BUCKETS = (32, 64, 128, 256, 512)


class QueueFullError(RuntimeError):
    """Raised by ``submit`` when the bounded queue is at capacity."""


class DeadlineExceeded(RuntimeError):
    """A request's deadline passed before its batch executed."""


class LoadShedError(RuntimeError):
    """A request was shed by tiered admission control (router overload tier:
    lowest deadline slack goes first) — the caller should back off; unlike
    :class:`QueueFullError` the queue is not hard-full, the request just
    could not have made its deadline."""


def usable_buckets(buckets: Sequence[int], max_seq_len: int) -> tuple:
    """The bucket list every serve path actually uses: capped at the
    model's padded length (encode truncates there, so a larger bucket could
    never fill) and never empty.  ONE definition — the batcher, the offline
    scorer and the CLI must clamp identically or a request could land in a
    bucket another path would reject."""
    usable = tuple(sorted(b for b in buckets if b <= max_seq_len))
    return usable or (int(max_seq_len),)


def pick_bucket(n_tokens: int, buckets: Sequence[int]) -> int:
    """Smallest bucket covering ``n_tokens`` (largest bucket if none does —
    entry paths truncate rows to the largest bucket, so topping out is the
    matching choice, not an error)."""
    for b in sorted(buckets):
        if n_tokens <= b:
            return b
    return max(buckets)


def resolve_serve_pack(mode: str, pack_width: int) -> bool:
    """ONE resolution of ``--serve_pack auto|on|off`` -> packed or padded,
    shared by the batcher, the router and the CLI/bench so a request can
    never be packed by one layer and padded by another.

    ``auto`` packs exactly where the segment-native pallas flash kernel
    routes for the pack width (TPU, 128-tiling widths): there the packed
    batch pays block-diagonal attention in-kernel and the win is pure.
    Elsewhere (CPU tests, non-tiling widths) the XLA fallback materializes
    the ``[B,1,S,S]`` segment bias per batch — packing still usually wins
    on padding waste (``on`` forces it; the bench gates it), but it is an
    opt-in, not a default."""
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"serve_pack must be 'auto', 'on' or 'off', "
                         f"got {mode!r}")
    if mode != "auto":
        return mode == "on"
    from pdnlp_tpu.ops.attention import routed_impl_cached

    return routed_impl_cached("auto", int(pack_width),
                              segmented=True) == "pallas"


#: grace added to a deadline-derived ``result()`` timeout: a request can be
#: mid-batch when its deadline passes, and the completion (or the expiry
#: error) needs the batch's execution time to arrive
RESULT_GRACE_SEC = 5.0

#: completion is first-wins (a hedged/requeued request may be completed from
#: two replicas; an ejected replica's hung worker may wake up later) — one
#: tiny shared lock beats a per-request lock for objects this small
_COMPLETE_LOCK = threading.Lock()


class _Request:
    __slots__ = ("ids", "bucket", "submitted", "born", "deadline",
                 "retries", "hedged", "shadow_of", "rid", "_event",
                 "_logits", "_error", "completed_at")

    def __init__(self, ids: List[int], bucket: int,
                 deadline: Optional[float]):
        self.ids = ids
        self.bucket = bucket
        self.submitted = time.monotonic()
        # `submitted` may be re-stamped into a router's INJECTABLE clock
        # domain; `born`/`completed_at` stay time.monotonic so latency
        # deltas computed from them (the fleet's ShadowReport) are always
        # same-domain
        self.born = self.submitted
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.retries = 0          # router: requeues after replica failure
        self.hedged = False       # router: a duplicate dispatch exists
        # fleet: the primary request this is a SHADOW duplicate of (its
        # rid) — a shadow's terminal hop is stamped shadow=True so the
        # chain contract can prove no caller ever saw a candidate answer
        self.shadow_of: Optional[str] = None
        self.completed_at: Optional[float] = None  # fleet: parity/latency
        # the distributed-tracing identity: minted at admission, carried
        # through every hop (queue, pack, dispatch, requeue, completion)
        # so ONE id reconstructs the request's whole life — trace_tpu.py
        # request <id> (pdnlp_tpu.obs.request)
        self.rid = mint_request_id()
        self._event = threading.Event()
        self._logits: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None

    # --- the caller-facing future half ---
    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block for the logits row; raises the request's error if it was
        rejected by deadline or failed in the engine.

        ``timeout=None`` on a request WITH a deadline derives the wait from
        the request's own remaining deadline budget (plus a grace window
        for an in-flight batch) instead of blocking forever — a worker that
        died mid-batch must surface as a bounded ``TimeoutError``, not a
        hung caller.  A deadline-free request keeps the wait-forever
        default."""
        if timeout is None and self.deadline is not None:
            timeout = max(0.0, self.deadline - time.monotonic()) \
                + RESULT_GRACE_SEC
        if not self._event.wait(timeout):
            raise TimeoutError("request still pending")
        if self._error is not None:
            raise self._error
        return self._logits

    def done(self) -> bool:
        return self._event.is_set()

    def slack(self, now: float) -> float:
        """Remaining deadline budget in seconds (+inf when deadline-free) —
        the shed tier's ordering key."""
        return float("inf") if self.deadline is None else self.deadline - now

    # --- the worker-facing completion half ---
    def _complete(self, logits: Optional[np.ndarray],
                  error: Optional[BaseException] = None) -> bool:
        """First completion wins; returns whether THIS call won (so metrics
        count each request exactly once across hedges/requeues)."""
        with _COMPLETE_LOCK:
            if self._event.is_set():
                return False
            self._logits = logits
            self._error = error
            self.completed_at = time.monotonic()
            self._event.set()
            return True


def pack_order(requests: Sequence["_Request"], now: float,
               age_floor_s: Optional[float] = None) -> List["_Request"]:
    """Deadline-aware packing priority: lowest remaining slack first
    (deadline-free requests last, FIFO among equals) — the most urgent
    requests close the earliest rows of the packed batch, and whatever
    does not fit is exactly the work that could best afford to wait.

    ``age_floor_s`` (the flush policy's ``max_wait_ms``) is the
    anti-starvation valve: a request whose queue wait has reached the
    floor outranks ALL slack ordering (FIFO among the aged), so
    deadline-free or far-deadline work cannot be displaced batch after
    batch by a sustained stream of urgent arrivals — and the aged-flush
    trigger (keyed on the oldest request) always serves the request that
    fired it instead of re-firing forever."""
    def key(r: "_Request"):
        if age_floor_s is not None and now - r.submitted >= age_floor_s:
            return (0, r.submitted, 0.0)
        return (1, r.slack(now), r.submitted)

    return sorted(requests, key=key)


class _PackedBatch:
    """One flushed packed batch: the fixed-shape channel arrays
    (``data.packing.pack_id_lists``) plus each riding request's
    ``(row, slot)`` placement — the scatter map that routes the
    ``[rows, M, C]`` packed logits back to their callers."""

    __slots__ = ("requests", "arrays", "placements", "tokens")

    def __init__(self, requests: List["_Request"], arrays: Dict,
                 placements: List, tokens: int):
        self.requests = requests
        self.arrays = arrays
        self.placements = placements
        self.tokens = int(tokens)      # real tokens riding the batch

    @property
    def slots(self) -> int:
        """Token slots the forward pays for (rows x width)."""
        return int(self.arrays["input_ids"].size)

    @property
    def width(self) -> int:
        """The batch's packed row width (the pack width for short flushes,
        a ``long_widths`` entry for chunked-prefill flushes)."""
        return int(self.arrays["input_ids"].shape[1])

    @property
    def fill(self) -> float:
        return self.tokens / float(self.slots or 1)


def form_packed_batch(requests: Sequence["_Request"], now: float,
                      width: int, rows: int, max_segments: int,
                      pad_id: int, age_floor_s: Optional[float]
                      ) -> tuple:
    """ONE copy of packed batch formation — ``pack_order`` priority ->
    ``pack_id_lists`` -> (batch, leftovers) — shared by
    :class:`DynamicBatcher` and the replica router so ordering, placement
    and leftover semantics can never drift between the two serve paths.
    Returns ``(packed_batch, leftover_requests)``; leftovers are the
    requests that did not fit and must stay queued for the next batch."""
    from pdnlp_tpu.data.packing import pack_id_lists

    ordered = pack_order(requests, now, age_floor_s=age_floor_s)
    arrays, placements = pack_id_lists(
        [r.ids for r in ordered], width, rows, max_segments, pad_id=pad_id)
    taken = [r for r, p in zip(ordered, placements) if p is not None]
    placed = [p for p in placements if p is not None]
    leftover = [r for r, p in zip(ordered, placements) if p is None]
    tokens = sum(len(r.ids) for r in taken)
    return _PackedBatch(taken, arrays, placed, tokens), leftover


class AdmissionControl:
    """Tiered overload policy — the one cliff (:class:`QueueFullError` at
    ``max_queue``) replaced with a ladder the router walks per submit:

    ====================  ==================================================
    tier (queue depth)    policy for the arriving request
    ====================  ==================================================
    healthy               ``< backpressure_at``: accept immediately
    backpressure          ``[backpressure_at, degrade_at)``: bounded wait
                          (at most ``backpressure_wait_ms``, never past the
                          request's own deadline slack) for depth to drop,
                          then accept — converts a burst into latency
                          instead of errors
    degrade               ``[degrade_at, shed_at)`` (only when
                          ``degrade_at`` is set — the multi-model fleet's
                          tier): the arrival should be RE-ROUTED to the
                          designated cheap model instead of queued here —
                          overload degrades answer QUALITY before it drops
                          requests.  The re-route itself lives in the
                          fleet front door (:class:`~pdnlp_tpu.serve.
                          fleet.FleetRouter`); a pool walking this ladder
                          with no cheap model behind it treats the band as
                          an early shed tier (the pre-fleet behavior,
                          reached ``shed_at - degrade_at`` requests sooner)
    shed                  ``[shed_at, max_queue)``: accept, but any request
                          (the arrival or a queued one — LOWEST deadline
                          slack first) whose remaining slack is under
                          ``shed_slack_ms`` is shed with
                          :class:`LoadShedError`: it could not have made
                          its deadline anyway, and dropping it early frees
                          capacity for requests that still can.  Deadline-
                          free requests are never shed
    reject                ``>= max_queue``: hard :class:`QueueFullError`
                          (the PR-1 behavior, now the LAST resort)
    ====================  ==================================================

    Pure policy (no locks, injectable clock) so tier transitions are
    unit-testable without threads; the queue mechanics stay in the caller.
    The single-replica :class:`DynamicBatcher` keeps its legacy
    reject-on-full contract (equivalent to ``backpressure_at = shed_at =
    max_queue``); the multi-replica router wires the full ladder.
    """

    def __init__(self, max_queue: int, *,
                 backpressure_at: Optional[int] = None,
                 shed_at: Optional[int] = None,
                 degrade_at: Optional[int] = None,
                 backpressure_wait_ms: float = 50.0,
                 shed_slack_ms: float = 0.0,
                 clock=time.monotonic):
        self.max_queue = int(max_queue)
        self.backpressure_at = int(backpressure_at if backpressure_at
                                   is not None else self.max_queue // 2)
        self.shed_at = int(shed_at if shed_at is not None
                           else (self.max_queue * 3) // 4)
        # the degrade band is OPT-IN (None = the pre-fleet 4-tier ladder):
        # only a fleet with a cheap model behind it should route this tier
        self.degrade_at = None if degrade_at is None else int(degrade_at)
        if not (self.backpressure_at <= self.shed_at <= self.max_queue):
            raise ValueError(
                f"tier thresholds must be ordered: backpressure_at "
                f"{self.backpressure_at} <= shed_at {self.shed_at} <= "
                f"max_queue {self.max_queue}")
        if self.degrade_at is not None and not (
                self.backpressure_at <= self.degrade_at <= self.shed_at):
            raise ValueError(
                f"degrade_at {self.degrade_at} must sit between "
                f"backpressure_at {self.backpressure_at} and shed_at "
                f"{self.shed_at}")
        self.backpressure_wait_ms = float(backpressure_wait_ms)
        self.shed_slack_ms = float(shed_slack_ms)
        self.clock = clock

    def tier(self, pending: int) -> str:
        """``healthy`` | ``backpressure`` | ``degrade`` | ``shed`` |
        ``reject`` (``degrade`` only when ``degrade_at`` is set)."""
        if pending >= self.max_queue:
            return "reject"
        if pending >= self.shed_at:
            return "shed"
        if self.degrade_at is not None and pending >= self.degrade_at:
            return "degrade"
        if pending >= self.backpressure_at:
            return "backpressure"
        return "healthy"

    def backpressure_wait_sec(self, req: "_Request") -> float:
        """How long the submitter may be held in the backpressure tier:
        the bounded wait, further capped by the request's own deadline
        slack (waiting past its deadline would just shed it later)."""
        wait = self.backpressure_wait_ms / 1e3
        if req.deadline is not None:
            wait = min(wait, max(0.0, req.slack(self.clock())))
        return wait

    def shed_victims(self, queued: Sequence["_Request"],
                     arriving: Optional["_Request"] = None
                     ) -> List["_Request"]:
        """The requests the shed tier drops right now: lowest deadline
        slack first, only while their slack is under ``shed_slack_ms``.
        ``arriving`` participates like a queued request — the newcomer is
        not privileged over requests already admitted."""
        now = self.clock()
        floor = self.shed_slack_ms / 1e3
        cands = list(queued) + ([arriving] if arriving is not None else [])
        doomed = [r for r in cands if r.slack(now) < floor]
        return sorted(doomed, key=lambda r: r.slack(now))


class DynamicBatcher:
    def __init__(
        self,
        engine: InferenceEngine,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_batch_size: int = 8,
        max_wait_ms: float = 5.0,
        max_queue: int = 256,
        default_deadline_ms: Optional[float] = None,
        serve_pack: str = "auto",
        pack_max_segments: int = 16,
        long_widths: Sequence[int] = (),
    ):
        self.engine = engine
        self.buckets = usable_buckets(buckets, engine.args.max_seq_len)
        # flush threshold = the PADDED row count: executed batches pad rows
        # to the mesh's data-axis multiple anyway, so flushing at a smaller
        # size would cap occupancy below 1.0 forever (e.g. data axis 8 with
        # max_batch_size 4 -> every batch half filler even under load)
        self.max_batch_size = engine.pad_rows(int(max_batch_size))
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue = int(max_queue)
        self.default_deadline_ms = default_deadline_ms
        # packed online batching: requests bin-pack many-per-row into one
        # fixed [rows, pack_width] batch; every bound moves to TOKEN units
        # — the flush trigger is "a full batch worth of real tokens" and
        # the queue bound is max_queue rows' worth of token slots, so a
        # storm of short requests is admitted by the work it actually
        # brings, not by how many envelopes it arrives in
        self.packed = resolve_serve_pack(serve_pack, self.buckets[-1])
        self.pack_width = self.buckets[-1]
        self.pack_rows = self.max_batch_size
        self.pack_segments = int(pack_max_segments)
        self.flush_tokens = self.pack_rows * self.pack_width
        self.max_queue_tokens = self.max_queue * self.pack_width
        # chunked prefill (``long_widths``): a request longer than the pack
        # width routes to a per-width LONG packed queue and executes as one
        # segment of a [rows_w, w] packed batch — exact whole-request
        # scoring at width w (positions restart per segment, attention
        # masked to the request) — where rows_w sizes every long flush to
        # ~the SAME token budget as a short flush (flush_tokens / w rows).
        # Long traffic is therefore consumed in short-flush-sized chunks
        # that interleave with the packed short-query flushes instead of
        # head-of-line-blocking them; admission already charges tokens, so
        # a long request simply costs more of the shared token pool.
        self.long_widths = tuple(sorted({int(w) for w in long_widths}))
        self.long_rows: Dict[int, int] = {}
        self.long_segments: Dict[int, int] = {}
        if self.long_widths:
            from pdnlp_tpu.data.packing import segment_cap

            if not self.packed:
                raise ValueError(
                    "chunked prefill (long_widths) rides the packed path — "
                    "it needs --serve_pack to resolve on for the pack "
                    "width, got the padded per-bucket path")
            for w in self.long_widths:
                if w <= self.pack_width or w % 128:
                    raise ValueError(
                        f"long width {w} must exceed the {self.pack_width}-"
                        "token pack width and tile the 128-wide kernel "
                        "blocks")
                if w > engine.cfg.max_position:
                    raise ValueError(
                        f"long width {w} exceeds {engine.args.model}'s "
                        f"{engine.cfg.max_position}-position table — a "
                        "long request is ONE segment, so its positions "
                        "span the full width and would gather garbage "
                        "embeddings past the table.  Use a long-position "
                        "model (--model bert-base-long, 2048 positions) "
                        "or drop the width")
                self.long_rows[w] = engine.pad_rows(
                    max(1, self.flush_tokens // w))
                self.long_segments[w] = segment_cap(w, self.pack_segments,
                                                    self.pack_width)
        self.metrics: ServeMetrics = engine.metrics
        self._queues: Dict[int, List[_Request]] = {b: [] for b in self.buckets}
        self._pack_queue: List[_Request] = []
        self._long_queues: Dict[int, List[_Request]] = {
            w: [] for w in self.long_widths}
        # O(1) per-queue token tallies for the flush decision (summing the
        # queue request-by-request under the lock would charge every worker
        # wake O(queued) exactly at saturation); keys: "pack" + each long
        # width.  _pending_tokens stays the ADMISSION total across them.
        self._queue_tokens: Dict = {"pack": 0,
                                    **{w: 0 for w in self.long_widths}}
        self._pending = 0
        self._pending_tokens = 0
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._stop = False
        self._worker: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "DynamicBatcher":
        if self._worker is None:
            self._stop = False  # a stopped batcher restarts cleanly
            self._worker = threading.Thread(target=self._run, daemon=True,
                                            name="pdnlp-serve-batcher")
            self._worker.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Shut the worker down; ``drain=True`` serves what is queued first."""
        if self._worker is None:
            return
        if drain:
            with self._lock:
                while self._pending and not self._stop:
                    self._wake.wait(timeout=0.05)
        with self._lock:
            self._stop = True
            self._wake.notify_all()
        self._worker.join(timeout=10)
        self._worker = None
        with self._lock:  # fail anything still queued (stop(drain=False))
            leftovers = [r for q in self._all_queues() for r in q]
            for q in self._queues.values():
                q.clear()
            self._pack_queue = []
            self._long_queues = {w: [] for w in self.long_widths}
            self._queue_tokens = {"pack": 0,
                                  **{w: 0 for w in self.long_widths}}
            self._pending = 0
            self._pending_tokens = 0
            self.metrics.queue_depth.set(0)
            self.metrics.queue_tokens.set(0)
        for r in leftovers:
            if r._complete(None, RuntimeError("batcher stopped")):
                record_hop(self.engine.tracer, r.rid, "failed",
                           error="batcher stopped")

    def __enter__(self) -> "DynamicBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- submit
    def _all_queues(self) -> List[List[_Request]]:
        """Every live queue (bucket + packed + long), for sweeps."""
        return (list(self._queues.values()) + [self._pack_queue]
                + [self._long_queues[w] for w in self.long_widths])

    @property
    def max_request_tokens(self) -> int:
        """The truncation bound a submitted request gets: the largest
        long width under chunked prefill, else the largest bucket."""
        return (self.long_widths[-1] if (self.long_widths and self.packed)
                else self.buckets[-1])

    def submit(self, text: str,
               deadline_ms: Optional[float] = None) -> _Request:
        """Enqueue one text; returns a future-like whose ``result()`` is the
        logits row.  Raises :class:`QueueFullError` at capacity (the
        backpressure contract: callers retry or shed).

        Encoding truncates to the LARGEST width this batcher can serve —
        the top long width under chunked prefill, else the largest bucket
        (a row no width covers would otherwise fail its whole batch at
        execute time)."""
        ids = self.engine.tokenizer.encode_ids(text, self.max_request_tokens)
        return self.submit_ids(ids, deadline_ms=deadline_ms)

    def submit_ids(self, ids: List[int],
                   deadline_ms: Optional[float] = None) -> _Request:
        if not ids:
            # an empty row is meaningless on the padded path and would
            # corrupt a packed batch (phantom segment aliasing a
            # neighbor's [CLS] gather) — reject at the door, loudly
            raise ValueError("empty request: submit at least one token id")
        if len(ids) > self.max_request_tokens:
            # pre-encoded rows get a plain tail truncation (only submit()'s
            # text path knows the [CLS]/[SEP] framing to preserve) — a row
            # that cannot fit any served width must never reach a batch,
            # where its shape error would poison every co-batched request
            ids = list(ids)[: self.max_request_tokens]
        deadline_ms = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        req = _Request(ids, pick_bucket(len(ids), self.buckets), deadline)
        tr = self.engine.tracer
        long_w = None  # set by the packed branch when the request is long
        with self._lock:
            if self._stop or self._worker is None:
                raise RuntimeError("batcher is not running (call start())")
            if self.packed:
                # token-unit admission: capacity is max_queue rows' worth
                # of token SLOTS — a short-request storm is bounded by the
                # work it brings, not by its request count
                if self._pending_tokens + len(ids) > self.max_queue_tokens:
                    self.metrics.rejected_total.inc()
                    record_hop(tr, req.rid, "rejected")
                    raise QueueFullError(
                        f"queue full ({self._pending_tokens}"
                        f"/{self.max_queue_tokens} tokens)")
                if self.long_widths and len(ids) > self.pack_width:
                    # chunked prefill: smallest long width covering the
                    # request; same shared token pool as the short queue
                    long_w = next(w for w in self.long_widths
                                  if len(ids) <= w)
                    req.bucket = long_w
                    self._long_queues[long_w].append(req)
                else:
                    long_w = None
                    self._pack_queue.append(req)
                self._pending_tokens += len(ids)
                self._queue_tokens[long_w or "pack"] += len(ids)
                self.metrics.queue_tokens.set(self._pending_tokens)
            else:
                if self._pending >= self.max_queue:
                    self.metrics.rejected_total.inc()
                    record_hop(tr, req.rid, "rejected")
                    raise QueueFullError(
                        f"queue full ({self._pending}/{self.max_queue})")
                self._queues[req.bucket].append(req)
            self._pending += 1
            self.metrics.requests_total.inc()
            self.metrics.queue_depth.set(self._pending)
            # ONE hop for admission + initial queue placement (recording
            # two would double the per-submit tracing cost for no extra
            # information — the attrs carry both); tokens + deadline ride
            # along for serve.replay's arrival reconstruction
            record_hop(tr, req.rid, "admit", tier="healthy",
                       tokens=len(ids),
                       **({} if deadline_ms is None
                          else {"deadline_ms": float(deadline_ms)}),
                       **({"packed": True} if self.packed
                          else {"bucket": req.bucket}),
                       **({"long_width": long_w}
                          if self.packed and long_w else {}))
            self._wake.notify()
        return req

    # ------------------------------------------------------------- worker
    def _take_flushable(self):
        """Under the lock: pop a flushable batch or None — a full (or aged)
        bucket on the padded path; on the packed path the priority ladder
        over the short token queue and the chunked-prefill long queues:

        1. OVERDUE long flush (oldest long request waited >= 2x
           ``max_wait_ms``) — the anti-starvation valve: it outranks even
           a full short flush, so sustained short saturation cannot park
           a long request forever, and it costs the short traffic one
           chunk (a long flush is sized to ~one short flush's tokens);
        2. short packed flush: a full token budget queued (throughput) or
           the oldest short aged out (latency) — shorts otherwise always
           go first, which is what holds the short-query p99 under mixed
           long/short storms;
        3. full long chunk (ascending width);
        4. aged long flush (>= ``max_wait_ms``).
        """
        now = time.monotonic()
        # expired-deadline requests leave their queue before batch selection
        # (their slot should not hold a flush back or ride a batch)
        expired: List[_Request] = []
        for key, q in ([(None, b) for b in self._queues.values()]
                       + [("pack", self._pack_queue)]
                       + list(self._long_queues.items())):
            keep = []
            dropped = 0
            for r in q:
                if r.deadline is not None and now >= r.deadline:
                    expired.append(r)
                    dropped += len(r.ids)
                else:
                    keep.append(r)
            q[:] = keep
            if key is not None and dropped:
                self._queue_tokens[key] -= dropped
        if expired:
            self._pending -= len(expired)
            if self.packed:  # tokens are only accounted on the packed path
                self._pending_tokens -= sum(len(r.ids) for r in expired)
                self.metrics.queue_tokens.set(self._pending_tokens)
            self.metrics.deadline_expired_total.inc(len(expired))
            self.metrics.queue_depth.set(self._pending)
            for r in expired:
                if r._complete(None, DeadlineExceeded(
                        "deadline passed while queued")):
                    record_hop(self.engine.tracer, r.rid, "deadline")
        if self.packed:
            oldest_long = [(min(r.submitted for r in q), w)
                           for w, q in self._long_queues.items() if q]
            if oldest_long:  # 1. overdue long outranks full shorts
                oldest, w = min(oldest_long)
                if (now - oldest) * 1e3 >= 2 * self.max_wait_ms:
                    return self._long_pop(w, now)
            # 2. token-budget flush: a full batch worth of REAL tokens
            # queued (throughput), else the oldest request aged (latency)
            q = self._pack_queue
            if q:
                if self._queue_tokens["pack"] >= self.flush_tokens \
                        or (now - min(r.submitted for r in q)) * 1e3 \
                        >= self.max_wait_ms:
                    return self._pack_pop(now)
            for w in self.long_widths:  # 3. full long chunk
                if self._long_queues[w] and self._queue_tokens[w] \
                        >= self.long_rows[w] * w:
                    return self._long_pop(w, now)
            if oldest_long:  # 4. aged long
                oldest, w = min(oldest_long)
                if (now - oldest) * 1e3 >= self.max_wait_ms:
                    return self._long_pop(w, now)
            return None
        # full bucket first (throughput); else the most-overdue aged bucket
        for b, q in self._queues.items():
            if len(q) >= self.max_batch_size:
                return self._pop(b, self.max_batch_size)
        aged = [(q[0].submitted, b) for b, q in self._queues.items() if q]
        if aged:
            oldest, b = min(aged)
            if (now - oldest) * 1e3 >= self.max_wait_ms:
                return self._pop(b, self.max_batch_size)
        return None

    def _pack_pop(self, now: float) -> _PackedBatch:
        """Under the lock: bin-pack the queue (``form_packed_batch``) into
        one fixed-shape batch; whatever does not fit stays queued.
        Holding the lock here is bounded work — the single-replica queue
        is capped at ``max_queue_tokens`` and only submitters contend (the
        router's multi-worker path packs OUTSIDE its pool-global lock)."""
        pb, self._pack_queue = self._form_pop(
            "pack", self._pack_queue, now, self.pack_width, self.pack_rows,
            self.pack_segments)
        return pb

    def _long_pop(self, width: int, now: float) -> _PackedBatch:
        """One chunked-prefill flush: the width's queue bin-packs into a
        ``[long_rows[w], w]`` batch — the same token budget as a short
        flush, so it interleaves instead of blocking."""
        pb, self._long_queues[width] = self._form_pop(
            width, self._long_queues[width], now, width,
            self.long_rows[width], self.long_segments[width])
        return pb

    def _form_pop(self, key, queue: List[_Request], now: float, width: int,
                  rows: int, segments: int):
        """Shared pop core: form, account, return (batch, leftovers)."""
        pb, leftover = form_packed_batch(
            queue, now, width, rows, segments,
            self.engine.tokenizer.pad_id, self.max_wait_ms / 1e3)
        self._pending -= len(pb.requests)
        self._pending_tokens -= pb.tokens
        self._queue_tokens[key] -= pb.tokens
        self.metrics.queue_depth.set(self._pending)
        self.metrics.queue_tokens.set(self._pending_tokens)
        return pb, leftover

    def _pop(self, bucket: int, n: int) -> List[_Request]:
        q = self._queues[bucket]
        batch, q[:] = q[:n], q[n:]
        self._pending -= len(batch)
        self.metrics.queue_depth.set(self._pending)
        return batch

    def _next_wakeup(self) -> Optional[float]:
        """Seconds until the earliest timeout/deadline, or None to sleep."""
        now = time.monotonic()
        ticks = []
        for q in self._all_queues():
            for r in q:
                ticks.append(r.submitted + self.max_wait_ms / 1e3)
                if r.deadline is not None:
                    ticks.append(r.deadline)
        if not ticks:
            return None
        return max(0.0, min(ticks) - now)

    def _run(self) -> None:
        while True:
            with self._lock:
                batch = self._take_flushable()
                if batch is None:
                    if self._stop:
                        return
                    self._wake.wait(timeout=self._next_wakeup())
                    continue
            self._execute(batch)
            with self._lock:
                self._wake.notify_all()  # unblock stop(drain=True) waiters

    #: the single-replica tuning surface (the router has the full set);
    #: ONE setter so controller-side writes stay auditable (jaxlint R13)
    KNOBS = ("max_wait_ms", "max_queue")

    def apply_knob(self, name: str, value) -> None:
        """Thread-safe setter for the batcher's tunable knobs, effective
        at the next flush decision."""
        with self._lock:
            if name == "max_wait_ms":
                self.max_wait_ms = float(value)
            elif name == "max_queue":
                self.max_queue = int(value)
                self.max_queue_tokens = self.max_queue * self.pack_width
            else:
                raise KeyError(f"unknown knob {name!r} (tunable: "
                               f"{self.KNOBS})")
            self._wake.notify_all()

    def knob_values(self) -> Dict[str, float]:
        return {"max_wait_ms": self.max_wait_ms,
                "max_queue": self.max_queue}

    def warmup(self) -> None:
        """Pre-trace every shape live traffic can reach: the fixed packed
        shape plus one fixed ``(w, long_rows[w], "packed")`` shape per
        chunked-prefill width on the packed path, one batch per bucket on
        the padded path — after this, steady-state serving never
        compiles."""
        if self.packed:
            self.engine.warmup_packed(self.pack_width, self.pack_rows,
                                      self.pack_segments)
            for w in self.long_widths:
                self.engine.warmup_packed(w, self.long_rows[w],
                                          self.long_segments[w])
        else:
            self.engine.warmup(self.buckets, self.max_batch_size)

    def _execute(self, batch) -> None:
        if isinstance(batch, _PackedBatch):
            return self._execute_packed(batch)
        bucket = batch[0].bucket
        t0 = time.monotonic()
        # dequeue-time expiry: the flush decision and this execution are
        # separated by however long the worker spent on the PREVIOUS batch
        # — a request whose deadline passed in that window must not ride
        # the batch (its caller already gave up) nor hold a row
        tr = self.engine.tracer
        live = []
        for r in batch:
            if r.deadline is not None and t0 >= r.deadline:
                self.metrics.deadline_expired_total.inc()
                if r._complete(None, DeadlineExceeded(
                        "deadline passed while queued")):
                    record_hop(tr, r.rid, "deadline")
            else:
                live.append(r)
        batch = live
        if not batch:
            return
        for r in batch:
            self.metrics.queue_wait_ms.observe((t0 - r.submitted) * 1e3)
        # one queue_wait span per flushed batch, duration = its OLDEST
        # request's wait (the flush-policy-visible latency); recorded in
        # the tracer's clock domain with explicit timestamps since the
        # wait began before this call
        if tr.enabled:
            now = tr.now()
            oldest = max(t0 - r.submitted for r in batch)
            tr.record("queue_wait", now - oldest, now, bucket=bucket,
                      rows=len(batch), request_ids=exemplar_ids(batch))
            for i, r in enumerate(batch):
                record_hop(tr, r.rid, "dispatch", bucket=bucket, row=i)
        try:
            rows = self.max_batch_size  # already padded to the mesh multiple
            logits = self.engine.infer_ids(
                [r.ids for r in batch], bucket, rows=rows,
                request_ids=[r.rid for r in batch])
            self.metrics.batches_total.inc()
            self.metrics.batch_occupancy.observe(len(batch) / rows)
            done = time.monotonic()
            for i, r in enumerate(batch):
                self.metrics.request_latency_ms.observe(
                    (done - r.submitted) * 1e3)
                if r._complete(logits[i]):
                    record_hop(tr, r.rid, "complete")
        except BaseException as e:  # noqa: BLE001 — a failed batch must
            for r in batch:        # never leave callers blocked forever
                if r._complete(None, e):
                    record_hop(tr, r.rid, "failed",
                               error=type(e).__name__)

    def _execute_packed(self, pb: _PackedBatch) -> None:
        t0 = time.monotonic()
        tr = self.engine.tracer
        # the batch is already packed — a corpse's tokens ride anyway —
        # but its caller gave up, so complete it with the expiry error and
        # skip its scatter rather than hand back a result nobody awaits
        live: List[tuple] = []
        for r, place in zip(pb.requests, pb.placements):
            if r.deadline is not None and t0 >= r.deadline:
                self.metrics.deadline_expired_total.inc()
                if r._complete(None, DeadlineExceeded(
                        "deadline passed while queued")):
                    record_hop(tr, r.rid, "deadline")
            else:
                live.append((r, place))
        if not live:
            return
        for r, _ in live:
            self.metrics.queue_wait_ms.observe((t0 - r.submitted) * 1e3)
        if tr.enabled:
            now = tr.now()
            oldest = max(t0 - r.submitted for r, _ in live)
            tr.record("queue_wait", now - oldest, now,
                      bucket=pb.width, rows=len(live), packed=True,
                      request_ids=exemplar_ids([r for r, _ in live]))
            for r, (row, slot) in live:
                record_hop(tr, r.rid, "pack", row=row, slot=slot)
                record_hop(tr, r.rid, "dispatch", row=row, slot=slot,
                           packed=True)
        try:
            logits = self.engine.infer_packed(
                pb.arrays, segments=len(live),
                request_ids=[r.rid for r, _ in live])
            self.metrics.batches_total.inc()
            # occupancy in TOKEN slots: a packed batch always spends every
            # row, so rows would read 1.0 forever — real tokens over the
            # rows x width slots is the number that stays honest
            self.metrics.batch_occupancy.observe(pb.fill)
            done = time.monotonic()
            for r, (row, slot) in live:
                self.metrics.request_latency_ms.observe(
                    (done - r.submitted) * 1e3)
                if r._complete(logits[row, slot]):
                    record_hop(tr, r.rid, "complete")
        except BaseException as e:  # noqa: BLE001 — a failed batch must
            for r, _ in live:      # never leave callers blocked forever
                if r._complete(None, e):
                    record_hop(tr, r.rid, "failed",
                               error=type(e).__name__)
