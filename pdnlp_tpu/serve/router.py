"""Multi-replica serving router — the tier that survives overload and
replica death.

The PR-1 serve design is one engine behind one batcher: a dead device
stream takes the whole service with it, and the only overload answer is a
reject-on-full cliff.  This module fronts **N engine replicas** (one per
device group / mesh slice, or N independent CPU engines in tests) with the
robustness discipline PR 7 built for training:

- **per-replica queues + least-loaded dispatch** — each replica keeps its
  own per-bucket queues and ONE worker thread that owns its engine (the
  single-dispatcher contract of :class:`~pdnlp_tpu.serve.batcher.
  DynamicBatcher`, times N); an arriving request lands on the least-loaded
  replica that can take it;
- **tiered admission** (:class:`~pdnlp_tpu.serve.batcher.AdmissionControl`)
  — healthy -> bounded-wait backpressure -> shed-lowest-deadline-slack ->
  hard reject, replacing the single :class:`QueueFullError` cliff;
- **health via the existing watchdog machinery** — every replica worker
  writes a beat-payload :class:`~pdnlp_tpu.parallel.watchdog.Heartbeat`
  (step = batches served) and a monitor thread reads them through a
  :class:`~pdnlp_tpu.parallel.watchdog.GangMonitor` over per-replica
  process adapters, so *crashed* (worker died) and *stalled* (worker wedged,
  beats stopped) replicas are classified by the same verdict logic the
  elastic trainer trusts;
- **ejection without loss** — an ejected replica's queued requests are
  requeued onto survivors within their remaining deadline budget; its
  in-flight batch is re-dispatched with a per-request retry budget
  (``max_retries``); completion is first-wins, so a wedged worker waking up
  later can never double-complete;
- **warmup-gated reintegration** — a relaunched replica serves nothing
  until its worker has re-run the bucket warmup, so reintegration can never
  introduce post-warmup retraces (each replica's retrace counter is
  baselined at the end of ITS warmup);
- **rolling checkpoint hot-swap** — :meth:`swap_checkpoint` drains and
  swaps one replica at a time; a corrupt artifact
  (:class:`~pdnlp_tpu.train.checkpoint.CorruptCheckpointError`, or a
  template mismatch) rolls back that replica (the engine's params are
  untouched on a failed load) and aborts the rollout instead of poisoning
  the rest of the pool;
- **optional tail hedging** — a request stuck in a queue past ``hedge_ms``
  with deadline budget left is duplicated onto a less-loaded replica;
  first completion wins;
- **packed online batching** (``serve_pack``, default ``auto``) — each
  replica bin-packs its queue many-requests-per-row into ONE fixed
  ``[rows, pack_width]`` packed batch (``data.packing.pack_id_lists``,
  lowest-deadline-slack rows close first), flush policy and admission move
  to TOKEN units, and ejection re-packs the victim's queued + in-flight
  requests on the survivors' token queues.  Hedged duplicates always stay
  on the padded per-bucket path (both paths are warmed, so neither can
  retrace post-warmup);
- **a mutable tuning surface** (:meth:`apply_knob` + warm-standby scaling)
  — the hand-set constants (``hedge_ms``, ``max_wait_ms``, the admission
  thresholds) are thread-safe knobs with ONE setter, and a healthy replica
  can be drained to a **warm standby** (:meth:`deactivate_replica`: its
  queue moves to peers, its engine keeps its compiled caches and its
  worker keeps beating) and brought back through the same warmup-gated
  path a relaunch uses (:meth:`activate_replica`) — so the feedback
  control plane (:mod:`pdnlp_tpu.serve.controller`) can actuate capacity
  without ever introducing a post-warmup retrace.  Every controller write
  must come through the controller's ``_actuate`` choke point (jaxlint
  R13), which records a decision chain explaining the change.

Single-replica serving is untouched: :class:`DynamicBatcher` remains the
default path (``serve_tpu.py`` only builds a router under ``--replicas N``
with N > 1).
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from pdnlp_tpu.obs.request import exemplar_ids, record_hop
from pdnlp_tpu.parallel.watchdog import GangMonitor, Heartbeat
from pdnlp_tpu.serve.batcher import (
    DEFAULT_BUCKETS, AdmissionControl, DeadlineExceeded, LoadShedError,
    QueueFullError, _PackedBatch, _Request, form_packed_batch, pick_bucket,
    resolve_serve_pack, usable_buckets,
)
from pdnlp_tpu.serve.metrics import ReplicaMetrics, RouterMetrics, _save_json
from pdnlp_tpu.train.checkpoint import CorruptCheckpointError


class ReplicaFailedError(RuntimeError):
    """A request's replica died and its retry budget is exhausted (or no
    survivor was available to take it)."""


class _InjectedFault(RuntimeError):
    """Raised inside a replica worker by the chaos hooks — stands in for
    the process death / wedge a SIGKILL'd or hung replica would show."""


class _Replica:
    """One replica incarnation: an engine, its queues, and worker state.

    States: ``warming`` (worker is pre-tracing every bucket; not
    dispatchable) -> ``healthy`` -> ``draining`` (rolling swap: finish
    in-flight, accept queue but execute nothing) -> back to ``healthy``;
    ``standby`` (scaled down by the control plane: queues empty, engine
    warm — compiled caches intact — worker parked but still beating;
    :meth:`ReplicaRouter.activate_replica` sends it back through
    ``warming``, which is all cache hits, so reactivation can never
    retrace); ``ejected`` is terminal for THIS incarnation (a relaunch
    builds a new one in the same slot)."""

    def __init__(self, index: int, engine, buckets: Sequence[int],
                 flush_rows: int, pack_width: int = 0):
        self.index = index
        self.engine = engine
        self.state = "warming"
        # the flush threshold is the PADDED row count (DynamicBatcher's
        # lesson): executed batches pad to the replica's mesh data-axis
        # multiple anyway, so flushing at a smaller size would cap this
        # replica's occupancy below 1.0 forever
        self.flush_rows = int(flush_rows)
        # packed path: the flush trigger in TOKEN units — a full packed
        # batch worth of real tokens (flush_rows rows x the pack width)
        self.flush_tokens = self.flush_rows * int(pack_width)
        self.queues: Dict[int, List[_Request]] = {b: [] for b in buckets}
        # packed mode's single token-level queue; the per-bucket queues
        # stay alive beside it for hedged duplicates (padded by contract)
        self.pack_queue: List[_Request] = []
        self.inflight: List[_Request] = []
        self.exit_code: Optional[int] = None  # None while the worker lives
        self.batches = 0
        self.retrace_warm: Optional[int] = None  # retraces at end of warmup
        self.fault: Optional[str] = None  # chaos hook: "crash" | "hang"
        self.worker: Optional[threading.Thread] = None
        self.hb: Optional[Heartbeat] = None

    def queued(self) -> int:
        return sum(len(q) for q in self.queues.values()) \
            + len(self.pack_queue)

    def queued_tokens(self) -> int:
        return sum(len(r.ids) for r in self.pack_queue)

    def all_queues(self) -> List[List[_Request]]:
        """Every queue holding requests (bucket queues + the pack queue)
        — the sweep/shed/stop paths must see both."""
        return list(self.queues.values()) + [self.pack_queue]

    def load(self) -> int:
        return self.queued() + len(self.inflight)

    @property
    def retraces_post_warmup(self) -> int:
        if self.retrace_warm is None:
            return 0
        return self.engine.metrics.retraces.value - self.retrace_warm


class _Slot:
    """Stable per-rank holder: the GangMonitor adapter and the replica-
    labelled metrics survive relaunches, so rank i's history is one series
    even as incarnations come and go."""

    def __init__(self, index: int):
        self.index = index
        self.replica: Optional[_Replica] = None
        self.metrics = ReplicaMetrics()
        self.ejected_at: Optional[float] = None


class _PackIntent:
    """A flush decision for the packed path: a SNAPSHOT of the replica's
    pack queue taken under the lock.  The expensive part — slack sort +
    six channel-array builds (``form_packed_batch``) — then runs OUTSIDE
    the pool-global lock (it would otherwise serialize every worker,
    submitter and the monitor against one replica's batch formation).
    The snapshot's requests stay IN the queue meanwhile, so ejection,
    shedding and expiry keep their normal queued semantics; the worker
    reconciles (removes the taken, abandons on ejection) under the lock
    before executing."""

    __slots__ = ("requests",)

    def __init__(self, requests: List[_Request]):
        self.requests = requests


class _ReplicaProc:
    """Quacks like a subprocess for :class:`GangMonitor`: ``poll()`` is
    None while the slot's current worker lives, its synthetic exit code
    after a crash, and 0 once the router has processed the ejection (so a
    handled crash stops short-circuiting the monitor's stall checks for
    the OTHER ranks)."""

    def __init__(self, slot: _Slot):
        self._slot = slot

    def poll(self) -> Optional[int]:
        rep = self._slot.replica
        if rep is None or rep.state == "ejected":
            return 0
        return rep.exit_code

    def terminate(self) -> None:  # pragma: no cover - monitor API surface
        pass

    def kill(self) -> None:  # pragma: no cover - monitor API surface
        pass


class ReplicaRouter:
    """N engine replicas behind tiered admission + health-ejecting dispatch
    (module docstring has the full story).

    ``engines`` seeds the pool; ``engine_factory(index)`` (optional) lets
    :meth:`relaunch` build replacement engines after an ejection.  All
    engines must share a tokenizer/bucket view (they are replicas, not a
    heterogeneous fleet).

    ``clock`` (deadlines/latency, default ``time.monotonic``) and
    ``health_clock`` (heartbeat domain, default ``time.time``) are
    injectable so tier transitions and slack ordering are testable without
    sleeping.
    """

    def __init__(
        self,
        engines: Sequence,
        *,
        engine_factory: Optional[Callable[[int], object]] = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_batch_size: int = 8,
        max_wait_ms: float = 5.0,
        max_queue: int = 256,
        default_deadline_ms: Optional[float] = None,
        backpressure_at: Optional[int] = None,
        shed_at: Optional[int] = None,
        backpressure_wait_ms: float = 50.0,
        shed_slack_ms: Optional[float] = None,
        degrade_at: Optional[int] = None,
        serve_pack: str = "auto",
        pack_max_segments: int = 16,
        max_retries: int = 1,
        model_id: Optional[str] = None,
        hedge_ms: Optional[float] = None,
        stall_timeout: float = 10.0,
        poll_interval: float = 0.1,
        hb_dir: Optional[str] = None,
        telemetry_dir: Optional[str] = None,
        checkpoint_path: Optional[str] = None,
        metrics: Optional[RouterMetrics] = None,
        tracer=None,
        clock: Callable[[], float] = time.monotonic,
        health_clock: Callable[[], float] = time.time,
    ):
        if not engines:
            raise ValueError("ReplicaRouter needs at least one engine")
        self.engine_factory = engine_factory
        self._tokenizer = engines[0].tokenizer
        self.buckets = usable_buckets(buckets, engines[0].args.max_seq_len)
        self.max_batch_size = int(max_batch_size)
        self.max_wait_ms = float(max_wait_ms)
        self.default_deadline_ms = default_deadline_ms
        # packed online serving: every admission/flush bound moves from
        # request (row) units to TOKEN units — AdmissionControl itself is
        # unit-agnostic (pending vs thresholds), so packed mode scales the
        # thresholds by the pack width and walks the SAME ladder with
        # pending-token depth.  Hedged duplicates always ride the padded
        # per-bucket path (a hedge exists to dodge a slow replica, not to
        # wait for a pack to fill).
        self.packed = resolve_serve_pack(serve_pack, self.buckets[-1])
        self.pack_width = self.buckets[-1]
        self.pack_segments = int(pack_max_segments)
        unit = self.pack_width if self.packed else 1
        # a request with less remaining slack than two flush waits cannot
        # make its deadline once the pool is in the shed band — that is the
        # default "doomed" floor the shed tier drops first
        self.admission = AdmissionControl(
            max_queue * unit,
            backpressure_at=(backpressure_at * unit
                             if backpressure_at is not None else None),
            shed_at=shed_at * unit if shed_at is not None else None,
            degrade_at=(degrade_at * unit
                        if degrade_at is not None else None),
            backpressure_wait_ms=backpressure_wait_ms,
            shed_slack_ms=(2 * max_wait_ms if shed_slack_ms is None
                           else shed_slack_ms),
            clock=clock)
        # fleet labelling: a pool serving one model of a multi-model fleet
        # stamps that model id on every hop it records (and the fleet's
        # snapshot keys this pool's metrics under it), so per-request
        # chains and per-model metrics stay joinable
        self.model_id = model_id
        self._hop_attrs: Dict = {"model": model_id} \
            if model_id is not None else {}
        self.max_retries = int(max_retries)
        self.hedge_ms = hedge_ms
        self.stall_timeout = float(stall_timeout)
        self.poll_interval = float(poll_interval)
        self.metrics = metrics or RouterMetrics()
        self.tracer = tracer if tracer is not None else engines[0].tracer
        self.clock = clock
        self.health_clock = health_clock
        self.hb_dir = hb_dir or tempfile.mkdtemp(prefix="pdnlp-serve-hb-")
        # crash-path telemetry: spans + a metrics snapshot land HERE on
        # every ejection and on stop, so a condemned replica's last
        # batches are on disk even when nothing exits cleanly
        self.telemetry_dir = telemetry_dir or self.hb_dir
        self._beat_interval = min(1.0, self.stall_timeout / 5.0)

        self._slots = [_Slot(i) for i in range(len(engines))]
        for slot, engine in zip(self._slots, engines):
            slot.replica = self._make_replica(slot.index, engine)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = 0          # accepted, not yet completed
        self._pending_tokens = 0   # same, in real tokens (packed admission)
        self._stop = False
        self._started = False
        self._monitor_thread: Optional[threading.Thread] = None
        self._mon: Optional[GangMonitor] = None
        # the checkpoint every incarnation must serve: factory-built
        # relaunch engines load it during their warmup; a successful
        # rolling swap advances it
        self._checkpoint_path = checkpoint_path

    # ------------------------------------------------------------ lifecycle
    def _make_replica(self, index: int, engine) -> _Replica:
        rep = _Replica(index, engine, self.buckets,
                       engine.pad_rows(self.max_batch_size),
                       pack_width=self.pack_width)
        rep.hb = Heartbeat(self.hb_dir, index, interval=self._beat_interval,
                           clock=self.health_clock)
        # forward/compile spans carry the replica rank so the per-replica
        # phase tables (obs.phases) can attribute engine time per replica
        engine.span_attrs = {"replica": index}
        return rep

    def start(self) -> "ReplicaRouter":
        if self._started:
            return self
        self._started = True
        self._stop = False
        for slot in self._slots:
            self._start_worker(slot.replica)
        self._mon = GangMonitor(
            [_ReplicaProc(s) for s in self._slots], self.hb_dir,
            len(self._slots), stall_timeout=self.stall_timeout,
            clock=self.health_clock)
        self._monitor_thread = threading.Thread(
            target=self._monitor, daemon=True, name="pdnlp-serve-monitor")
        self._monitor_thread.start()
        return self

    def _start_worker(self, rep: _Replica) -> None:
        rep.worker = threading.Thread(
            target=self._worker, args=(rep,), daemon=True,
            name=f"pdnlp-serve-replica{rep.index}")
        rep.worker.start()

    def wait_ready(self, timeout: float = 120.0) -> bool:
        """Block until every (non-ejected) replica finished its warmup."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while time.monotonic() < deadline:
                reps = [s.replica for s in self._slots if s.replica]
                if reps and all(r.state in ("healthy", "draining",
                                            "standby", "ejected")
                                for r in reps) \
                        and any(r.state in ("healthy", "draining")
                                for r in reps):
                    return True
                self._cond.wait(timeout=0.05)
        return False

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Shut the pool down; ``drain=True`` serves what is queued first
        (bounded by ``timeout`` and by replica liveness — a dead pool
        cannot drain, it fails what is left loudly instead)."""
        if drain:
            deadline = time.monotonic() + timeout
            with self._lock:
                while self._pending and time.monotonic() < deadline:
                    if not any(s.replica and s.replica.state in
                               ("healthy", "warming", "draining")
                               and s.replica.exit_code is None
                               for s in self._slots):
                        break  # nobody left to serve the backlog
                    self._cond.wait(timeout=0.05)
        with self._lock:
            self._stop = True
            self._cond.notify_all()
            leftovers = []
            for slot in self._slots:
                rep = slot.replica
                if rep is None:
                    continue
                for q in rep.all_queues():
                    leftovers += [r for r in q if not r.done()]
                    q.clear()
                leftovers += [r for r in rep.inflight if not r.done()]
        for t in [s.replica.worker for s in self._slots
                  if s.replica and s.replica.worker] \
                + ([self._monitor_thread] if self._monitor_thread else []):
            t.join(timeout=5)
        self._started = False
        self._monitor_thread = None
        for r in leftovers:
            self._finish(r, error=RuntimeError("router stopped"))
        self.flush_telemetry("stop")

    def __enter__(self) -> "ReplicaRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- metrics
    def _hop(self, rid: str, hop: str, **attrs) -> None:
        """One hop record with this pool's fleet labels (``model``) folded
        in — every hop the router records comes through here so a fleet
        pool can never emit an unlabelled hop."""
        record_hop(self.tracer, rid, hop, **self._hop_attrs, **attrs)

    def _finish(self, r: _Request, logits=None, error=None,
                latency: bool = False,
                replica: Optional[int] = None) -> bool:
        """Complete ``r`` exactly once and keep the pool accounting true
        (first completion decrements pending; hedged losers are no-ops)."""
        with self._lock:
            return self._finish_locked(r, logits, error, latency=latency,
                                       replica=replica)

    def _finish_locked(self, r: _Request, logits=None, error=None,
                       latency: bool = False,
                       replica: Optional[int] = None) -> bool:
        """:meth:`_finish`'s core, for callers already holding the router
        lock — ONE copy of the completion/error taxonomy so the counters,
        the latency histogram the p99 gate reads, and the request's
        TERMINAL hop (exactly one per accepted request — completion is
        first-wins) cannot drift."""
        won = r._complete(logits, error)
        if won:
            self._pending -= 1
            self._pending_tokens -= len(r.ids)
            self.metrics.queue_depth.set(self._pending)
            hop_attrs: Dict = {}
            if replica is not None:
                hop_attrs["replica"] = replica
            if error is None:
                self.metrics.completed_total.inc()
                hop = "complete"
                if latency:
                    self.metrics.request_latency_ms.observe(
                        (self.clock() - r.submitted) * 1e3)
            elif isinstance(error, DeadlineExceeded):
                self.metrics.deadline_expired_total.inc()
                hop = "deadline"
            elif isinstance(error, LoadShedError):
                self.metrics.shed_total.inc()
                hop = "shed"
            else:
                self.metrics.failed_total.inc()
                hop = "failed"
                hop_attrs["error"] = type(error).__name__
            if r.shadow_of is not None:
                # the shadow-side terminal marker: the chain contract
                # (obs.request) proves a shadow duplicate's life ends HERE
                # and never as a caller-visible answer
                hop_attrs["shadow"] = True
            self._hop(r.rid, hop, **hop_attrs)
            self._cond.notify_all()
        return won

    # -------------------------------------------------------------- submit
    def submit(self, text: str,
               deadline_ms: Optional[float] = None) -> _Request:
        """Enqueue one text (same truncation contract as the batcher)."""
        ids = self._tokenizer.encode_ids(text, self.buckets[-1])
        return self.submit_ids(ids, deadline_ms=deadline_ms)

    def make_request(self, ids: List[int],
                     deadline_ms: Optional[float] = None) -> _Request:
        """Build (but do NOT enqueue) a request in this pool's clock
        domain: truncation, bucket pick and deadline stamping — the
        :meth:`submit_ids` front half.  The fleet front door uses this to
        mint the request id and record fleet-level hops (``degrade``,
        ``shadow``) BEFORE a group pool admits the request."""
        if not ids:
            raise ValueError("empty request: submit at least one token id")
        if len(ids) > self.buckets[-1]:
            ids = list(ids)[: self.buckets[-1]]
        deadline_ms = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        now = self.clock()
        deadline = (now + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        req = _Request(ids, pick_bucket(len(ids), self.buckets), deadline)
        req.submitted = now  # _Request stamps time.monotonic; re-stamp in
        req.deadline = deadline  # the router's (injectable) clock domain
        return req

    def submit_ids(self, ids: List[int],
                   deadline_ms: Optional[float] = None) -> _Request:
        """Tiered admission + least-loaded dispatch; returns the future.

        Raises :class:`QueueFullError` (hard-full, or no replica able to
        take the request) or :class:`LoadShedError` (the shed tier dropped
        the arrival itself: its deadline slack was the pool's lowest and
        under the viability floor)."""
        return self.submit_request(self.make_request(ids, deadline_ms),
                                   deadline_ms=deadline_ms)

    def submit_request(self, req: _Request,
                       deadline_ms: Optional[float] = None) -> _Request:
        """Admission + enqueue for a request :meth:`make_request` built
        (the :meth:`submit_ids` back half, public so the fleet can route
        ONE minted request into whichever model group the traffic policy
        picks)."""
        deadline_ms = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        shadow = {"shadow": True} if req.shadow_of is not None else {}
        with self._lock:
            if self._stop or not self._started:
                raise RuntimeError("router is not running (call start())")
            tier = self._admit(req)
            slot = self._pick_slot(exclude=None)
            if slot is None:
                self.metrics.rejected_total.inc()
                self._hop(req.rid, "rejected", reason="no-replica",
                          **shadow)
                raise QueueFullError("no replica available (all ejected?)")
            self._enqueue(slot, req)
            # ONE hop for admission + initial queue placement (the attrs
            # carry the tier AND where the request landed); tokens +
            # deadline ride along so serve.replay can reconstruct the
            # arrival process (timestamps, lengths, deadlines) from the
            # recorded chains
            self._hop(req.rid, "admit", tier=tier,
                      replica=slot.index, tokens=len(req.ids),
                      **({} if deadline_ms is None
                         else {"deadline_ms": float(deadline_ms)}),
                      **({"packed": True} if self.packed
                         else {"bucket": req.bucket}))
            self.metrics.requests_total.inc()
            self._pending += 1
            self._pending_tokens += len(req.ids)
            self.metrics.queue_depth.set(self._pending)
            self._cond.notify_all()
        return req

    @property
    def _pending_units(self) -> int:
        """Admission-ladder depth in the ladder's own unit: real TOKENS on
        the packed path (thresholds were scaled by the pack width), raw
        request count on the padded path."""
        return self._pending_tokens if self.packed else self._pending

    def _admit(self, req: _Request) -> str:
        """Walk the admission ladder under the lock; raises to refuse,
        returns the tier the request was accepted at (its ``admit`` hop
        attr)."""
        adm = self.admission
        waited = False
        while True:
            tier = adm.tier(self._pending_units)
            if tier == "healthy":
                return "backpressure" if waited else "healthy"
            if tier == "backpressure":
                if waited:
                    return tier  # bounded wait paid: accept at elevated depth
                waited = True
                self.metrics.backpressure_waits_total.inc()
                wait = adm.backpressure_wait_sec(req)
                t0 = time.monotonic()
                self._cond.wait(timeout=wait)
                self.metrics.backpressure_wait_ms.observe(
                    (time.monotonic() - t0) * 1e3)
                continue  # re-evaluate: depth may have dropped OR grown
            if tier in ("shed", "degrade"):
                # a pool reaching the degrade band with nothing behind it
                # (no fleet, or a fleet with no cheap model) treats it as
                # an early shed tier — the re-route decision belongs to
                # the fleet front door, which consults admission_tier()
                # BEFORE submitting here
                self._shed_pass(arriving=req)
                if req.done():  # the arrival itself was the doomed one
                    raise LoadShedError(
                        "shed: lowest deadline slack in the pool and under "
                        f"the {adm.shed_slack_ms:.0f}ms viability floor")
                return tier  # accepted at shed depth (its slack is viable)
            # tier == "reject"
            self.metrics.rejected_total.inc()
            self._hop(req.rid, "rejected", tier="reject",
                      **({"shadow": True} if req.shadow_of is not None
                         else {}))
            raise QueueFullError(
                f"queue full ({self._pending_units}/{adm.max_queue}"
                + (" tokens)" if self.packed else ")"))

    def _shed_pass(self, arriving: Optional[_Request] = None) -> None:
        """Shed-tier sweep (caller holds the lock): drop the doomed,
        lowest-slack first, across every replica queue."""
        queued = [r for s in self._slots if s.replica
                  for q in s.replica.all_queues() for r in q
                  if not r.done()]
        victims = self.admission.shed_victims(queued, arriving=arriving)
        if not victims:
            return
        victimset = set(map(id, victims))
        for s in self._slots:
            if s.replica is None:
                continue
            for q in s.replica.all_queues():
                q[:] = [r for r in q if id(r) not in victimset]
        for r in victims:
            if r is arriving:
                if r._complete(None, LoadShedError("shed on arrival")):
                    self._hop(r.rid, "shed", arrival=True,
                              **({"shadow": True}
                                 if r.shadow_of is not None else {}))
                self.metrics.shed_total.inc()
            else:
                self._finish_locked(r, error=LoadShedError(
                    "shed while queued: overload tier, lowest deadline "
                    "slack first"))

    def _pick_slot(self, exclude: Optional[int]) -> Optional[_Slot]:
        """Least-loaded dispatchable slot (healthy first; a warming or
        draining replica is a valid queue target — it just executes later
        — but never preferred over a healthy one)."""
        def candidates(states):
            return [s for s in self._slots
                    if s.index != exclude and s.replica is not None
                    and s.replica.state in states
                    and s.replica.exit_code is None]

        for states in (("healthy",), ("warming", "draining")):
            cands = candidates(states)
            if cands:
                return min(cands, key=lambda s: s.replica.load())
        return None

    def _enqueue(self, slot: _Slot, req: _Request) -> None:
        if self.packed:
            slot.replica.pack_queue.append(req)
        else:
            slot.replica.queues[req.bucket].append(req)
        slot.metrics.requests_total.inc()
        slot.metrics.queue_depth.set(slot.replica.queued())

    # -------------------------------------------------------------- worker
    def _worker(self, rep: _Replica) -> None:
        try:
            self._warm(rep)
            while True:
                if rep.fault == "crash":  # chaos hook fires even when idle
                    raise _InjectedFault(
                        f"replica {rep.index} killed (injected)")
                if rep.fault != "hang":  # a wedged process beats no more
                    mem = getattr(rep.engine, "beat_memory", None)
                    rep.hb.beat(step=rep.batches,
                                **(mem() if mem is not None else {}))
                rewarm = False
                with self._lock:
                    if self._stop or rep.state == "ejected":
                        return
                    # snapshot the flush-age knob for the out-of-lock
                    # pack formation below — the knob is written under
                    # this lock (apply_knob), so reading it after release
                    # would race the controller (threadlint T1)
                    wait_ms = self.max_wait_ms
                    # standby -> warming (activate_replica): leave the lock
                    # and re-run the warmup probes — all compile-cache hits
                    # on a warm engine, but the GATE is the same as a
                    # relaunch's, so a cold engine could never slip through
                    rewarm = rep.state == "warming"
                    batch = None
                    if not rewarm and rep.state == "healthy":
                        batch = self._take_flushable(rep)
                    if not rewarm and batch is None:
                        # a non-healthy replica (draining/warming/standby)
                        # must NOT derive its wakeup from overdue queue
                        # ticks — _next_wakeup would return 0 and the
                        # worker would busy-spin on the router lock
                        timeout = (self._next_wakeup(rep)
                                   if rep.state == "healthy" else None)
                        self._cond.wait(timeout=min(
                            self._beat_interval,
                            timeout if timeout is not None else 3600.0))
                        continue
                    if not rewarm:
                        slot = self._slots[rep.index]
                        if not isinstance(batch, _PackIntent):
                            # a _PackIntent's requests stay QUEUED (visible
                            # to eject/shed/expiry) until the pack is
                            # formed below
                            rep.inflight = batch
                            slot.metrics.inflight.set(len(rep.inflight))
                        slot.metrics.queue_depth.set(rep.queued())
                if rewarm:
                    self._warm(rep)
                    continue
                if isinstance(batch, _PackIntent):
                    # the expensive bin-pack runs OUTSIDE the pool lock
                    pb, _ = form_packed_batch(
                        batch.requests, self.clock(), self.pack_width,
                        rep.flush_rows, self.pack_segments,
                        self._tokenizer.pad_id, wait_ms / 1e3)
                    with self._lock:
                        if self._stop or rep.state in ("ejected", "standby"):
                            # ejected (or drained to standby) mid-pack:
                            # every snapshot request was requeued onto
                            # peers (they were still queued) — abandon the
                            # formed batch
                            continue
                        # a snapshot request that VANISHED from the queue
                        # without completing was re-homed by the fleet's
                        # rollback drain (extract_queued) while the batch
                        # formed — executing it here would complete a
                        # request another pool now owns and double-count
                        # its pending slot.  Abandon; whatever is still
                        # queued rides the next pack.  (Completed corpses
                        # — shed/expired by the monitor — stay harmless:
                        # their _finish is an idempotent no-op.)
                        queued_ids = set(map(id, rep.pack_queue))
                        if any(id(r) not in queued_ids and not r.done()
                               for r in pb.requests):
                            continue
                        # reconcile: take exactly the packed requests out
                        # of the queue; anything the monitor completed
                        # meanwhile (shed/expired) executes harmlessly —
                        # its _finish is an idempotent no-op.  Leftovers
                        # never left the queue, order intact.
                        takenset = set(map(id, pb.requests))
                        rep.pack_queue = [r for r in rep.pack_queue
                                          if id(r) not in takenset]
                        rep.inflight = pb.requests
                        slot = self._slots[rep.index]
                        slot.metrics.inflight.set(len(pb.requests))
                        slot.metrics.queue_depth.set(rep.queued())
                    batch = pb
                # _execute's hang-chaos loop polls self._stop lock-free
                # by design: a wedged worker exists to SIMULATE a stuck
                # device stream, and flag writes are atomic under the
                # GIL — the monitor ejects this replica either way
                # jaxlint: disable=T1
                self._execute(rep, batch)
                with self._lock:
                    rep.inflight = []
                    rep.batches += 1
                    slot = self._slots[rep.index]
                    slot.metrics.queue_depth.set(rep.queued())
                    slot.metrics.inflight.set(0)
                    self._cond.notify_all()
        except BaseException:  # noqa: BLE001 — a dying worker must leave a
            # verdict behind: the monitor classifies the crash, ejects the
            # replica, and requeues its queued + in-flight requests onto
            # survivors.  Deliberately NO cleanup here — a SIGKILL'd
            # process would not have run any either, and one recovery path
            # (ejection) is easier to trust than two.
            rep.exit_code = 1

    def _warm(self, rep: _Replica) -> None:
        """Warmup-gated (re)integration: pre-trace every bucket shape, then
        baseline the retrace counter — only after that may dispatch see
        this replica, so a relaunch can never introduce a post-warmup
        retrace."""
        rep.hb.beat(force=True)  # the monitor's grace clock starts now
        if self._checkpoint_path and \
                getattr(rep.engine, "checkpoint_path", None) \
                != self._checkpoint_path:
            rep.engine.load_checkpoint(self._checkpoint_path)
        for seq in self.buckets:
            rep.engine.infer_ids(
                [[self._tokenizer.cls_id, self._tokenizer.sep_id]], seq,
                rows=rep.flush_rows)
            rep.hb.beat(force=True)  # a slow compile must not read as a stall
        if self.packed:
            # the packed path's ONE compiled shape; the bucket warmups
            # above stay — hedged duplicates ride the padded path and must
            # not pay (or count) a compile either
            rep.engine.warmup_packed(self.pack_width, rep.flush_rows,
                                     self.pack_segments)
            rep.hb.beat(force=True)
        rep.retrace_warm = rep.engine.metrics.retraces.value
        with self._lock:
            slot = self._slots[rep.index]
            # recovery/reintegration are recorded ONLY on a real warming ->
            # healthy transition: an incarnation ejected mid-warmup never
            # serves, and claiming its recovery would let the serve-load
            # gates pass on a pool that is actually a replica short
            if rep.state == "warming":
                rep.state = "healthy"
                if slot.ejected_at is not None:
                    self.metrics.recovery_sec.observe(
                        self.clock() - slot.ejected_at)
                    slot.ejected_at = None
                    self.metrics.reintegrations_total.inc()
            self._cond.notify_all()

    def _take_flushable(self, rep: _Replica):
        """Under the lock: expire/skip dead entries, then pop a flushable
        batch — token-budget/aged from the pack queue on the packed path,
        a full or most-overdue aged bucket otherwise (hedged duplicates
        keep the bucket path alive even when packing is on)."""
        now = self.clock()
        for q in rep.all_queues():
            keep = []
            for r in q:
                if r.done():  # hedge copy whose original already finished
                    continue
                if r.deadline is not None and now >= r.deadline:
                    self._finish_locked(r, error=DeadlineExceeded(
                        "deadline passed while queued"))
                else:
                    keep.append(r)
            q[:] = keep
        if rep.pack_queue:
            # O(queue) scans, deliberately: the queue is bounded by the
            # token-unit admission ceiling (max_queue x width tokens pool-
            # wide, ~1e3 entries/replica at short-request mixes), so the
            # sum + min cost ~tens of µs per wake — noise against the
            # multi-ms batch execution, and the expensive part (batch
            # FORMATION) already runs outside this lock via _PackIntent
            if rep.queued_tokens() >= rep.flush_tokens \
                    or (now - min(r.submitted for r in rep.pack_queue)) \
                    * 1e3 >= self.max_wait_ms:
                # snapshot only — the worker forms the batch OUTSIDE the
                # pool lock (see _PackIntent) and reconciles after
                return _PackIntent(list(rep.pack_queue))
        for b, q in rep.queues.items():
            if len(q) >= rep.flush_rows:
                return self._pop(rep, b)
        aged = [(q[0].submitted, b) for b, q in rep.queues.items() if q]
        if aged:
            oldest, b = min(aged)
            if (now - oldest) * 1e3 >= self.max_wait_ms:
                return self._pop(rep, b)
        return None

    def _pop(self, rep: _Replica, bucket: int) -> List[_Request]:
        q = rep.queues[bucket]
        batch, q[:] = q[: rep.flush_rows], q[rep.flush_rows:]
        return batch

    def _next_wakeup(self, rep: _Replica) -> Optional[float]:
        now = self.clock()
        ticks = []
        for q in rep.all_queues():
            for r in q:
                ticks.append(r.submitted + self.max_wait_ms / 1e3)
                if r.deadline is not None:
                    ticks.append(r.deadline)
        if not ticks:
            return None
        return max(0.0, min(ticks) - now)

    def _execute(self, rep: _Replica, batch) -> None:
        """Run one batch on ``rep``'s engine (outside the lock).  Chaos
        hooks fire here; any engine exception condemns the replica (its
        worker dies with the verdict, the monitor handles recovery)."""
        if rep.fault == "crash":
            raise _InjectedFault(f"replica {rep.index} killed (injected)")
        while rep.fault == "hang":
            # wedged, beats stopped: hold the in-flight batch until the
            # monitor ejects us — the stalled-replica failure shape
            if rep.state == "ejected" or self._stop:
                raise _InjectedFault(f"replica {rep.index} wedged (injected)")
            time.sleep(0.02)
        if isinstance(batch, _PackedBatch):
            return self._execute_packed(rep, batch)
        bucket = batch[0].bucket
        t0 = self.clock()
        retried = sum(1 for r in batch if r.retries)
        for r in batch:
            self.metrics.queue_wait_ms.observe((t0 - r.submitted) * 1e3)
        tr = self.tracer
        if tr.enabled:
            now = tr.now()
            oldest = max(t0 - r.submitted for r in batch)
            tr.record("queue_wait", now - oldest, now, replica=rep.index,
                      bucket=bucket, rows=len(batch), retry=retried,
                      request_ids=exemplar_ids(batch))
            for i, r in enumerate(batch):
                # a hedge loser may have been completed elsewhere AFTER
                # this batch formed — a dispatch hop recorded past its
                # terminal would read as an incomplete chain
                if not r.done():
                    self._hop(r.rid, "dispatch", replica=rep.index,
                              bucket=bucket, row=i, retry=r.retries)
        rows = rep.flush_rows
        logits = rep.engine.infer_ids([r.ids for r in batch], bucket,
                                      rows=rows,
                                      request_ids=[r.rid for r in batch])
        slot = self._slots[rep.index]
        slot.metrics.batches_total.inc()
        slot.metrics.batch_occupancy.observe(len(batch) / rows)
        slot.metrics.fill_ratio.observe(
            sum(len(r.ids) for r in batch) / float(rows * bucket))
        for i, r in enumerate(batch):
            self._finish(r, logits=logits[i], latency=True,
                         replica=rep.index)

    def _execute_packed(self, rep: _Replica, pb: _PackedBatch) -> None:
        """The packed twin of :meth:`_execute`: one fixed-shape packed
        forward serving every riding request, results scattered back by
        the batch's ``(row, slot)`` placements.  Occupancy/fill land in
        TOKEN units — a packed batch spends all its rows by construction,
        so rows would read 1.0 forever."""
        t0 = self.clock()
        retried = sum(1 for r in pb.requests if r.retries)
        for r in pb.requests:
            self.metrics.queue_wait_ms.observe((t0 - r.submitted) * 1e3)
        tr = self.tracer
        if tr.enabled:
            now = tr.now()
            oldest = max(t0 - r.submitted for r in pb.requests)
            tr.record("queue_wait", now - oldest, now, replica=rep.index,
                      bucket=self.pack_width, rows=len(pb.requests),
                      retry=retried, packed=True,
                      request_ids=exemplar_ids(pb.requests))
            for r, (row, seg) in zip(pb.requests, pb.placements):
                if r.done():  # completed elsewhere since the pack formed
                    continue
                self._hop(r.rid, "pack", replica=rep.index,
                          row=row, slot=seg)
                self._hop(r.rid, "dispatch", replica=rep.index,
                          row=row, slot=seg, packed=True,
                          retry=r.retries)
        logits = rep.engine.infer_packed(
            pb.arrays, segments=len(pb.requests),
            request_ids=[r.rid for r in pb.requests])
        slot = self._slots[rep.index]
        slot.metrics.batches_total.inc()
        slot.metrics.batch_occupancy.observe(pb.fill)
        slot.metrics.fill_ratio.observe(pb.fill)
        for r, (row, seg) in zip(pb.requests, pb.placements):
            self._finish(r, logits=logits[row, seg], latency=True,
                         replica=rep.index)

    # ------------------------------------------------------------- monitor
    def _monitor(self) -> None:
        """Health loop: GangMonitor verdicts -> ejection; plus the deadline
        sweep and the hedging scan each tick."""
        while True:
            time.sleep(self.poll_interval)
            with self._lock:
                if self._stop:
                    return
                self._sweep_expired()
                if self.hedge_ms is not None:
                    self._hedge_scan()
            verdict = self._mon.poll()
            if not verdict or verdict.get("kind") not in ("crashed",
                                                          "stalled"):
                continue
            for i in verdict.get("dead_ranks", []):
                slot = self._slots[i]
                rep = slot.replica
                if rep is None or rep.state == "ejected":
                    continue
                if verdict["kind"] == "stalled" and rep.state == "warming":
                    # warmup compiles can outlast stall_timeout (the same
                    # reason Heartbeat skips its construction beat and the
                    # GangMonitor grants a pre-first-beat grace window):
                    # beats land between buckets, but ONE bucket's XLA
                    # compile is allowed to run long.  A warming replica
                    # is not dispatch-preferred, so leniency costs
                    # nothing; a crashed warmup still ejects above.
                    continue
                self._eject(i, verdict["kind"])

    def _sweep_expired(self) -> None:
        now = self.clock()
        for s in self._slots:
            rep = s.replica
            if rep is None:
                continue
            for q in rep.all_queues():
                keep = []
                for r in q:
                    if r.done():
                        continue
                    if r.deadline is not None and now >= r.deadline:
                        self._finish_locked(r, error=DeadlineExceeded(
                            "deadline passed while queued"))
                    else:
                        keep.append(r)
                q[:] = keep

    def _hedge_scan(self) -> None:
        """Tail hedging, bounded by the deadline budget: a request queued
        past ``hedge_ms`` that still has slack gets ONE duplicate on a
        strictly less-loaded healthy replica; first completion wins.  The
        duplicate always rides the PADDED per-bucket path — a hedge exists
        to dodge a slow replica NOW, so it must not sit waiting for a pack
        to fill, and the padded bucket shapes are always warm."""
        now = self.clock()
        for s in self._slots:
            rep = s.replica
            if rep is None or rep.state == "ejected":
                continue
            for q in rep.all_queues():
                for r in q:
                    if (r.hedged or r.done()
                            or (now - r.submitted) * 1e3 < self.hedge_ms
                            or r.slack(now) <= 0):
                        continue
                    target = self._pick_slot(exclude=rep.index)
                    if target is None or \
                            target.replica.load() >= rep.load():
                        continue
                    r.hedged = True
                    target.replica.queues[r.bucket].append(r)
                    target.metrics.queue_depth.set(target.replica.queued())
                    self.metrics.hedges_total.inc()
                    self._hop(r.rid, "hedge",
                              from_replica=rep.index,
                              to_replica=target.index)
                    self._cond.notify_all()

    def _eject(self, index: int, reason: str) -> None:
        """Remove a dead/stalled replica from dispatch and move every one
        of its requests (queued AND in-flight) onto survivors within their
        remaining deadline budget."""
        with self._lock:
            slot = self._slots[index]
            rep = slot.replica
            rep.state = "ejected"
            slot.ejected_at = self.clock()
            self.metrics.ejections_total.inc()
            slot.metrics.ejections.inc()
            queued = [r for q in rep.all_queues() for r in q]
            inflight = list(rep.inflight)
            for q in rep.all_queues():
                q.clear()
            rep.inflight = []
            slot.metrics.queue_depth.set(0)
            slot.metrics.inflight.set(0)
            now = self.clock()
            for r, was_inflight in [(r, False) for r in queued] \
                    + [(r, True) for r in inflight]:
                if r.done():
                    continue
                # a hedged request whose copy already lives on a survivor
                # needs no requeue — appending it again would put the SAME
                # request twice in one queue and waste a padded row
                if r.hedged and any(
                        s.replica is not None
                        and s.replica.state != "ejected"
                        and any(r in q
                                for q in s.replica.all_queues())
                        for s in self._slots if s.index != index):
                    continue
                if r.deadline is not None and now >= r.deadline:
                    self._finish_locked(r, error=DeadlineExceeded(
                        f"deadline passed during replica {index} ejection"))
                    continue
                if was_inflight and r.retries >= self.max_retries:
                    self._finish_locked(r, error=ReplicaFailedError(
                        f"replica {index} {reason}; retry budget "
                        f"({self.max_retries}) exhausted"))
                    continue
                target = self._pick_slot(exclude=index)
                if target is None:
                    self._finish_locked(r, error=ReplicaFailedError(
                        f"replica {index} {reason}; no survivor to take "
                        "the request"))
                    continue
                if was_inflight:
                    r.retries += 1
                    self.metrics.retries_total.inc()
                    target.metrics.retries.inc()
                else:
                    self.metrics.requeued_total.inc()
                slot.metrics.requeued_out.inc()
                target.metrics.requeued_in.inc()
                self._hop(r.rid, "requeue",
                          from_replica=index, to_replica=target.index,
                          inflight=was_inflight, packed=self.packed)
                if self.packed:
                    # survivors RE-PACK the orphans: they join the
                    # target's token queue and ride its next packed batch
                    # within whatever deadline budget they have left
                    target.replica.pack_queue.append(r)
                else:
                    target.replica.queues[r.bucket].append(r)
                target.metrics.queue_depth.set(target.replica.queued())
            self._cond.notify_all()
        # crash-path telemetry: the condemned replica's spans + a metrics
        # snapshot land on disk NOW — ejection is the only exit a crashed
        # worker gets, so this is its flush (outside the lock: file I/O
        # must not serialize submitters)
        self.flush_telemetry(f"eject replica {index} ({reason})")

    # ------------------------------------------------------------ recovery
    def kill_replica(self, index: int, kind: str = "crash") -> None:
        """Chaos hook (tests, ``bench.py --serve-load``): make replica
        ``index`` die like a SIGKILL'd process (``crash``: worker dies,
        beats stop) or wedge like a stuck device stream (``hang``: worker
        holds its batch, beats stop)."""
        if kind not in ("crash", "hang"):
            raise ValueError(f"unknown fault kind {kind!r}")
        with self._lock:
            self._slots[index].replica.fault = kind
            self._cond.notify_all()

    def relaunch(self, index: int, engine=None) -> None:
        """Replace an ejected replica with a fresh incarnation.  The new
        engine loads the pool's current checkpoint and re-runs the bucket
        warmup on its worker BEFORE turning healthy (warmup-gated
        reintegration); recovery time (ejection -> healthy) lands in
        ``metrics.recovery_sec``."""
        if engine is None:
            if self.engine_factory is None:
                raise ValueError("relaunch needs an engine or a factory")
            engine = self.engine_factory(index)

        def check_slot_free() -> None:
            old = self._slots[index].replica
            if old is not None and old.state not in ("ejected",):
                raise RuntimeError(
                    f"replica {index} is {old.state}, not ejected")

        with self._lock:
            check_slot_free()
        # replica construction and the pre-install beat both touch the
        # filesystem (heartbeat dir + beat file) — they run OUTSIDE the
        # pool lock (threadlint T3) so a relaunch never serializes
        # submitters and the monitor behind disk I/O; the slot is
        # re-validated under the lock before install
        rep = self._make_replica(index, engine)
        # the dead incarnation's LAST beat is >= stall_timeout old by
        # construction; a fresh beat must land BEFORE the slot flips
        # live, or the monitor's very next poll reads the stale age
        # against a now-alive adapter and falsely ejects the newcomer
        rep.hb.beat(force=True)
        with self._lock:
            check_slot_free()
            self._slots[index].replica = rep
        self._start_worker(rep)

    def swap_checkpoint(self, path: str) -> Dict:
        """Rolling hot-swap: drain + swap one replica at a time so the pool
        keeps serving throughout.  A corrupt artifact
        (:class:`CorruptCheckpointError`) or template mismatch ROLLS BACK
        that replica (a failed load leaves the engine's params untouched)
        and aborts the rollout — a bad file must cost one replica's swap
        attempt, never the pool.  Returns a report dict."""
        report: Dict = {"path": path, "swapped": [], "rolled_back": [],
                        "skipped": []}
        for slot in self._slots:
            with self._lock:
                rep = slot.replica
                if rep is None or rep.state != "healthy":
                    report["skipped"].append(slot.index)
                    continue
                rep.state = "draining"
                self._cond.notify_all()
            # wait out the in-flight batch (new dispatch is paused; its
            # queue keeps accepting and survivors keep serving)
            with self._lock:
                while rep.inflight and rep.exit_code is None \
                        and not self._stop:
                    self._cond.wait(timeout=0.02)
                # the replica may have died or been ejected DURING the
                # drain wait (or the router may be stopping) — swapping a
                # corpse must not count as a successful rollout step
                if self._stop or rep.exit_code is not None \
                        or rep.state != "draining":
                    if rep.state == "draining" and rep.exit_code is None:
                        rep.state = "healthy"  # un-pause a stop-skipped one
                    report["skipped"].append(slot.index)
                    continue
            try:
                with self.tracer.span("swap", replica=slot.index,
                                      path=os.path.basename(path)):
                    rep.engine.load_checkpoint(path)
                self.metrics.swaps_total.inc()
                report["swapped"].append(slot.index)
            except (CorruptCheckpointError, ValueError) as e:
                self.metrics.swap_rollbacks_total.inc()
                report["rolled_back"].append(slot.index)
                report["error"] = f"{type(e).__name__}: {e}"
                with self._lock:
                    if rep.state == "draining":
                        rep.state = "healthy"
                    self._cond.notify_all()
                break
            with self._lock:
                if rep.state == "draining":
                    rep.state = "healthy"
                self._cond.notify_all()
        if report["swapped"] and not report["rolled_back"]:
            self._checkpoint_path = path  # relaunches warm onto the new one
        return report

    # ------------------------------------------------------- tuning surface
    #: the knobs the feedback control plane may actuate — ONE setter
    #: (:meth:`apply_knob`) so every write is thread-safe and every
    #: controller-side write can be funneled through the decision-recording
    #: ``_actuate`` choke point (jaxlint R13 flags any other path)
    KNOBS = ("hedge_ms", "max_wait_ms", "backpressure_at", "shed_at",
             "degrade_at", "shed_slack_ms")

    def apply_knob(self, name: str, value) -> None:
        """Set one tunable serving knob, thread-safely, effective for the
        next flush/scan (workers and the monitor read these under the
        pool lock).  Admission thresholds are validated against the
        ladder's ordering invariant — a controller bug must surface here,
        not as an unreachable tier."""
        with self._lock:
            if name == "hedge_ms":
                self.hedge_ms = None if value is None else float(value)
            elif name == "max_wait_ms":
                self.max_wait_ms = float(value)
            elif name in ("backpressure_at", "shed_at", "degrade_at"):
                adm = self.admission
                trial = {"backpressure_at": adm.backpressure_at,
                         "shed_at": adm.shed_at,
                         "degrade_at": adm.degrade_at,
                         name: (None if value is None and
                                name == "degrade_at" else int(value))}
                if not (0 <= trial["backpressure_at"] <= trial["shed_at"]
                        <= adm.max_queue):
                    raise ValueError(
                        f"knob {name}={value} breaks tier ordering: "
                        f"backpressure_at {trial['backpressure_at']} <= "
                        f"shed_at {trial['shed_at']} <= max_queue "
                        f"{adm.max_queue}")
                if trial["degrade_at"] is not None and not (
                        trial["backpressure_at"] <= trial["degrade_at"]
                        <= trial["shed_at"]):
                    raise ValueError(
                        f"knob {name}={value} breaks tier ordering: "
                        f"degrade_at {trial['degrade_at']} must sit "
                        f"between backpressure_at "
                        f"{trial['backpressure_at']} and shed_at "
                        f"{trial['shed_at']}")
                setattr(adm, name, trial[name])
            elif name == "shed_slack_ms":
                self.admission.shed_slack_ms = float(value)
            else:
                raise KeyError(f"unknown knob {name!r} (tunable: "
                               f"{self.KNOBS})")
            self._cond.notify_all()

    def knob_values(self) -> Dict:
        """Current values of every tunable knob (controller sense input +
        the exporter's ``controller`` source).  Reads under the pool lock
        — the knobs are written there (:meth:`apply_knob`), and a torn
        multi-knob snapshot would hand the controller a tier ordering no
        actuation ever installed (threadlint T1).  No caller holds the
        lock: the telemetry paths (`snapshot`, ejection flush) all run
        outside it."""
        with self._lock:
            return {"hedge_ms": self.hedge_ms,
                    "max_wait_ms": self.max_wait_ms,
                    "backpressure_at": self.admission.backpressure_at,
                    "shed_at": self.admission.shed_at,
                    "degrade_at": self.admission.degrade_at,
                    "shed_slack_ms": self.admission.shed_slack_ms}

    # -------------------------------------------------------- fleet surface
    def admission_tier(self) -> str:
        """The ladder tier an arrival would meet RIGHT NOW — the fleet
        front door consults this before submitting, so a ``degrade``-band
        arrival can be re-routed to the cheap model instead of walking
        into this pool's shed pass."""
        with self._lock:
            return self.admission.tier(self._pending_units)

    def extract_queued(self) -> List[_Request]:
        """Pull every queued (NOT in-flight) request out of this pool —
        the fleet's canary-rollback drain.  Accounting is reconciled
        (pending counts, gauges); in-flight batches finish where they are
        (their callers get the answer that was already executing).  The
        extracted requests are live futures the caller must re-home."""
        with self._lock:
            out: List[_Request] = []
            seen: set = set()  # a hedged request lives in TWO queues
            # a queued request whose twin is IN FLIGHT (a hedged
            # duplicate racing its original) must not be re-homed: this
            # pool is about to complete it, and handing it to another
            # pool would charge two pending slots for one completion
            inflight_ids = {id(r) for s in self._slots if s.replica
                            for r in s.replica.inflight}
            for s in self._slots:
                rep = s.replica
                if rep is None:
                    continue
                for q in rep.all_queues():
                    out += [r for r in q if not r.done()
                            and id(r) not in seen
                            and id(r) not in inflight_ids]
                    seen.update(map(id, q))
                    q.clear()
                s.metrics.queue_depth.set(0)
            for r in out:
                self._pending -= 1
                self._pending_tokens -= len(r.ids)
            self.metrics.queue_depth.set(self._pending)
            self._cond.notify_all()
            return out

    def adopt(self, req: _Request) -> int:
        """Enqueue an ALREADY-ADMITTED request (a fleet re-home: canary
        rollback drains the candidate's queue into the primary pool) —
        deliberately bypassing the admission ladder, because a rollback
        must never turn accepted work into rejections.  Returns the slot
        index; raises :class:`ReplicaFailedError` when no replica can
        take it."""
        with self._lock:
            if self._stop or not self._started:
                raise RuntimeError("router is not running (call start())")
            slot = self._pick_slot(exclude=None)
            if slot is None:
                raise ReplicaFailedError(
                    "no replica available to adopt the request")
            self._enqueue(slot, req)
            self._pending += 1
            self._pending_tokens += len(req.ids)
            self.metrics.requests_total.inc()
            self.metrics.queue_depth.set(self._pending)
            self._cond.notify_all()
            return slot.index

    def deactivate_replica(self, index: Optional[int] = None) -> int:
        """Drain one healthy replica to a WARM STANDBY (control-plane
        scale-down): its queued requests move to peers within their
        deadline budgets (graceful — no retry is charged), its worker
        parks (still beating, so the monitor keeps seeing it alive), and
        its engine keeps every compiled cache, so
        :meth:`activate_replica`'s warmup-gated return is all cache hits —
        zero post-warmup retraces by construction.  ``index=None`` picks
        the least-loaded healthy replica.  Refuses to drain the last
        dispatchable replica.  Returns the drained slot index."""
        with self._lock:
            healthy = [s for s in self._slots if s.replica is not None
                       and s.replica.state == "healthy"
                       and s.replica.exit_code is None]
            dispatchable = [s for s in self._slots if s.replica is not None
                            and s.replica.state in ("healthy", "draining")
                            and s.replica.exit_code is None]
            if index is None:
                cands = sorted(healthy, key=lambda s: s.replica.load())
                if not cands:
                    raise RuntimeError("no healthy replica to deactivate")
                slot = cands[0]
            else:
                slot = self._slots[index]
                if slot.replica is None \
                        or slot.replica.state != "healthy":
                    raise RuntimeError(
                        f"replica {index} is "
                        f"{slot.replica.state if slot.replica else 'empty'}"
                        ", not healthy")
            if len(dispatchable) <= 1:
                raise RuntimeError("refusing to drain the last "
                                   "dispatchable replica")
            rep = slot.replica
            rep.state = "standby"
            self.metrics.scale_downs_total.inc()
            # queued work moves to peers NOW (the standby executes
            # nothing); in-flight work finishes on this worker first —
            # the state flip only stops NEW dispatch
            queued = [r for q in rep.all_queues() for r in q]
            for q in rep.all_queues():
                q.clear()
            slot.metrics.queue_depth.set(0)
            now = self.clock()
            for r in queued:
                if r.done():
                    continue
                if r.deadline is not None and now >= r.deadline:
                    self._finish_locked(r, error=DeadlineExceeded(
                        "deadline passed while queued"))
                    continue
                target = self._pick_slot(exclude=slot.index)
                if target is None:  # cannot happen (dispatchable > 1),
                    rep.state = "healthy"  # but never strand work on a bug
                    raise RuntimeError("no peer to absorb the drained "
                                       "queue")
                self.metrics.requeued_total.inc()
                slot.metrics.requeued_out.inc()
                target.metrics.requeued_in.inc()
                self._hop(r.rid, "requeue",
                          from_replica=slot.index,
                          to_replica=target.index, standby=True,
                          inflight=False, packed=self.packed)
                if self.packed:
                    target.replica.pack_queue.append(r)
                else:
                    target.replica.queues[r.bucket].append(r)
                target.metrics.queue_depth.set(target.replica.queued())
            self._cond.notify_all()
            return slot.index

    def activate_replica(self, index: Optional[int] = None) -> int:
        """Bring a warm standby back into dispatch through the SAME
        warmup gate a relaunch uses: standby -> warming (the worker
        re-runs every bucket probe — compile-cache hits on the warm
        engine) -> healthy.  If the pool's checkpoint advanced while the
        replica was parked (rolling swap), the warmup reloads it first.
        ``index=None`` picks the first standby.  Returns the slot index."""
        with self._lock:
            if index is None:
                standbys = [s for s in self._slots if s.replica is not None
                            and s.replica.state == "standby"]
                if not standbys:
                    raise RuntimeError("no standby replica to activate")
                slot = standbys[0]
            else:
                slot = self._slots[index]
                if slot.replica is None \
                        or slot.replica.state != "standby":
                    raise RuntimeError(
                        f"replica {index} is "
                        f"{slot.replica.state if slot.replica else 'empty'}"
                        ", not standby")
            slot.replica.state = "warming"
            self.metrics.scale_ups_total.inc()
            self._cond.notify_all()
            return slot.index

    @property
    def active_count(self) -> int:
        """Replicas currently dispatchable or becoming so (healthy /
        draining / warming) — the control plane's capacity signal."""
        return sum(1 for s in self._slots if s.replica is not None
                   and s.replica.state in ("healthy", "draining", "warming")
                   and s.replica.exit_code is None)

    @property
    def standby_count(self) -> int:
        return sum(1 for s in self._slots if s.replica is not None
                   and s.replica.state == "standby")

    # ----------------------------------------------------------- reporting
    def flush_telemetry(self, event: str = "") -> None:
        """Spans + a full metrics snapshot to disk (``telemetry_dir``),
        best-effort: called from the ejection path and from ``stop`` so a
        pool that dies mid-storm still leaves its evidence.  Telemetry
        flushing must never take the router down with it."""
        try:
            self.tracer.flush()
        except OSError:
            pass
        try:
            _save_json({"event": event,
                        "wall_time": time.time(),
                        **self.snapshot()},
                       os.path.join(self.telemetry_dir,
                                    "router_snapshot.json"))
        except OSError:
            pass

    def control_snapshot(self) -> Dict:
        """The control plane's per-tick sense input: counters, gauges,
        knobs and ONE latency percentile — none of the per-replica
        histogram-window copies :meth:`snapshot` pays, so a sub-second
        control interval never steals meaningful time from the serving
        workers it exists to help."""
        m = self.metrics
        return {
            "router": {
                "requests_total": m.requests_total.value,
                "deadline_expired_total": m.deadline_expired_total.value,
                "queue_depth": m.queue_depth.value,
                "admission": {
                    "backpressure_waits":
                        m.backpressure_waits_total.value,
                    "shed": m.shed_total.value,
                    "rejected": m.rejected_total.value,
                },
                "request_latency_ms":
                    {"p99": m.request_latency_ms.percentile(99)},
            },
            "knobs": self.knob_values(),
            "active": self.active_count,
            "standby": self.standby_count,
        }

    @property
    def tokenizer(self):
        """The pool's shared tokenizer (every replica encodes identically
        — the fleet front door encodes once through this)."""
        return self._tokenizer

    def engine(self, index: int = 0):
        """The live engine in slot ``index`` (current incarnation)."""
        rep = self._slots[index].replica
        if rep is None:
            raise KeyError(f"slot {index} has no replica")
        return rep.engine

    @property
    def states(self) -> Dict[int, str]:
        return {s.index: (s.replica.state if s.replica else "empty")
                for s in self._slots}

    @property
    def retraces_post_warmup(self) -> int:
        """Pool-wide retraces since each LIVE replica's warmup baseline —
        the serve-load smoke's zero-retrace gate (ejected incarnations are
        out of the pool and out of the count)."""
        return sum(s.replica.retraces_post_warmup for s in self._slots
                   if s.replica and s.replica.state != "ejected")

    def snapshot(self) -> Dict:
        """Router + per-replica metrics (incl. each replica's device-slice
        HBM state), JSON-ready (the ``results/serve_load_smoke.json``
        building block and the live exporter's ``serve`` source)."""
        def replica_memory(s: _Slot):
            fn = getattr(s.replica.engine, "memory_snapshot", None) \
                if s.replica else None
            return fn() if fn is not None else None

        return {
            "router": self.metrics.snapshot(),
            "knobs": self.knob_values(),
            "active": self.active_count,
            "standby": self.standby_count,
            "replicas": {
                str(s.index): {
                    "state": s.replica.state if s.replica else "empty",
                    "batches": s.replica.batches if s.replica else 0,
                    "retraces_post_warmup":
                        s.replica.retraces_post_warmup if s.replica else 0,
                    **s.metrics.snapshot(),
                    "engine": (s.replica.engine.metrics.snapshot()
                               if s.replica else None),
                    "memory": replica_memory(s),
                }
                for s in self._slots
            },
        }
