"""Trace-driven load replay: recorded traffic, re-playable at will.

Capacity planning for millions-of-users traffic needs *reproducible*
storms: the same arrival process, replayed at 1x/5x/20x speed, reshaped
into the diurnal ramps and flash crowds production actually sees, against
any pool configuration — so a controller-vs-static comparison is a seeded
experiment, not an anecdote.

The recording already exists: every admitted request's hop chain
(:mod:`pdnlp_tpu.obs.request`) carries its admission timestamp, and since
the control-plane PR the ``admit`` hop also carries ``tokens`` and
``deadline_ms`` — a flushed trace file IS a load recording.  This module
closes the loop:

- :func:`arrivals_from_trace` — reconstruct the arrival process
  (relative timestamp, token length, deadline) from a span stream's hop
  chains;
- :func:`synth_arrivals` — a seeded Poisson arrival process with a mixed
  length/deadline distribution, for recording-free use (and for seeding
  the recording storm itself);
- :func:`shape_arrivals` — deterministic time-warps over a base schedule:
  ``steady`` (pure speedup), ``diurnal`` (a low -> peak -> low rate ramp,
  the daily traffic curve compressed), ``flash`` (a sustained burst at
  ``flash_factor`` x the base rate mid-replay — the thundering-herd
  shape).  Pure functions of their inputs: same trace + same shape/speed
  -> identical schedule, bit for bit;
- :func:`replay` — drive a schedule through any ``submit_ids``-shaped
  callable open-loop (arrivals happen when the schedule says, whether or
  not the pool is keeping up — that is the point), collecting per-request
  outcomes and the goodput/latency numbers the ``bench.py --replay``
  frontier gate compares.

Everything is stdlib + injectable clocks; nothing here imports jax.
"""
from __future__ import annotations

import math
import random
import time
from typing import Callable, Dict, List, Optional, Sequence

from pdnlp_tpu.obs.request import chains


class Arrival:
    """One request of a replayable schedule: WHEN (seconds since the
    schedule's start), how BIG (real tokens), and how URGENT."""

    __slots__ = ("t", "tokens", "deadline_ms")

    def __init__(self, t: float, tokens: int,
                 deadline_ms: Optional[float] = None):
        self.t = float(t)
        self.tokens = int(tokens)
        self.deadline_ms = (float(deadline_ms)
                            if deadline_ms is not None else None)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Arrival(t={self.t:.6f}, tokens={self.tokens}, "
                f"deadline_ms={self.deadline_ms})")

    def as_tuple(self) -> tuple:
        return (round(self.t, 9), self.tokens, self.deadline_ms)


def arrivals_from_trace(records: Sequence[Dict]) -> List[Arrival]:
    """The arrival process a span stream recorded: one :class:`Arrival`
    per ``admit`` hop (relative to the first admission, time-ordered).
    Chains without a ``tokens`` attr (pre-control-plane traces) fall back
    to the admit hop's ``bucket`` width; chains with neither are skipped
    — a replay must never invent work that was not recorded."""
    out: List[Arrival] = []
    for chain in chains(records).values():
        first = chain[0]
        attrs = dict(first.get("attrs") or {})
        if attrs.get("hop") != "admit":
            continue
        tokens = attrs.get("tokens", attrs.get("bucket"))
        if tokens is None:
            continue
        out.append(Arrival(float(first.get("t0", 0.0)), int(tokens),
                           attrs.get("deadline_ms")))
    out.sort(key=lambda a: a.t)
    if out:
        t0 = out[0].t
        for a in out:
            a.t -= t0
    return out


def synth_arrivals(n: int, qps: float, *,
                   lengths: Sequence[int] = (6, 10, 16, 22, 28),
                   deadline_ms: Optional[float] = 8000.0,
                   seed: int = 0) -> List[Arrival]:
    """A seeded Poisson arrival process (exponential gaps at ``qps``) with
    lengths cycling the given mix — the recording-free schedule source."""
    rng = random.Random(seed)
    t = 0.0
    out: List[Arrival] = []
    for i in range(int(n)):
        out.append(Arrival(t, lengths[i % len(lengths)], deadline_ms))
        t += rng.expovariate(qps)
    return out


#: the supported traffic shapes (rate multiplier over replay progress)
SHAPES = ("steady", "diurnal", "flash")


def _rate_multiplier(shape: str, u: float, flash_factor: float,
                     diurnal_low: float, diurnal_peak: float) -> float:
    """Instantaneous arrival-rate multiplier at progress ``u`` in [0, 1)."""
    if shape == "steady":
        return 1.0
    if shape == "diurnal":
        # low -> peak -> low over the replay: half a sine period riding on
        # the trough rate — the daily curve compressed into one run
        return diurnal_low + (diurnal_peak - diurnal_low) \
            * math.sin(math.pi * u)
    if shape == "flash":
        # a sustained mid-replay burst: the thundering herd arrives at
        # flash_factor x the base rate, then leaves as fast as it came
        return flash_factor if 0.45 <= u < 0.65 else 1.0
    raise ValueError(f"unknown shape {shape!r} (supported: {SHAPES})")


def shape_arrivals(base: Sequence[Arrival], shape: str, *,
                   speed: float = 1.0, flash_factor: float = 8.0,
                   diurnal_low: float = 0.35, diurnal_peak: float = 1.8
                   ) -> List[Arrival]:
    """Deterministic time-warp of a base schedule: each inter-arrival gap
    is divided by ``speed x rate_multiplier(progress)``, so the SAME
    requests (lengths, deadlines, order) arrive on a reshaped clock.
    Progress is indexed, not timed — the warp is a pure function of the
    base schedule, which is what makes replays reproducible."""
    if speed <= 0:
        raise ValueError(f"speed must be positive, got {speed}")
    out: List[Arrival] = []
    t = 0.0
    prev = None
    n = max(1, len(base))
    for i, a in enumerate(base):
        if prev is not None:
            mult = _rate_multiplier(shape, i / n, flash_factor,
                                    diurnal_low, diurnal_peak)
            t += (a.t - prev) / (speed * mult)
        prev = a.t
        out.append(Arrival(t, a.tokens, a.deadline_ms))
    return out


def ids_for(arrival: Arrival, index: int, *, cls_id: int = 2,
            sep_id: int = 3, vocab: int = 200, base_id: int = 5
            ) -> List[int]:
    """Deterministic token ids for one replayed arrival: the recorded
    LENGTH is what shapes serving (bucketing, packing, fill); the ids only
    need to be valid and reproducible.  ``[CLS] body... [SEP]`` framed,
    body derived from the arrival index."""
    body = max(0, arrival.tokens - 2)
    return [cls_id] + [base_id + ((index * 31 + j) % vocab)
                       for j in range(body)] + [sep_id]


class ReplayReport:
    """One replay run's outcome accounting (JSON-ready via
    :meth:`as_dict`)."""

    def __init__(self) -> None:
        self.submitted = 0
        self.ok = 0
        self.deadline = 0
        self.shed = 0
        self.rejected = 0
        self.lost = 0
        self.tokens_ok = 0
        self.elapsed_s = 0.0
        self.max_lag_s = 0.0   # worst pacing slip (loaded host diagnostics)

    @property
    def goodput_tokens_per_s(self) -> float:
        return self.tokens_ok / self.elapsed_s if self.elapsed_s else 0.0

    def as_dict(self) -> Dict:
        return {
            "submitted": self.submitted, "ok": self.ok,
            "deadline": self.deadline, "shed": self.shed,
            "rejected": self.rejected, "lost": self.lost,
            "tokens_ok": self.tokens_ok,
            "elapsed_s": round(self.elapsed_s, 3),
            "goodput_tokens_per_s": round(self.goodput_tokens_per_s, 1),
            "max_lag_s": round(self.max_lag_s, 3),
        }


def replay(submit_ids: Callable, schedule: Sequence[Arrival], *,
           make_ids: Callable[[Arrival, int], List[int]] = ids_for,
           result_timeout: float = 120.0,
           clock: Callable[[], float] = time.monotonic,
           sleep: Callable[[float], None] = time.sleep,
           on_tick: Optional[Callable[[int], None]] = None
           ) -> ReplayReport:
    """Drive a schedule through ``submit_ids(ids, deadline_ms=...)``
    open-loop: each arrival is submitted at its scheduled offset (pacing
    slips on a loaded host are measured into ``max_lag_s``, never
    silently absorbed), futures are resolved at the end, and the report
    carries the outcome split + goodput.  ``on_tick(i)`` (optional) runs
    before arrival ``i`` — the bench's kill/injection hook."""
    from pdnlp_tpu.serve.batcher import (
        DeadlineExceeded, LoadShedError, QueueFullError,
    )

    rep = ReplayReport()
    futs = []
    t0 = clock()
    for i, a in enumerate(schedule):
        if on_tick is not None:
            on_tick(i)
        due = t0 + a.t
        now = clock()
        if now < due:
            sleep(due - now)
        else:
            rep.max_lag_s = max(rep.max_lag_s, now - due)
        rep.submitted += 1
        try:
            futs.append((a, submit_ids(make_ids(a, i),
                                       deadline_ms=a.deadline_ms)))
        except LoadShedError:
            rep.shed += 1
        except QueueFullError:
            rep.rejected += 1
    for a, f in futs:
        try:
            f.result(timeout=result_timeout)
            rep.ok += 1
            rep.tokens_ok += a.tokens
        except DeadlineExceeded:
            rep.deadline += 1
        except LoadShedError:
            rep.shed += 1
        except Exception:  # noqa: BLE001 — replica error/timeout = LOST
            rep.lost += 1
    rep.elapsed_s = clock() - t0
    return rep
