"""Paged KV memory: page allocator + refcounted cross-request prefix index.

The slot cache (PR 14) charged every stream a full ``max_len`` stripe and
stored two identical system prompts twice.  This module is the memory
half of the paged rebase (the math half is ``models.decoder``'s
``paged_*`` programs; the serving half is ``serve.decode``'s
``PagedDecodeEngine``):

- **pages**: K/V storage is ``[L, n_pages, page_sz, N, D]``; a stream
  holds pages for the positions it actually uses (``ceil((prompt +
  max_new) / page_sz)``, reserved IN FULL at claim time — no mid-decode
  page faults, no preemption machinery, and the capacity math stays
  deterministic), mapped through a per-stream page table the decode step
  gathers through.
- **:class:`PageAllocator`**: the free-list + refcount ledger.  Every
  page has one refcount; a stream's claim increments it, completion/kill
  decrements it, and a page returns to the free list exactly when its
  count reaches zero.  Per-owner accounting makes :meth:`leak_check` a
  real audit (the chaos tests and the bench storm call it after drain),
  and exhaustion is a LOUD :class:`KVPagesExhausted` with the page math
  — never an OOM three layers deep.
- **:class:`PrefixIndex`**: page-granularity prefix sharing.  Every FULL
  page of a prefilled prompt registers under the exact token tuple it
  covers (token-tuple keys, so hash collisions cannot alias two
  prompts), and the whole prompt registers as a FULL entry carrying the
  first generated token.  A later identical prompt is a **full hit**:
  map the pages at refcount+1, emit the stored first token, skip prefill
  entirely.  A shared-prefix prompt is a **partial hit**: map the
  matching full pages and run only the divergent suffix
  (``decoder.paged_chunk_step``).  Copy-on-write: a full hit whose last
  page is partial copies THAT page before the stream writes into it
  (``decoder.copy_pages``); full pages are immutable once written, so
  they share without copying.
- **eviction**: the index holds its own reference on every registered
  page, so a "cached" prompt's pages survive the stream that computed
  them — that IS the prefix cache.  When an allocation falls short the
  allocator asks the index (its ``reclaimer``) to drop least-recently-
  used entries until enough pages fall free; entries whose pages live
  streams still hold can be dropped too (they just stop being
  shareable).  Evictions are counted and surfaced, never silent.

``snapshot()`` blocks ride ``DecodeEngine.kv_snapshot`` ->
``router.snapshot()``/``control_snapshot()`` -> the Prometheus exporter,
so page occupancy, free-list depth, prefix-hit rate and copy-on-write
counts are one scrape away.
"""
from __future__ import annotations

import threading
from collections import Counter, OrderedDict, deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from pdnlp_tpu.obs.memory import KVBudgetExceeded

#: owner key for references the prefix index itself holds
INDEX_OWNER = "__prefix_index__"

#: suffix marking a stream's DRAFT-side page references (speculative
#: decoding).  Two-owner custody: on the drafter engine, pages wholly
#: beyond the committed length are held by ``draft_owner(owner)`` while
#: the drafter writes tentative K/V into them; each verify round
#: ``transfer``\ s boundary-crossed pages back to the stream owner
#: (commit), and a rejection simply leaves them under the draft owner to
#: be overwritten in place next round.  ``detach`` releases both owners,
#: so drained-allocator audits (and leaklint L1, which recognises
#: ``transfer`` as a releaser) stay clean.
DRAFT_SUFFIX = "#draft"


def draft_owner(owner: str) -> str:
    """Owner key for a stream's draft-side (uncommitted) page refs."""
    return owner + DRAFT_SUFFIX


#: suffix marking pages staged for a cross-engine KV handoff
#: (disaggregated prefill -> decode).  The prefill engine moves a
#: finished stream's pages from the stream owner to
#: ``handoff_owner(owner)`` the moment the payload is exported; from
#: that point the stream no longer "lives" on the prefill engine (its
#: slot and table row are reusable) but the pages stay pinned until the
#: decode side acknowledges the import — then the staged owner is
#: released in one sweep.  A dispatch failure releases the SAME staged
#: owner, so there is exactly one discharge point per outcome and
#: ``leak_check`` reconciles to zero on both allocators.
HANDOFF_SUFFIX = "#handoff"


def handoff_owner(owner: str) -> str:
    """Owner key for a stream's staged (in-flight handoff) page refs."""
    return owner + HANDOFF_SUFFIX


def stage_handoff(allocator: "PageAllocator", pages: Sequence[int],
                  from_owner: str) -> str:
    """Re-ledger ``from_owner``'s pages onto its handoff staging owner
    and return that owner key.  This is the custody acquire of a KV
    handoff: the caller now OWES a ``release_owner`` (success ack or
    dispatch failure) on the returned key — leaklint L1 tracks the
    obligation (``kv-pages`` spec, ``stage_handoff`` in ``funcs``), so a
    path that exports a payload and forgets the staged pages is a lint
    finding, not a slow leak."""
    staged = handoff_owner(from_owner)
    allocator.transfer(pages, from_owner, staged)
    return staged


class KVPagesExhausted(KVBudgetExceeded):
    """A page allocation could not be satisfied even after index
    eviction — the paged engine's loud refusal, in page units."""


def pages_needed(positions: int, page_sz: int) -> int:
    """Logical pages backing ``positions`` KV positions (ceil)."""
    return -(-int(positions) // int(page_sz))


class PageAllocator:
    """Free-list page allocator with refcounts and per-owner accounting.

    Thread-safe: the decode worker allocates/releases while snapshot
    threads read.  ``reclaimer`` (installed by the engine) is called with
    the shortfall when :meth:`alloc` comes up short — the prefix index's
    LRU eviction hook — and the allocation retries once before raising
    :class:`KVPagesExhausted`."""

    def __init__(self, n_pages: int, page_sz: int, page_bytes: int = 0):
        self.n_pages = int(n_pages)
        self.page_sz = int(page_sz)
        self.page_bytes = int(page_bytes)
        self._free: deque = deque(range(self.n_pages))
        self._ref = [0] * self.n_pages
        self._owned: Dict[str, Counter] = {}
        self._lock = threading.Lock()
        self.reclaimer: Optional[Callable[[int], int]] = None
        # counters (ints under the lock; snapshot reads them JSON-ready)
        self.cow_copies = 0
        self.evictions = 0
        self.alloc_failures = 0

    # ------------------------------------------------------------- internal
    def _incref_locked(self, pages: Sequence[int], owner: str) -> None:
        owned = self._owned.setdefault(owner, Counter())
        for p in pages:
            self._ref[p] += 1
            owned[p] += 1

    def _decref_locked(self, pages: Sequence[int], owner: str) -> int:
        freed = 0
        owned = self._owned.get(owner)
        for p in pages:
            if owned is None or owned[p] <= 0:
                raise AssertionError(
                    f"decref of page {p} not held by owner {owner!r}")
            owned[p] -= 1
            if owned[p] == 0:
                del owned[p]
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(p)
                freed += 1
        if owned is not None and not owned:
            del self._owned[owner]
        return freed

    # -------------------------------------------------------------- surface
    def alloc(self, n: int, owner: str) -> List[int]:
        """Claim ``n`` fresh pages for ``owner`` (refcount 1 each).  When
        the free list is short the reclaimer (prefix-index eviction) runs
        once; still short -> :class:`KVPagesExhausted` with the math."""
        n = int(n)
        if n == 0:
            return []
        with self._lock:
            short = n - len(self._free)
        if short > 0 and self.reclaimer is not None:
            self.reclaimer(short)
        with self._lock:
            if n > len(self._free):
                self.alloc_failures += 1
                raise KVPagesExhausted(
                    f"need {n} KV pages but only {len(self._free)} of "
                    f"{self.n_pages} are free "
                    f"({self.page_bytes * n / 2**20:.2f} MB requested "
                    "under --kv_hbm_mb) — streams will retry as pages "
                    "drain, or raise the budget")
            pages = [self._free.popleft() for _ in range(n)]
            self._incref_locked(pages, owner)
            # alloc hands out refcount-1 pages; _incref pushed 0 -> 1
            return pages

    def share(self, pages: Sequence[int], owner: str) -> None:
        """Add ``owner``'s reference to already-live pages (prefix hit:
        a new stream maps shared pages at refcount+1)."""
        with self._lock:
            for p in pages:
                if self._ref[p] <= 0:
                    raise AssertionError(
                        f"share of free page {p} (refcount 0)")
            self._incref_locked(pages, owner)

    def release(self, pages: Sequence[int], owner: str) -> int:
        """Drop ``owner``'s reference on ``pages``; returns how many fell
        free (refcount reached zero -> back on the free list)."""
        with self._lock:
            return self._decref_locked(pages, owner)

    def release_if_idle(self, pages: Sequence[int],
                        owner: str) -> Optional[int]:
        """Drop one ``owner`` reference per page — but only when at
        least one of ``pages`` is held by ``owner`` ALONE (its whole
        refcount is ``owner``'s): releasing then makes progress toward
        freeing.  Returns pages freed, or ``None`` (nothing released)
        when every page is also mapped by someone else.  The prefix
        index's eviction uses this to skip entries whose pages are all
        still mapped by live streams — dropping those frees nothing and
        only destroys shareability.  Atomic under the allocator lock, so
        a concurrent stream release can't slip between the check and the
        decref."""
        with self._lock:
            owned = self._owned.get(owner)
            if owned is None:
                return None
            if not any(owned.get(p, 0) > 0
                       and self._ref[p] == owned.get(p, 0)
                       for p in pages):
                return None
            return self._decref_locked(list(pages), owner)

    def release_owner(self, owner: str) -> int:
        """Drop EVERY reference ``owner`` holds (stream completion/kill
        path — also the stop()-time sweep)."""
        with self._lock:
            owned = self._owned.get(owner)
            if not owned:
                return 0
            pages = [p for p, c in owned.items() for _ in range(c)]
            return self._decref_locked(pages, owner)

    def transfer(self, pages: Sequence[int], from_owner: str,
                 to_owner: str) -> None:
        """Re-ledger one ``from_owner`` reference per page (with
        multiplicity) onto ``to_owner``, atomically.  Total refcounts
        never move, so no page can transit the free list mid-handoff —
        the blip a ``share``-then-``release`` pair would open if the
        source dropped to refcount 0 between the calls.  This is the
        sanctioned ownership-handoff idiom (the lifecycle lint's L1
        recognizes it as a release on ``from_owner``'s side).  The
        whole batch is validated before any page moves: raises
        :class:`AssertionError` (and changes nothing) when
        ``from_owner`` does not hold every requested page."""
        with self._lock:
            if from_owner == to_owner:
                return
            need = Counter(int(p) for p in pages)
            if not need:
                return
            owned = self._owned.get(from_owner)
            for p, c in need.items():
                held = owned.get(p, 0) if owned else 0
                if held < c:
                    raise AssertionError(
                        f"transfer of page {p} x{c} not held by owner "
                        f"{from_owner!r} (holds {held})")
            dst = self._owned.setdefault(to_owner, Counter())
            for p, c in need.items():
                owned[p] -= c
                if owned[p] == 0:
                    del owned[p]
                dst[p] += c
            if not owned:
                del self._owned[from_owner]

    # ------------------------------------------------------------- metering
    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        with self._lock:
            return self.n_pages - len(self._free)

    def owners(self) -> List[str]:
        with self._lock:
            return list(self._owned)

    def count_cow(self, n: int = 1) -> None:
        with self._lock:
            self.cow_copies += int(n)

    def count_evictions(self, n: int = 1) -> None:
        with self._lock:
            self.evictions += int(n)

    def leak_check(self) -> Dict:
        """Audit the ledger: every page's refcount must equal the sum of
        owner holds, free pages must have refcount 0, and used + free
        must cover the pool.  ``leaked_pages`` counts pages that are
        unreachable (nonzero refcount with NO owner holding them) —
        after a drained storm releases every stream and the index is
        cleared, it must be 0.  Called by the chaos tests and the bench
        storm gate."""
        with self._lock:
            held = Counter()
            for owned in self._owned.values():
                held.update(owned)
            free_set = set(self._free)
            mismatched = [p for p in range(self.n_pages)
                          if self._ref[p] != held.get(p, 0)]
            free_referenced = [p for p in free_set if self._ref[p] != 0]
            leaked = [p for p in range(self.n_pages)
                      if self._ref[p] > 0 and held.get(p, 0) == 0]
            double_free = len(self._free) != len(free_set)
            unaccounted = [p for p in range(self.n_pages)
                           if self._ref[p] == 0 and p not in free_set]
            ok = not (mismatched or free_referenced or leaked
                      or double_free or unaccounted)
            return {
                "ok": ok,
                "leaked_pages": len(leaked) + len(unaccounted),
                "refcount_mismatches": len(mismatched),
                "free_but_referenced": len(free_referenced),
                "double_free": double_free,
                "owners": len(self._owned),
                "free": len(free_set),
                "total": self.n_pages,
            }

    def snapshot(self) -> Dict:
        with self._lock:
            free = len(self._free)
            used = self.n_pages - free
            return {
                "total_pages": self.n_pages,
                "page_sz": self.page_sz,
                "page_bytes": self.page_bytes,
                "pages_live": used,
                "free_depth": free,
                "page_occupancy": (used / self.n_pages
                                   if self.n_pages else 0.0),
                "owners": len(self._owned),
                "cow_copies": self.cow_copies,
                "evictions": self.evictions,
                "alloc_failures": self.alloc_failures,
            }


class PrefixHit:
    """One lookup result: ``kind`` in {"full", "partial", "miss"};
    ``pages`` = the shareable physical pages in logical order (full
    pages only for partial hits; ALL prompt pages, including a trailing
    partial page, for full hits); ``first_token`` = the stored first
    generated token (full hits only)."""

    __slots__ = ("kind", "pages", "first_token")

    def __init__(self, kind: str, pages: Tuple[int, ...] = (),
                 first_token: Optional[int] = None):
        self.kind = kind
        self.pages = tuple(pages)
        self.first_token = first_token


class PrefixIndex:
    """Token-prefix -> shared-pages index at page granularity.

    Entries are keyed by the EXACT token tuple they cover (``("chain",
    tokens[:k * page_sz])`` for full page k-1; ``("full", tokens)`` for
    a whole prefilled prompt), so two prompts can never alias.  The
    index holds one allocator reference per entry per page (owner
    :data:`INDEX_OWNER`); :meth:`evict` drops LRU entries and returns
    how many pages actually fell free."""

    def __init__(self, allocator: PageAllocator, page_sz: int, *,
                 max_entries: int = 4096):
        self.alloc = allocator
        self.page_sz = int(page_sz)
        self.max_entries = int(max_entries)
        self._lru: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits_full = 0
        self.hits_partial = 0
        self.misses = 0

    # -------------------------------------------------------------- lookup
    def lookup(self, tokens: Sequence[int], *,
               count: bool = True) -> PrefixHit:
        """Best shareable prefix for ``tokens``: a full-prompt entry
        wins outright; otherwise walk the page chain from page 0 while
        entries match.  ``count=False`` is the admission-time PEEK (the
        ``admit`` hop's ``prefix_hit`` attr) — no LRU movement, no hit
        accounting, so the authoritative attach-time lookup stays the
        only one that counts."""
        toks = tuple(int(t) for t in tokens)
        ps = self.page_sz
        with self._lock:
            full = self._lru.get(("full", toks))
            if full is not None:
                if count:
                    self._lru.move_to_end(("full", toks))
                    for k in range(1, len(toks) // ps + 1):
                        key = ("chain", toks[:k * ps])
                        if key in self._lru:
                            self._lru.move_to_end(key)
                    self.hits_full += 1
                return PrefixHit("full", full[0], full[1])
            pages: List[int] = []
            for k in range(1, len(toks) // ps + 1):
                entry = self._lru.get(("chain", toks[:k * ps]))
                if entry is None:
                    break
                pages.append(entry[0][0])
                if count:
                    self._lru.move_to_end(("chain", toks[:k * ps]))
            if count:
                if pages:
                    self.hits_partial += 1
                else:
                    self.misses += 1
            return PrefixHit("partial" if pages else "miss", pages)

    # ------------------------------------------------------------ register
    def register(self, tokens: Sequence[int], pages: Sequence[int],
                 first_token: Optional[int] = None) -> None:
        """Index a freshly prefilled prompt: one chain entry per FULL
        page not already indexed, plus (when ``first_token`` is given) a
        full-prompt entry over ALL the prompt's pages.  The index takes
        its own allocator reference on every page it records, so the
        entries outlive the stream — that reference is what the LRU
        eviction later releases."""
        toks = tuple(int(t) for t in tokens)
        ps = self.page_sz
        with self._lock:
            for k in range(1, len(toks) // ps + 1):
                key = ("chain", toks[:k * ps])
                if key not in self._lru:
                    page = int(pages[k - 1])
                    self.alloc.share([page], INDEX_OWNER)
                    self._lru[key] = ((page,), None)
                self._lru.move_to_end(key)
            if first_token is not None:
                key = ("full", toks)
                if key not in self._lru:
                    held = tuple(int(p) for p in pages)
                    self.alloc.share(held, INDEX_OWNER)
                    self._lru[key] = (held, int(first_token))
                self._lru.move_to_end(key)
            over = len(self._lru) - self.max_entries
        if over > 0:
            self.evict(0, entries=over)

    # ------------------------------------------------------------- evict
    def evict(self, need_pages: int, entries: int = 0) -> int:
        """Drop least-recently-used entries until ``need_pages`` pages
        fell free (or ``entries`` entries dropped, when given); returns
        pages actually freed.  The pages-driven path SKIPS entries whose
        pages are all still mapped by live streams (rotating them to
        MRU): dropping those releases the INDEX references only — the
        pages stay allocated, so nothing falls free and the hot prefix
        just stops being shareable.  One pool-pressure event must not
        sweep the shared prefix the whole mix is riding.  The
        entries-driven path (the ``max_entries`` bound, :meth:`clear`)
        drops unconditionally."""
        freed = 0
        dropped = 0
        scanned = 0
        with self._lock:
            bound = len(self._lru)
        while True:
            with self._lock:
                done = ((need_pages and freed >= need_pages)
                        or (entries and dropped >= entries)
                        or (not need_pages and not entries)
                        or (not entries and scanned >= bound)
                        or not self._lru)
                if done:
                    return freed
                key = next(iter(self._lru))
                pages, _tok = self._lru[key]
            scanned += 1
            if entries:
                with self._lock:
                    if self._lru.pop(key, None) is None:
                        continue
                freed += self.alloc.release(list(pages), INDEX_OWNER)
                dropped += 1
                self.alloc.count_evictions()
                continue
            got = self.alloc.release_if_idle(list(pages), INDEX_OWNER)
            with self._lock:
                if got is None:
                    if key in self._lru:
                        self._lru.move_to_end(key)
                    continue
                self._lru.pop(key, None)
            freed += got
            dropped += 1
            self.alloc.count_evictions()

    def clear(self) -> int:
        """Drop every entry (teardown/leak-audit path)."""
        with self._lock:
            n = len(self._lru)
        return self.evict(0, entries=n) if n else 0

    # ------------------------------------------------------------ metering
    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    def snapshot(self) -> Dict:
        with self._lock:
            total = self.hits_full + self.hits_partial + self.misses
            return {
                "entries": len(self._lru),
                "hits_full": self.hits_full,
                "hits_partial": self.hits_partial,
                "misses": self.misses,
                "hit_rate": ((self.hits_full + self.hits_partial) / total
                             if total else 0.0),
            }
