"""KV handoff payloads + the length-prefixed loopback socket transport.

Disaggregated serving (prefill-role vs decode-role engine pools) needs to
move one stream's KV pages between engines.  Same-host the payload is a
pair of device arrays (``models.decoder.gather_pages`` output) handed
straight to the importing engine; cross-pool it crosses the repo's first
real RPC boundary — this module's thin stdlib-socket transport, modeled
on ``obs/exporter.py``'s stdlib-server idiom (no framework, no new
dependency, a background thread owning a listening socket).

Wire format (one frame per handoff)::

    MAGIC(4) | body_len(4, big-endian) | body
    body = crc32(4) | header_len(4) | header JSON | K bytes | V bytes

The header carries the stream metadata (rid, tokens, pos, next token)
plus the dtype/shape of both page payloads.  Every read is
exact-length: a connection that dies mid-frame, a truncated body, a
length prefix pointing past the data, or a checksum mismatch is a LOUD
:class:`HandoffError` — a torn payload must never be imported as a
shorter-but-plausible one (the pages it fills back a live stream's
attention).  After each frame the receiver answers a 2-byte ack
(``OK``/``ER``), so the sender's staged custody
(:func:`~pdnlp_tpu.serve.kvpage.stage_handoff`) is released exactly when
the import landed, and re-queued for recovery when it did not.

The transport is deliberately payload-agnostic: it moves ``(meta dict,
K ndarray, V ndarray)`` and returns the ack.  Which engine imports,
which slot seats the stream, and who owns the pages on each side is the
serve tier's business (``serve.decode``); leaklint L1 treats an open
:class:`HandoffChannel` / accepted connection as an acquire that must be
closed on every path (``handoff-conn`` spec).
"""
from __future__ import annotations

import json
import socket
import struct
import threading
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

#: frame magic — rejects a stray connection (or an HTTP probe) loudly
MAGIC = b"PDKV"

#: per-frame acknowledgement bytes
ACK_OK = b"OK"
ACK_ERR = b"ER"

#: refuse absurd frames before allocating for them (a corrupt length
#: prefix must fail the frame, not OOM the receiver)
MAX_FRAME_BYTES = 1 << 31


class HandoffError(RuntimeError):
    """A handoff frame could not be sent, parsed, or acknowledged."""


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 and friends register with numpy via ml_dtypes (a jax
        # dependency, already in the image)
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


# ------------------------------------------------------------- framing

def encode_frame(meta: Dict, payload_k: np.ndarray,
                 payload_v: np.ndarray) -> bytes:
    """One handoff as a self-delimiting byte frame (see module doc)."""
    k = np.ascontiguousarray(payload_k)
    v = np.ascontiguousarray(payload_v)
    header = dict(meta)
    header["k"] = {"dtype": k.dtype.name, "shape": list(k.shape)}
    header["v"] = {"dtype": v.dtype.name, "shape": list(v.shape)}
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    tail = struct.pack(">I", len(hdr)) + hdr + k.tobytes() + v.tobytes()
    body = struct.pack(">I", zlib.crc32(tail)) + tail
    return MAGIC + struct.pack(">I", len(body)) + body


def decode_frame(frame: bytes) -> Tuple[Dict, np.ndarray, np.ndarray]:
    """Parse one frame back into ``(meta, K, V)``.  Raises
    :class:`HandoffError` on any truncation, bad magic, checksum
    mismatch, or size that disagrees with the header's own shapes."""
    if len(frame) < 8 or frame[:4] != MAGIC:
        raise HandoffError("torn handoff payload: bad magic "
                           f"{frame[:4]!r} (not a KV handoff frame)")
    (body_len,) = struct.unpack(">I", frame[4:8])
    body = frame[8:]
    if len(body) != body_len:
        raise HandoffError(
            f"torn handoff payload: frame declares {body_len} body "
            f"bytes but carries {len(body)}")
    if body_len < 8:
        raise HandoffError("torn handoff payload: body too short for "
                           "checksum + header length")
    (crc,) = struct.unpack(">I", body[:4])
    tail = body[4:]
    if zlib.crc32(tail) != crc:
        raise HandoffError("torn handoff payload: checksum mismatch — "
                           "refusing to import corrupt KV pages")
    (hdr_len,) = struct.unpack(">I", tail[:4])
    if 4 + hdr_len > len(tail):
        raise HandoffError("torn handoff payload: header length "
                           "overruns the frame")
    meta = json.loads(tail[4:4 + hdr_len].decode("utf-8"))
    off = 4 + hdr_len
    arrays: List[np.ndarray] = []
    for part in ("k", "v"):
        spec = meta.pop(part)
        dt = _np_dtype(spec["dtype"])
        shape = tuple(int(s) for s in spec["shape"])
        n = int(np.prod(shape)) * dt.itemsize if shape else dt.itemsize
        chunk = tail[off:off + n]
        if len(chunk) != n:
            raise HandoffError(
                f"torn handoff payload: {part.upper()} pages need {n} "
                f"bytes, frame holds {len(chunk)}")
        arrays.append(np.frombuffer(chunk, dtype=dt).reshape(shape))
        off += n
    if off != len(tail):
        raise HandoffError(f"torn handoff payload: {len(tail) - off} "
                           "trailing bytes after the V pages")
    return meta, arrays[0], arrays[1]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise (EOF mid-frame = torn)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise HandoffError(
                f"torn handoff payload: connection closed {got}/{n} "
                "bytes into a frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket
               ) -> Optional[Tuple[Dict, np.ndarray, np.ndarray]]:
    """Read one frame off a socket; ``None`` on a CLEAN EOF between
    frames (peer closed the channel), :class:`HandoffError` on a tear
    anywhere inside one."""
    head = b""
    while len(head) < 8:
        chunk = sock.recv(8 - len(head))
        if not chunk:
            if head:
                raise HandoffError(
                    "torn handoff payload: connection closed inside "
                    "the frame prefix")
            return None
        head += chunk
    if head[:4] != MAGIC:
        raise HandoffError(f"torn handoff payload: bad magic "
                           f"{head[:4]!r} on the wire")
    (body_len,) = struct.unpack(">I", head[4:8])
    if body_len > MAX_FRAME_BYTES:
        raise HandoffError(f"torn handoff payload: implausible frame "
                           f"length {body_len}")
    return decode_frame(head + _recv_exact(sock, body_len))


# ----------------------------------------------------------- transport

class HandoffChannel:
    """Sender side of the RPC boundary: one connected socket, one frame
    per :meth:`send`, each awaited to its 2-byte ack.  Close it on every
    path — an open channel is a tracked acquire (leaklint
    ``handoff-conn``)."""

    def __init__(self, address: Tuple[str, int], timeout: float = 10.0):
        self._sock = socket.create_connection(address, timeout=timeout)
        self._lock = threading.Lock()

    def send(self, meta: Dict, payload_k: np.ndarray,
             payload_v: np.ndarray) -> None:
        """Ship one handoff and wait for the receiver's ack; raises
        :class:`HandoffError` when the peer refused the import or the
        connection tore."""
        frame = encode_frame(meta, payload_k, payload_v)
        with self._lock:
            try:
                self._sock.sendall(frame)
                ack = _recv_exact(self._sock, len(ACK_OK))
            except OSError as e:
                raise HandoffError(f"handoff send failed: {e}") from e
        if ack != ACK_OK:
            raise HandoffError(
                f"handoff rejected by receiver (ack {ack!r}) — payload "
                f"for {meta.get('rid')!r} was NOT imported")

    def close(self) -> None:
        self._sock.close()

    def __enter__(self) -> "HandoffChannel":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class HandoffServer:
    """Receiver side: a background accept loop (stdlib socket server,
    the ``obs/exporter.py`` idiom) that reads frames and hands each
    ``(meta, K, V)`` to ``on_payload``.  The callback's return/raise IS
    the ack: return -> ``OK``, raise -> ``ER`` (the sender keeps custody
    and recovers).  Binds ``127.0.0.1:0`` by default — the cross-host
    half is future scope; this is the process-split boundary."""

    def __init__(self, on_payload: Callable[[Dict, np.ndarray,
                                             np.ndarray], None],
                 host: str = "127.0.0.1", port: int = 0):
        self._on_payload = on_payload
        self._listener = socket.create_server((host, port))
        self.address: Tuple[str, int] = self._listener.getsockname()[:2]
        self._stop = threading.Event()
        self._accept: Optional[threading.Thread] = None
        self._conns: List[threading.Thread] = []
        self.frames_ok = 0
        self.frames_err = 0

    def start(self) -> "HandoffServer":
        self._accept = threading.Thread(target=self._accept_loop,
                                        name="handoff-accept",
                                        daemon=True)
        self._accept.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="handoff-conn", daemon=True)
            t.start()
            self._conns.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    frame = recv_frame(conn)
                except HandoffError:
                    self.frames_err += 1
                    try:
                        conn.sendall(ACK_ERR)
                    except OSError:
                        pass
                    return  # a torn stream cannot be resynchronized
                if frame is None:
                    return
                meta, k, v = frame
                try:
                    self._on_payload(meta, k, v)
                except Exception:
                    self.frames_err += 1
                    conn.sendall(ACK_ERR)
                else:
                    self.frames_ok += 1
                    conn.sendall(ACK_OK)
        except OSError:
            pass  # peer vanished; sender sees the tear on its side
        finally:
            conn.close()

    def stop(self) -> None:
        self._stop.set()
        self._listener.close()
        if self._accept is not None:
            self._accept.join(timeout=5.0)
        for t in self._conns:
            t.join(timeout=5.0)

    def __enter__(self) -> "HandoffServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
