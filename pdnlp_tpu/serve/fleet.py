"""Multi-model serving fleet: one front door, many models, safe rollouts.

PRs 8-11 built a fault-tolerant pool of N replicas of ONE checkpoint.
Production serving is never one model: a new checkpoint must be validated
against live traffic without risking callers, a bad rollout must undo
itself, and overload should degrade answer QUALITY before it drops
requests.  :class:`FleetRouter` composes the existing
:class:`~pdnlp_tpu.serve.router.ReplicaRouter` machinery into that fleet —
one ``ReplicaRouter`` per **model id** (each with its own replicas,
engines, metrics and health loop), fronted by a traffic policy:

- **roles** — exactly one ``primary`` (the model callers' answers come
  from), at most one ``candidate`` (a checkpoint under validation: shadow
  target + canary target) and at most one ``cheap`` (an int8/distilled
  variant that absorbs overload).  ``parse_fleet_spec`` turns the
  ``--fleet`` CLI string (``id=checkpoint:dtype:replicas[:role]``) into
  :class:`ModelSpec` rows;

- **shadow traffic** (``shadow_fraction``) — a sampled fraction of
  primary-routed requests is DUPLICATED onto the candidate.  The caller
  always gets the primary's answer (the shadow is a separate request whose
  terminal hop is stamped ``shadow=True`` — the chain contract in
  :mod:`pdnlp_tpu.obs.request` proves no candidate answer can leak); a
  harvester thread joins each (primary, shadow) pair off the hot path and
  accumulates per-request argmax parity + latency deltas in a
  :class:`ShadowReport` — the evidence the rollout law advances on;

- **canary rollout** (``canary_fraction``) — a fraction of CALLER traffic
  is routed to the candidate for real.  The fraction is a knob: the
  control plane (:class:`~pdnlp_tpu.serve.controller.ServeController`
  with a :class:`RolloutPlan`) steps it up only while shadow parity and
  candidate p99 hold, and **auto-rolls-back** to 0 through its
  ``_actuate`` choke point when either regresses.  Setting the fraction
  to 0 from a live rollout drains every request still queued on the
  candidate back to the primary with a ``rollback`` hop — zero accepted
  work lost;

- **degrade tier** — the primary pool's admission ladder gains the
  ``degrade`` band (:class:`~pdnlp_tpu.serve.batcher.AdmissionControl`
  ``degrade_at``, between backpressure and shed): an arrival meeting that
  band is re-routed to the cheap model instead of walking into the shed
  pass, with a ``degrade`` hop recorded BEFORE the cheap pool's admit —
  ``trace_tpu.py request <id>`` shows who got the cheap answer and why.
  With no cheap model registered the band falls through to the shed tier
  (loudly, once): quality degradation is opt-in, losing requests is the
  ladder's own last resort as before.

Every traffic-fraction write comes through :meth:`FleetRouter.apply_knob`
— the fleet's ONE setter — and controller-side writes must come through
the controller's ``_actuate`` (jaxlint R15 flags any other path, the R13
contract extended to rollout state).
"""
from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from pdnlp_tpu.obs.request import record_hop
from pdnlp_tpu.serve.batcher import LoadShedError, QueueFullError, _Request
from pdnlp_tpu.serve.metrics import FleetMetrics, _save_json
from pdnlp_tpu.serve.router import ReplicaRouter
from pdnlp_tpu.utils.metrics import Histogram

#: the fleet roles a model spec may declare
ROLES = ("primary", "candidate", "cheap")

#: serving dtypes a spec may pin (``auto`` follows ``args.dtype``)
SPEC_DTYPES = ("auto", "bf16", "int8")


class ModelSpec:
    """One ``--fleet`` entry: model id -> checkpoint / dtype / replicas /
    role."""

    __slots__ = ("model_id", "checkpoint", "dtype", "replicas", "role")

    def __init__(self, model_id: str, checkpoint: Optional[str], *,
                 dtype: str = "auto", replicas: int = 1,
                 role: str = "primary"):
        if dtype not in SPEC_DTYPES:
            raise ValueError(f"fleet spec {model_id!r}: dtype must be one "
                             f"of {SPEC_DTYPES}, got {dtype!r}")
        if role not in ROLES:
            raise ValueError(f"fleet spec {model_id!r}: role must be one "
                             f"of {ROLES}, got {role!r}")
        if int(replicas) < 1:
            raise ValueError(f"fleet spec {model_id!r}: replicas must be "
                             f">= 1, got {replicas}")
        self.model_id = model_id
        self.checkpoint = checkpoint or None
        self.dtype = dtype
        self.replicas = int(replicas)
        self.role = role

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"ModelSpec({self.model_id}={self.checkpoint}:{self.dtype}"
                f":{self.replicas}:{self.role})")


def parse_fleet_spec(spec: str) -> List[ModelSpec]:
    """``--fleet`` string -> validated :class:`ModelSpec` rows.

    Format (comma-separated entries)::

        model_id=checkpoint[:dtype[:replicas[:role]]]

    e.g. ``prod=out/dp-cls.msgpack:bf16:2,next=out/new.msgpack:bf16:1:
    candidate,tiny=out/dp-cls.int8.msgpack:int8:1:cheap``.  The FIRST
    entry defaults to role ``primary``; later entries must name a role.
    Exactly one primary; at most one candidate; at most one cheap."""
    specs: List[ModelSpec] = []
    for i, entry in enumerate(s.strip() for s in spec.split(",")):
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(f"fleet spec entry {entry!r}: expected "
                             "model_id=checkpoint[:dtype[:replicas[:role]]]")
        model_id, rest = entry.split("=", 1)
        parts = rest.split(":")
        ckpt = parts[0] or None
        dtype = parts[1] if len(parts) > 1 and parts[1] else "auto"
        replicas = int(parts[2]) if len(parts) > 2 and parts[2] else 1
        role = parts[3] if len(parts) > 3 and parts[3] else (
            "primary" if i == 0 else None)
        if role is None:
            raise ValueError(
                f"fleet spec entry {entry!r}: every entry after the first "
                f"must name a role ({'/'.join(ROLES)})")
        if len(parts) > 4:
            raise ValueError(f"fleet spec entry {entry!r}: too many "
                             "':'-separated fields")
        specs.append(ModelSpec(model_id.strip(), ckpt, dtype=dtype,
                               replicas=replicas, role=role))
    if not specs:
        raise ValueError("empty fleet spec")
    ids = [s.model_id for s in specs]
    if len(set(ids)) != len(ids):
        raise ValueError(f"duplicate model ids in fleet spec: {ids}")
    for role, lo, hi in (("primary", 1, 1), ("candidate", 0, 1),
                         ("cheap", 0, 1)):
        n = sum(1 for s in specs if s.role == role)
        if not (lo <= n <= hi):
            raise ValueError(f"fleet spec needs {lo}..{hi} {role!r} "
                             f"model(s), got {n}")
    return specs


def parse_speculate_spec(spec: str) -> ModelSpec:
    """``--speculate`` string -> the drafter's :class:`ModelSpec`.

    Accepts a bare checkpoint path (a distilled same-architecture
    checkpoint: the model id defaults to ``draft`` and the pool builder
    keeps the primary's architecture) or a fleet-style entry
    ``model_id=checkpoint[:dtype]`` whose model id names the drafter's
    ARCHITECTURE (a :mod:`pdnlp_tpu.models.config` registry key, e.g.
    ``bert-tiny``).  The returned spec
    is pinned to role ``cheap`` — the fleet role whose job description
    (int8/distilled, fast, vocabulary-compatible with the primary) is
    exactly what a draft model needs — with 1 replica: a drafter rides
    its primary engine's replica, it is never a pool of its own."""
    entry = spec.strip()
    if not entry:
        raise ValueError("empty --speculate spec")
    if "=" not in entry:
        return ModelSpec("draft", entry, role="cheap")
    model_id, rest = entry.split("=", 1)
    parts = rest.split(":")
    if len(parts) > 2:
        raise ValueError(f"speculate spec {entry!r}: expected "
                         "model_id=checkpoint[:dtype]")
    dtype = parts[1] if len(parts) > 1 and parts[1] else "auto"
    return ModelSpec(model_id.strip(), parts[0] or None, dtype=dtype,
                     role="cheap")


def drafter_spec(specs: Sequence[ModelSpec]) -> Optional[ModelSpec]:
    """The fleet's speculative-decoding drafter: its ``cheap`` entry.

    The same distilled/int8 variant that absorbs classification overload
    through the degrade band becomes the draft model in generative
    serving (draft-k / verify-1 — :mod:`pdnlp_tpu.serve.decode`); one
    spec, two jobs.  ``None`` when the fleet declares no cheap model."""
    for s in specs:
        if s.role == "cheap":
            return s
    return None


class ShadowReport:
    """Accumulated shadow-pair evidence: per-request argmax parity and
    latency deltas between the primary's answer and the candidate's.
    Fed by the fleet's harvester thread (off the hot path); read by the
    rollout law and the ``--fleet`` smoke."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.checked = 0          # pairs resolved with a primary answer
        self.matches = 0
        self.mismatches = 0
        self.shadow_failed = 0    # shadow errored/timed out (primary fine)
        self.voided = 0           # primary errored/timed out: nothing to judge
        self.primary_ms = Histogram()
        self.shadow_ms = Histogram()
        self.delta_ms = Histogram()   # shadow latency - primary latency

    def observe(self, match: bool, primary_ms: Optional[float],
                shadow_ms: Optional[float]) -> None:
        with self._lock:
            self.checked += 1
            if match:
                self.matches += 1
            else:
                self.mismatches += 1
            if primary_ms is not None:
                self.primary_ms.observe(primary_ms)
            if shadow_ms is not None:
                self.shadow_ms.observe(shadow_ms)
            if primary_ms is not None and shadow_ms is not None:
                self.delta_ms.observe(shadow_ms - primary_ms)

    def observe_failed(self, primary_ms: Optional[float] = None) -> None:
        with self._lock:
            self.checked += 1
            self.shadow_failed += 1
            if primary_ms is not None:
                self.primary_ms.observe(primary_ms)

    def observe_void(self) -> None:
        with self._lock:
            self.voided += 1

    @property
    def parity_checked(self) -> int:
        """Pairs where BOTH sides produced an answer to compare."""
        return self.matches + self.mismatches

    @property
    def mismatch_rate(self) -> float:
        return self.mismatches / max(1, self.parity_checked)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "checked": self.checked,
                "matches": self.matches,
                "mismatches": self.mismatches,
                "mismatch_rate": round(self.mismatch_rate, 6),
                "shadow_failed": self.shadow_failed,
                "voided": self.voided,
                "primary_ms": self.primary_ms.snapshot(),
                "shadow_ms": self.shadow_ms.snapshot(),
                "delta_ms": self.delta_ms.snapshot(),
            }


class RolloutPlan:
    """Config for the controller's canary-rollout law: the fraction steps,
    the parity/latency evidence each advance needs, and the regression
    bounds that trigger auto-rollback."""

    __slots__ = ("steps", "min_shadow_checked", "parity_tolerance",
                 "p99_factor", "p99_floor_ms", "patience")

    def __init__(self, steps: Sequence[float] = (0.05, 0.25, 0.5, 1.0), *,
                 min_shadow_checked: int = 20,
                 parity_tolerance: float = 0.02,
                 p99_factor: float = 1.5,
                 p99_floor_ms: float = 10.0,
                 patience: int = 3):
        steps = tuple(float(s) for s in steps)
        if not steps or any(not (0.0 < s <= 1.0) for s in steps) \
                or list(steps) != sorted(set(steps)):
            raise ValueError(f"rollout steps must be strictly ascending "
                             f"fractions in (0, 1], got {steps}")
        self.steps = steps
        #: shadow pairs that must have been parity-checked before the
        #: FIRST advance (and before a mismatch rate is trusted at all)
        self.min_shadow_checked = int(min_shadow_checked)
        #: mismatch rate above this = parity regression -> rollback
        self.parity_tolerance = float(parity_tolerance)
        #: candidate p99 above ``factor x primary p99 + floor`` = latency
        #: regression -> rollback (the floor keeps ms-scale jitter on a
        #: fast pool from reading as a regression)
        self.p99_factor = float(p99_factor)
        self.p99_floor_ms = float(p99_floor_ms)
        #: consecutive healthy control ticks between advances
        self.patience = int(patience)


class _ShadowPair:
    __slots__ = ("primary", "shadow", "t0")

    def __init__(self, primary: _Request, shadow: _Request, t0: float):
        self.primary = primary
        self.shadow = shadow
        self.t0 = t0


class FleetRouter:
    """The fleet front door (module docstring has the full story).

    ``groups`` maps model id -> a **started-able** :class:`ReplicaRouter`
    whose ``model_id`` matches its key (so every hop either pool records
    is model-labelled).  The fleet quacks like a router where the control
    plane is concerned — ``knob_values``/``apply_knob``/
    ``control_snapshot``/``active_count``/``deactivate_replica``/... all
    delegate to the PRIMARY group, plus the fleet-owned traffic knobs
    (``shadow_fraction``, ``canary_fraction``) — so one
    :class:`ServeController` drives both the serving knobs and the
    rollout.
    """

    #: the fleet-owned traffic knobs (group knobs delegate to the primary)
    FLEET_KNOBS = ("shadow_fraction", "canary_fraction")

    def __init__(self, groups: Dict[str, ReplicaRouter], *,
                 primary: str,
                 candidate: Optional[str] = None,
                 cheap: Optional[str] = None,
                 shadow_fraction: float = 0.0,
                 canary_fraction: float = 0.0,
                 shadow_timeout_s: float = 60.0,
                 harvest_interval_s: float = 0.02,
                 metrics: Optional[FleetMetrics] = None,
                 tracer=None,
                 clock: Callable[[], float] = time.monotonic):
        if primary not in groups:
            raise ValueError(f"primary model {primary!r} not in groups "
                             f"{sorted(groups)}")
        for role, mid in (("candidate", candidate), ("cheap", cheap)):
            if mid is not None and mid not in groups:
                raise ValueError(f"{role} model {mid!r} not in groups "
                                 f"{sorted(groups)}")
        if candidate is not None and candidate == primary:
            raise ValueError("candidate must be a different model than "
                             "the primary")
        for mid, g in groups.items():
            if g.model_id != mid:
                raise ValueError(
                    f"group {mid!r} was built with model_id="
                    f"{g.model_id!r} — every pool must stamp its fleet "
                    "key on its hops (ReplicaRouter(model_id=...))")
        self.groups = dict(groups)
        self.primary = primary
        self.candidate = candidate
        self.cheap = cheap
        if not (0.0 <= float(shadow_fraction) <= 1.0):
            raise ValueError(f"shadow_fraction must be in [0, 1], got "
                             f"{shadow_fraction}")
        if not (0.0 <= float(canary_fraction) <= 1.0):
            raise ValueError(f"canary_fraction must be in [0, 1], got "
                             f"{canary_fraction}")
        if canary_fraction and candidate is None:
            raise ValueError("canary_fraction needs a candidate model")
        self.shadow_fraction = float(shadow_fraction)
        self.canary_fraction = float(canary_fraction)
        self.shadow_timeout_s = float(shadow_timeout_s)
        self.harvest_interval_s = float(harvest_interval_s)
        self.metrics = metrics or FleetMetrics()
        self.shadow_report = ShadowReport()
        self.tracer = tracer if tracer is not None \
            else groups[primary].tracer
        self.clock = clock
        # deterministic fraction accumulators (exactly `fraction` of
        # traffic, no RNG) — one small lock for both, taken per submit
        self._traffic_lock = threading.Lock()
        self._shadow_acc = 0.0
        self._canary_acc = 0.0
        self._pairs_lock = threading.Lock()
        self._pairs: List[_ShadowPair] = []
        self._harvester: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._warned_no_cheap = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FleetRouter":
        for g in self.groups.values():
            g.start()
        if self._harvester is None:
            self._stop_evt.clear()
            self._harvester = threading.Thread(
                target=self._harvest_loop, daemon=True,
                name="pdnlp-fleet-shadow")
            self._harvester.start()
        return self

    def wait_ready(self, timeout: float = 120.0) -> bool:
        return all(g.wait_ready(timeout) for g in self.groups.values())

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        for g in self.groups.values():
            g.stop(drain=drain, timeout=timeout)
        self._stop_evt.set()
        if self._harvester is not None:
            self._harvester.join(timeout=5)
            self._harvester = None
        # every request is completed by now (a stopped pool fails its
        # leftovers loudly): resolve what resolved, void the rest
        self._harvest_once(final=True)

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -------------------------------------------------------------- submit
    def submit(self, text: str,
               deadline_ms: Optional[float] = None) -> _Request:
        prim = self.groups[self.primary]
        ids = prim.tokenizer.encode_ids(text, prim.buckets[-1])
        return self.submit_ids(ids, deadline_ms=deadline_ms)

    def submit_ids(self, ids: List[int],
                   deadline_ms: Optional[float] = None) -> _Request:
        """The fleet front door: canary split -> degrade-band re-route ->
        group admission; a sampled fraction of primary-routed admissions
        grows a shadow duplicate on the candidate.  Raises exactly what
        :meth:`ReplicaRouter.submit_ids` raises."""
        self.metrics.requests_total.inc()
        target = self._pick_model()
        group = self.groups[target]
        req = group.make_request(ids, deadline_ms=deadline_ms)
        tier = group.admission_tier()
        if tier == "degrade" and target != self.cheap:
            if self.cheap is None:
                # the band is configured but nothing sits behind it: fall
                # through to the group's own ladder, where the degrade
                # band IS an early shed tier — loudly, once, because a
                # fleet shedding where it meant to degrade is an operator
                # error worth a page
                if not self._warned_no_cheap:
                    self._warned_no_cheap = True
                    print("WARNING: fleet degrade band reached with NO "
                          "cheap model registered — falling through to "
                          "the shed tier (register a cheap/int8 model to "
                          "absorb overload instead of dropping it)",
                          file=sys.stderr)
                self.metrics.degrade_fallthrough_total.inc()
            else:
                # re-route to the cheap model: the degrade hop lands
                # BEFORE the cheap pool's admit, so the chain reads
                # degrade -> admit -> dispatch -> complete and the
                # degrade-precedes-dispatch contract holds by construction
                record_hop(self.tracer, req.rid, "degrade",
                           from_model=target, to_model=self.cheap,
                           tier=tier)
                self.metrics.degraded_total.inc()
                return self.groups[self.cheap].submit_request(
                    req, deadline_ms=deadline_ms)
        fut = group.submit_request(req, deadline_ms=deadline_ms)
        if target == self.candidate:
            # counted AFTER admission: this is "caller traffic whose
            # answer IS the candidate's" — a canary pick the candidate's
            # door refused never became candidate-answered traffic
            self.metrics.canary_routed_total.inc()
        elif target == self.primary:
            self._maybe_shadow(req, deadline_ms)
        return fut

    def _pick_model(self) -> str:
        """Canary split: exactly ``canary_fraction`` of caller traffic to
        the candidate (deterministic accumulator, no RNG), the rest to
        the primary."""
        if self.candidate is None:
            return self.primary
        with self._traffic_lock:
            if self.canary_fraction <= 0.0:
                return self.primary
            self._canary_acc += self.canary_fraction
            if self._canary_acc >= 1.0:
                self._canary_acc -= 1.0
                return self.candidate
        return self.primary

    # -------------------------------------------------------------- shadow
    def _maybe_shadow(self, primary_req: _Request,
                      deadline_ms: Optional[float]) -> None:
        if self.candidate is None:
            return
        with self._traffic_lock:
            if self.shadow_fraction <= 0.0:
                return
            self._shadow_acc += self.shadow_fraction
            if self._shadow_acc < 1.0:
                return
            self._shadow_acc -= 1.0
        group = self.groups[self.candidate]
        sreq = group.make_request(list(primary_req.ids),
                                  deadline_ms=deadline_ms)
        sreq.shadow_of = primary_req.rid
        # the duplicate's chain OPENS with the shadow hop (before the
        # candidate pool's admit): first-hop shadow IS the chain-contract
        # marker that this request must never terminate caller-visibly
        record_hop(self.tracer, sreq.rid, "shadow", of=primary_req.rid,
                   model=self.candidate)
        record_hop(self.tracer, primary_req.rid, "shadow",
                   to_model=self.candidate, shadow_rid=sreq.rid)
        try:
            group.submit_request(sreq, deadline_ms=deadline_ms)
        except (LoadShedError, QueueFullError, RuntimeError):
            # the candidate refused (overloaded/stopped): the caller is
            # untouched — shadow traffic is strictly best-effort
            self.metrics.shadow_dropped_total.inc()
            return
        self.metrics.shadows_total.inc()
        with self._pairs_lock:
            self._pairs.append(_ShadowPair(primary_req, sreq,
                                           self.clock()))

    def _harvest_loop(self) -> None:
        while not self._stop_evt.wait(self.harvest_interval_s):
            self._harvest_once()

    def _harvest_once(self, final: bool = False) -> None:
        """Join resolved (primary, shadow) pairs into the report — runs on
        the harvester thread (and once at stop), never on a caller's."""
        now = self.clock()
        with self._pairs_lock:
            pairs, self._pairs = self._pairs, []
        keep: List[_ShadowPair] = []
        for p in pairs:
            if p.primary.done() and p.shadow.done():
                self._resolve(p)
            elif final or now - p.t0 > self.shadow_timeout_s:
                # one side never resolved: a wedged candidate must not
                # hold parity evidence hostage forever
                if p.primary.done() and p.primary._error is None:
                    self.shadow_report.observe_failed(
                        self._latency_ms(p.primary))
                else:
                    self.shadow_report.observe_void()
            else:
                keep.append(p)
        if keep:
            with self._pairs_lock:
                self._pairs = keep + self._pairs

    @staticmethod
    def _latency_ms(r: _Request) -> Optional[float]:
        # born/completed_at are BOTH time.monotonic stamps (`submitted`
        # may live in a group's injectable clock domain — mixing the two
        # would corrupt the parity evidence under any non-default clock)
        if r.completed_at is None:
            return None
        return max(0.0, (r.completed_at - r.born) * 1e3)

    def _resolve(self, p: _ShadowPair) -> None:
        if p.primary._error is not None:
            self.shadow_report.observe_void()
            return
        plat = self._latency_ms(p.primary)
        if p.shadow._error is not None or p.shadow._logits is None:
            self.shadow_report.observe_failed(plat)
            return
        match = int(np.argmax(p.primary._logits)) \
            == int(np.argmax(p.shadow._logits))
        self.shadow_report.observe(match, plat, self._latency_ms(p.shadow))

    # ------------------------------------------------------ tuning surface
    def apply_knob(self, name: str, value) -> None:
        """The fleet's ONE knob setter (jaxlint R15 flags fleet-scope
        traffic-fraction writes outside the controller's ``_actuate``
        path).  Fleet-owned knobs are handled here; everything else
        delegates to the PRIMARY group's setter.  Dropping
        ``canary_fraction`` to 0 from a live rollout IS the rollback: the
        candidate's queued requests drain back to the primary."""
        if name == "shadow_fraction":
            v = float(value)
            if not (0.0 <= v <= 1.0):
                raise ValueError(f"shadow_fraction must be in [0, 1], "
                                 f"got {value}")
            with self._traffic_lock:
                self.shadow_fraction = v
        elif name == "canary_fraction":
            if self.candidate is None:
                raise ValueError("canary_fraction needs a candidate model "
                                 "in the fleet")
            v = float(value)
            if not (0.0 <= v <= 1.0):
                raise ValueError(f"canary_fraction must be in [0, 1], "
                                 f"got {value}")
            with self._traffic_lock:
                old, self.canary_fraction = self.canary_fraction, v
            if v == 0.0 and old > 0.0:
                self._rollback_drain()
        else:
            self.groups[self.primary].apply_knob(name, value)

    def knob_values(self) -> Dict:
        return {**self.groups[self.primary].knob_values(),
                "shadow_fraction": self.shadow_fraction,
                "canary_fraction": self.canary_fraction}

    def _rollback_drain(self) -> None:
        """Canary rollback: re-home every request still QUEUED on the
        candidate onto the primary (``rollback`` hop -> adopt — the
        admission ladder is deliberately bypassed, accepted work must
        never become a rejection), and retire queued shadow duplicates
        (they have no caller; their terminal stays on the shadow side).
        In-flight candidate batches finish where they are."""
        cand = self.groups[self.candidate]
        prim = self.groups[self.primary]
        drained = cand.extract_queued()
        self.metrics.rollbacks_total.inc()
        for r in drained:
            if r.shadow_of is not None:
                if r._complete(None, LoadShedError("canary rolled back")):
                    record_hop(self.tracer, r.rid, "shed", shadow=True,
                               model=self.candidate, rollback=True)
                continue
            record_hop(self.tracer, r.rid, "rollback",
                       from_model=self.candidate, to_model=self.primary)
            self.metrics.rolled_back_requests_total.inc()
            try:
                prim.adopt(r)
            except Exception as e:  # noqa: BLE001 — a primary with no
                # replica left cannot adopt: fail the caller loudly
                # rather than strand the future forever
                if r._complete(None, e):
                    record_hop(self.tracer, r.rid, "failed",
                               model=self.primary,
                               error=type(e).__name__)

    # --------------------------------------------- controller quack surface
    @property
    def max_batch_size(self) -> int:
        return self.groups[self.primary].max_batch_size

    @property
    def active_count(self) -> int:
        return self.groups[self.primary].active_count

    @property
    def standby_count(self) -> int:
        return self.groups[self.primary].standby_count

    def deactivate_replica(self, index: Optional[int] = None) -> int:
        return self.groups[self.primary].deactivate_replica(index)

    def activate_replica(self, index: Optional[int] = None) -> int:
        return self.groups[self.primary].activate_replica(index)

    def engine(self, index: int = 0):
        return self.groups[self.primary].engine(index)

    @property
    def retraces_post_warmup(self) -> int:
        return sum(g.retraces_post_warmup for g in self.groups.values())

    @property
    def states(self) -> Dict[str, Dict[int, str]]:
        return {mid: g.states for mid, g in self.groups.items()}

    def control_snapshot(self) -> Dict:
        """The controller's per-tick sense input: the PRIMARY group's
        lightweight snapshot (its knobs/queue/p99 drive the serving laws)
        with the fleet knobs folded in."""
        snap = self.groups[self.primary].control_snapshot()
        snap["knobs"] = self.knob_values()
        return snap

    def rollout_sense(self) -> Dict:
        """The rollout law's evidence: the live fraction, shadow parity,
        and primary-vs-candidate p99 (None without a candidate)."""
        rep = self.shadow_report
        out = {
            "canary_fraction": self.canary_fraction,
            "shadow_fraction": self.shadow_fraction,
            "parity_checked": rep.parity_checked,
            "mismatch_rate": rep.mismatch_rate,
            "shadow_failed": rep.shadow_failed,
            "primary_p99_ms": self.groups[self.primary]
            .metrics.request_latency_ms.percentile(99),
            "candidate_p99_ms": None,
        }
        if self.candidate is not None:
            out["candidate_p99_ms"] = self.groups[self.candidate] \
                .metrics.request_latency_ms.percentile(99)
        return out

    # ----------------------------------------------------------- reporting
    def snapshot(self) -> Dict:
        """Fleet + per-model metrics, JSON-ready.  The ``models`` block is
        keyed by model id — the exporter renders it as a ``model`` label
        on every per-model gauge, so one Prometheus scrape distinguishes
        primary/candidate/cheap tiers."""
        return {
            "fleet": {
                **self.metrics.snapshot(),
                "roles": {"primary": self.primary,
                          "candidate": self.candidate,
                          "cheap": self.cheap},
                "knobs": {"shadow_fraction": self.shadow_fraction,
                          "canary_fraction": self.canary_fraction},
            },
            "shadow": self.shadow_report.snapshot(),
            "models": {mid: g.snapshot()
                       for mid, g in self.groups.items()},
        }

    def save_snapshot(self, path: str) -> None:
        _save_json(self.snapshot(), path)

    def health_summary(self) -> Dict:
        """The compact ``/healthz`` block: per-model role/active state,
        the live traffic split and the shadow verdict at a glance."""
        rep = self.shadow_report
        return {
            "models": {mid: {
                "role": ("primary" if mid == self.primary else
                         "candidate" if mid == self.candidate else
                         "cheap" if mid == self.cheap else "unknown"),
                "active": g.active_count,
                "standby": g.standby_count,
            } for mid, g in self.groups.items()},
            "canary_fraction": self.canary_fraction,
            "shadow_fraction": self.shadow_fraction,
            "shadow": {"parity_checked": rep.parity_checked,
                       "mismatch_rate": round(rep.mismatch_rate, 4),
                       "shadow_failed": rep.shadow_failed},
            "degraded": self.metrics.degraded_total.value,
            "rollbacks": self.metrics.rollbacks_total.value,
        }
