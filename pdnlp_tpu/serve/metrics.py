"""Serving observability: one object the engine/batcher/offline paths share.

Built from the primitives in ``pdnlp_tpu.utils.metrics`` (Counter / Gauge /
Histogram).  ``snapshot()`` returns a plain-JSON dict in the same artifact
style as the training results under ``results/`` — ``bench.py --serve``
writes one as ``results/serve_smoke.json``.

What each instrument answers:

- ``request_latency_ms`` — end-to-end submit->result time per request
  (p50/p95/p99: the SLO numbers);
- ``queue_wait_ms`` — how long requests sat before their batch flushed
  (separates batching delay from compute);
- ``queue_depth`` — instantaneous queued-request gauge (backpressure health);
- ``batch_occupancy`` — per executed batch, the fraction of paid-for
  accelerator slots doing real work: real rows / padded rows on the padded
  path, real TOKENS / (rows x width) token slots on the packed path (a
  packed batch always uses every row, so row units would pin it at 1.0 —
  token slots are the unit that stays honest across both paths);
- ``fill_ratio`` / ``padding_waste`` — token-level accounting for every
  executed batch on BOTH paths: real tokens / total token slots, and its
  complement (the fraction of the forward burned on padding — the number
  packed serving exists to crush);
- ``queue_tokens`` — instantaneous queued REAL-token gauge (the packed
  flush policy and token-unit admission operate in this unit);
- ``cache_hits`` / ``cache_misses`` — engine compiled-shape cache: a miss is
  the first call at a ``(bucket, rows)`` shape, a hit is every later one;
- ``retraces`` — times the jitted forward actually re-traced; after warmup
  this must stay FLAT (the acceptance bar for the serve smoke);
- ``requests_total`` / ``rejected_total`` / ``deadline_expired_total`` —
  admission accounting (rejects = backpressure, expiries = shed load).

The multi-replica router adds :class:`RouterMetrics` (pool-level: per-tier
admission counts, requeues/retries/hedges, ejections, swap + recovery
accounting) and :class:`ReplicaMetrics` (replica-labelled queue depth,
occupancy, requeue/retry/ejection counters) — composed by
``ReplicaRouter.snapshot()`` into the ``bench.py --serve-load`` report.
"""
from __future__ import annotations

import json
import os
from typing import Dict

from pdnlp_tpu.utils.metrics import Counter, Gauge, Histogram


class ServeMetrics:
    def __init__(self) -> None:
        self.request_latency_ms = Histogram()
        self.queue_wait_ms = Histogram()
        self.batch_occupancy = Histogram()
        self.fill_ratio = Histogram()
        self.padding_waste = Histogram()
        self.queue_depth = Gauge()
        self.queue_tokens = Gauge()
        self.cache_hits = Counter()
        self.cache_misses = Counter()
        self.retraces = Counter()
        self.requests_total = Counter()
        self.rejected_total = Counter()
        self.deadline_expired_total = Counter()
        self.batches_total = Counter()

    def snapshot(self) -> Dict:
        """JSON-ready state of every instrument (plain floats/ints only)."""
        return {
            "requests_total": self.requests_total.value,
            "rejected_total": self.rejected_total.value,
            "deadline_expired_total": self.deadline_expired_total.value,
            "batches_total": self.batches_total.value,
            "queue_depth": self.queue_depth.value,
            "queue_tokens": self.queue_tokens.value,
            "request_latency_ms": self.request_latency_ms.snapshot(),
            "queue_wait_ms": self.queue_wait_ms.snapshot(),
            "batch_occupancy": self.batch_occupancy.snapshot(),
            "fill_ratio": self.fill_ratio.snapshot(),
            "padding_waste": self.padding_waste.snapshot(),
            "compile_cache": {
                "hits": self.cache_hits.value,
                "misses": self.cache_misses.value,
                "retraces": self.retraces.value,
            },
        }

    def save(self, path: str) -> None:
        """Atomic JSON dump (the ``results/`` artifact convention)."""
        _save_json(self.snapshot(), path)


def _save_json(obj: Dict, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2)
    os.replace(tmp, path)


class ReplicaMetrics:
    """One replica's share of the router's observability — every instrument
    is replica-labelled in the snapshot so a sick replica is visible as
    ITSELF, not as a pool-average smear:

    - ``queue_depth`` / ``inflight`` — where that replica's backlog stands;
    - ``batch_occupancy`` — slot accounting for batches IT executed (real
      rows / padded rows padded, real tokens / token slots packed — token
      units, so a packed replica can never read >1.0 or permanently low);
    - ``fill_ratio`` — token-level fill of its executed batches (both
      paths: real tokens / rows x width);
    - ``batches_total`` / ``requests_total`` — dispatch volume;
    - ``requeued_out`` — requests moved OFF this replica at ejection (the
      "ejected without dropping its queued requests" receipt);
    - ``requeued_in`` — requests it absorbed from ejected peers;
    - ``retries`` — failed-batch requests it re-dispatched after a replica
      failure;
    - ``ejections`` — times this slot's replica was ejected (dead/stalled).

    Generative decoding adds the slot view (the decode engine's unit of
    capacity is a KV-cache SLOT, not a queue row):

    - ``slot_occupancy`` — per decode step, live slots / usable slots:
      the continuous-batching health number (streams joining freed slots
      between steps is what keeps it near 1.0 under load);
    - ``slot_reuse_ms`` — freed-slot reuse latency: how long a slot a
      finished stream vacated sat idle before a waiting stream claimed it
      (the online analogue of packing's fill ratio — high occupancy with
      slow reuse means admission, not capacity, is the bottleneck).
    """

    def __init__(self) -> None:
        self.queue_depth = Gauge()
        self.inflight = Gauge()
        self.batch_occupancy = Histogram()
        self.fill_ratio = Histogram()
        self.slot_occupancy = Histogram()
        self.slot_reuse_ms = Histogram()
        self.batches_total = Counter()
        self.requests_total = Counter()
        self.requeued_out = Counter()
        self.requeued_in = Counter()
        self.retries = Counter()
        self.ejections = Counter()

    def snapshot(self) -> Dict:
        return {
            "queue_depth": self.queue_depth.value,
            "inflight": self.inflight.value,
            "batches_total": self.batches_total.value,
            "requests_total": self.requests_total.value,
            "requeued_out": self.requeued_out.value,
            "requeued_in": self.requeued_in.value,
            "retries": self.retries.value,
            "ejections": self.ejections.value,
            "batch_occupancy": self.batch_occupancy.snapshot(),
            "fill_ratio": self.fill_ratio.snapshot(),
            "slot_occupancy": self.slot_occupancy.snapshot(),
            "slot_reuse_ms": self.slot_reuse_ms.snapshot(),
        }


class DecodeMetrics:
    """Generative-decoding observability (``serve.decode``), in the units
    that tier actually optimizes — TOKENS and inter-token gaps, not
    request rows:

    - ``streams_total`` / ``rejected_total`` / ``deadline_expired_total``
      — stream admission accounting (rejects include KV-budget refusals);
    - ``prefills_total`` / ``prefill_tokens_total`` — bucketed prompt
      forwards and the prompt tokens they consumed;
    - ``decode_steps_total`` / ``tokens_out_total`` — fixed-shape decode
      dispatches and the tokens they produced (tokens/s/chip = the bench
      headline);
    - ``ttft_ms`` — submit -> first token (the prefill-visible latency);
    - ``intertoken_ms`` — gap between consecutive tokens of one stream
      (p99 is the streaming SLO ``bench.py --decode`` gates);
    - ``waiting`` — streams queued for a free slot;
    - ``kv_bytes_live`` / ``kv_slots_live`` — live KV occupancy (the
      ``--kv_hbm_mb`` budget gauge on ``/metrics``);
    - ``kv_pages_live`` / ``kv_pages_free`` — paged layout only: page
      pool occupancy and free-list depth (allocator/index detail rides
      ``kv_snapshot()``/``control_snapshot()``);
    - ``peak_live_streams`` — high-water concurrent live streams (the
      admitted-concurrency headline the paged-vs-slot bench gates).

    Speculative decoding (draft-k / verify-1) adds its acceptance
    accounting — the live signal the controller's ``draft_k`` law and
    the bench's speedup gate both read:

    - ``draft_tokens_total`` / ``accepted_tokens_total`` — tokens the
      cheap drafter proposed / tokens the primary's verify call kept
      (their ratio is the acceptance rate; every ACCEPTED token skipped
      one full primary decode step);
    - ``verify_calls_total`` / ``spec_rounds_total`` — primary verify
      dispatches and completed draft→verify rounds;
    - ``accept_rate`` — live cumulative acceptance gauge (per-stream
      counts ride the ``verify`` hops);
    - ``drafter_deaths_total`` — drafter engines lost mid-storm (each
      one degraded its pair to primary-only decode, decision-recorded).

    Disaggregated pools (prefill-role vs decode-role engines) add the
    handoff accounting — sender-side, counted when the receiver ACKED:

    - ``handoffs_total`` / ``handoff_pages_total`` /
      ``handoff_bytes_total`` — placed handoffs and the page/byte
      volume they moved between allocators;
    - ``handoff_failures_total`` — dispatches no decode engine took
      (each one re-prefilled at the sender: recovery, not loss);
    - ``handoff_ms`` — export→ack latency per handoff (the
      disaggregation tax ``bench.py --decode`` phase F budgets).
    """

    def __init__(self) -> None:
        self.streams_total = Counter()
        self.rejected_total = Counter()
        self.deadline_expired_total = Counter()
        self.prefills_total = Counter()
        self.prefill_tokens_total = Counter()
        self.decode_steps_total = Counter()
        self.tokens_out_total = Counter()
        self.draft_tokens_total = Counter()
        self.accepted_tokens_total = Counter()
        self.verify_calls_total = Counter()
        self.spec_rounds_total = Counter()
        self.drafter_deaths_total = Counter()
        self.handoffs_total = Counter()
        self.handoff_pages_total = Counter()
        self.handoff_bytes_total = Counter()
        self.handoff_failures_total = Counter()
        self.ttft_ms = Histogram()
        self.intertoken_ms = Histogram()
        self.handoff_ms = Histogram()
        self.waiting = Gauge()
        self.accept_rate = Gauge()
        self.kv_bytes_live = Gauge()
        self.kv_slots_live = Gauge()
        self.kv_pages_live = Gauge()
        self.kv_pages_free = Gauge()
        self.peak_live_streams = Gauge()

    def snapshot(self) -> Dict:
        return {
            "streams_total": self.streams_total.value,
            "rejected_total": self.rejected_total.value,
            "deadline_expired_total": self.deadline_expired_total.value,
            "prefills_total": self.prefills_total.value,
            "prefill_tokens_total": self.prefill_tokens_total.value,
            "decode_steps_total": self.decode_steps_total.value,
            "tokens_out_total": self.tokens_out_total.value,
            "draft_tokens_total": self.draft_tokens_total.value,
            "accepted_tokens_total": self.accepted_tokens_total.value,
            "verify_calls_total": self.verify_calls_total.value,
            "spec_rounds_total": self.spec_rounds_total.value,
            "drafter_deaths_total": self.drafter_deaths_total.value,
            "handoffs_total": self.handoffs_total.value,
            "handoff_pages_total": self.handoff_pages_total.value,
            "handoff_bytes_total": self.handoff_bytes_total.value,
            "handoff_failures_total": self.handoff_failures_total.value,
            "accept_rate": self.accept_rate.value,
            "ttft_ms": self.ttft_ms.snapshot(),
            "intertoken_ms": self.intertoken_ms.snapshot(),
            "handoff_ms": self.handoff_ms.snapshot(),
            "waiting": self.waiting.value,
            "kv_bytes_live": self.kv_bytes_live.value,
            "kv_slots_live": self.kv_slots_live.value,
            "kv_pages_live": self.kv_pages_live.value,
            "kv_pages_free": self.kv_pages_free.value,
            "peak_live_streams": self.peak_live_streams.value,
        }


class FleetMetrics:
    """Fleet-front-door observability (``FleetRouter``): how the traffic
    policy split the caller stream across models.  Per-model serving
    metrics stay on each group's own :class:`RouterMetrics`/
    :class:`ReplicaMetrics` — the fleet snapshot keys those by model id so
    the exporter can label them — and THESE counters are the policy's own
    receipts:

    - ``requests_total`` — caller submissions through the fleet door;
    - ``canary_routed_total`` — caller requests the canary fraction sent
      to the candidate (their answers ARE the candidate's);
    - ``shadows_total`` / ``shadow_dropped_total`` — shadow duplicates
      admitted on the candidate / refused at its door (callers unaffected
      either way);
    - ``degraded_total`` — degrade-band arrivals re-routed to the cheap
      model instead of shed;
    - ``degrade_fallthrough_total`` — degrade-band arrivals with NO cheap
      model registered (fell through to the shed tier, loudly);
    - ``rollbacks_total`` / ``rolled_back_requests_total`` — canary
      rollback events / requests drained candidate -> primary by them.
    """

    def __init__(self) -> None:
        self.requests_total = Counter()
        self.canary_routed_total = Counter()
        self.shadows_total = Counter()
        self.shadow_dropped_total = Counter()
        self.degraded_total = Counter()
        self.degrade_fallthrough_total = Counter()
        self.rollbacks_total = Counter()
        self.rolled_back_requests_total = Counter()

    def snapshot(self) -> Dict:
        return {
            "requests_total": self.requests_total.value,
            "canary_routed_total": self.canary_routed_total.value,
            "shadows_total": self.shadows_total.value,
            "shadow_dropped_total": self.shadow_dropped_total.value,
            "degraded_total": self.degraded_total.value,
            "degrade_fallthrough_total":
                self.degrade_fallthrough_total.value,
            "rollbacks_total": self.rollbacks_total.value,
            "rolled_back_requests_total":
                self.rolled_back_requests_total.value,
        }


class RouterMetrics:
    """Pool-level router observability: admission tiers, failure handling,
    and the recovery loop.  Per-tier shed accounting
    (``admission`` block: backpressure waits / sheds / hard rejects) is
    what the ``bench.py --serve-load`` report gates on — "tiered shedding
    engaged" must be a recorded number, not an inference."""

    def __init__(self) -> None:
        self.requests_total = Counter()
        self.completed_total = Counter()
        self.failed_total = Counter()          # completed with a non-
        #                                        deadline error (lost)
        self.deadline_expired_total = Counter()
        self.backpressure_waits_total = Counter()
        self.shed_total = Counter()
        self.rejected_total = Counter()
        self.requeued_total = Counter()
        self.retries_total = Counter()
        self.hedges_total = Counter()
        self.ejections_total = Counter()
        self.reintegrations_total = Counter()
        self.swaps_total = Counter()
        self.swap_rollbacks_total = Counter()
        self.scale_downs_total = Counter()     # control plane: healthy ->
        self.scale_ups_total = Counter()       # warm standby and back
        self.queue_depth = Gauge()             # pool-wide pending
        self.request_latency_ms = Histogram()
        self.queue_wait_ms = Histogram()
        self.backpressure_wait_ms = Histogram()
        self.recovery_sec = Histogram()        # ejection -> healthy again

    def snapshot(self) -> Dict:
        return {
            "requests_total": self.requests_total.value,
            "completed_total": self.completed_total.value,
            "failed_total": self.failed_total.value,
            "deadline_expired_total": self.deadline_expired_total.value,
            "admission": {
                "backpressure_waits": self.backpressure_waits_total.value,
                "shed": self.shed_total.value,
                "rejected": self.rejected_total.value,
            },
            "requeued_total": self.requeued_total.value,
            "retries_total": self.retries_total.value,
            "hedges_total": self.hedges_total.value,
            "ejections_total": self.ejections_total.value,
            "reintegrations_total": self.reintegrations_total.value,
            "swaps_total": self.swaps_total.value,
            "swap_rollbacks_total": self.swap_rollbacks_total.value,
            "scale_downs_total": self.scale_downs_total.value,
            "scale_ups_total": self.scale_ups_total.value,
            "queue_depth": self.queue_depth.value,
            "request_latency_ms": self.request_latency_ms.snapshot(),
            "queue_wait_ms": self.queue_wait_ms.snapshot(),
            "backpressure_wait_ms": self.backpressure_wait_ms.snapshot(),
            "recovery_sec": self.recovery_sec.snapshot(),
        }

    def save(self, path: str) -> None:
        _save_json(self.snapshot(), path)
