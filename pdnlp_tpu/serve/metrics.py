"""Serving observability: one object the engine/batcher/offline paths share.

Built from the primitives in ``pdnlp_tpu.utils.metrics`` (Counter / Gauge /
Histogram).  ``snapshot()`` returns a plain-JSON dict in the same artifact
style as the training results under ``results/`` — ``bench.py --serve``
writes one as ``results/serve_smoke.json``.

What each instrument answers:

- ``request_latency_ms`` — end-to-end submit->result time per request
  (p50/p95/p99: the SLO numbers);
- ``queue_wait_ms`` — how long requests sat before their batch flushed
  (separates batching delay from compute);
- ``queue_depth`` — instantaneous queued-request gauge (backpressure health);
- ``batch_occupancy`` — real rows / padded rows per executed batch (how much
  accelerator work is filler; 1.0 = perfectly packed);
- ``cache_hits`` / ``cache_misses`` — engine compiled-shape cache: a miss is
  the first call at a ``(bucket, rows)`` shape, a hit is every later one;
- ``retraces`` — times the jitted forward actually re-traced; after warmup
  this must stay FLAT (the acceptance bar for the serve smoke);
- ``requests_total`` / ``rejected_total`` / ``deadline_expired_total`` —
  admission accounting (rejects = backpressure, expiries = shed load).
"""
from __future__ import annotations

import json
import os
from typing import Dict

from pdnlp_tpu.utils.metrics import Counter, Gauge, Histogram


class ServeMetrics:
    def __init__(self) -> None:
        self.request_latency_ms = Histogram()
        self.queue_wait_ms = Histogram()
        self.batch_occupancy = Histogram()
        self.queue_depth = Gauge()
        self.cache_hits = Counter()
        self.cache_misses = Counter()
        self.retraces = Counter()
        self.requests_total = Counter()
        self.rejected_total = Counter()
        self.deadline_expired_total = Counter()
        self.batches_total = Counter()

    def snapshot(self) -> Dict:
        """JSON-ready state of every instrument (plain floats/ints only)."""
        return {
            "requests_total": self.requests_total.value,
            "rejected_total": self.rejected_total.value,
            "deadline_expired_total": self.deadline_expired_total.value,
            "batches_total": self.batches_total.value,
            "queue_depth": self.queue_depth.value,
            "request_latency_ms": self.request_latency_ms.snapshot(),
            "queue_wait_ms": self.queue_wait_ms.snapshot(),
            "batch_occupancy": self.batch_occupancy.snapshot(),
            "compile_cache": {
                "hits": self.cache_hits.value,
                "misses": self.cache_misses.value,
                "retraces": self.retraces.value,
            },
        }

    def save(self, path: str) -> None:
        """Atomic JSON dump (the ``results/`` artifact convention)."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(), f, indent=2)
        os.replace(tmp, path)
