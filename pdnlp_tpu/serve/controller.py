"""Feedback control plane for the serving tier: the sensors grow reflexes.

PR 10 built a full telemetry plane — per-request hop chains, live
``/metrics``, HBM accounting — and nothing *acted* on it: PR 8's admission
thresholds, PR 9's packed flush age, the hedge bound and the replica count
were all still hand-set constants.  A system serving real traffic cannot
page a human to retune ``hedge_ms`` when the arrival shape changes, and a
*robust* one must notice when its own actuation made things worse and undo
it.  :class:`ServeController` closes the loop:

    sense -> decide -> actuate -> evaluate -> (auto-revert)

- **sense**: one ``router.snapshot()`` per tick, reduced to windowed rates
  (arrival, deadline-miss, shed, reject, backpressure), the latency p99,
  and a queue-pressure utilization EWMA;
- **decide**: small, explainable control laws per knob — ``hedge_ms``
  tracks a multiple of observed p99; the flush age (``max_wait_ms``)
  tracks the observed arrival rate (slow traffic earns a longer age so
  batches fill, storms earn a short one so latency holds); the admission
  ladder (``backpressure_at``) tightens under deadline-miss/shed pressure
  and relaxes back when the pool is clean; the **replica count** drains a
  replica to a warm standby when utilization stays low and reactivates it
  through the router's warmup-gated path when load returns (never below
  ``min_replicas``);
- **actuate**: every write — no exceptions — passes through the
  :meth:`_actuate` choke point (jaxlint R13 flags any other path), which
  enforces the knob's **clamp range**, a per-knob **cooldown**, the
  decide-side **hysteresis band** (no oscillation), and any active
  **backoff hold**, then records a hop-style **decision record**
  (:mod:`pdnlp_tpu.obs.decision`: cause metrics -> action -> old/new) so
  ``trace_tpu.py decisions`` can explain why capacity changed;
- **evaluate / revert**: every actuation opens an evaluation window over
  the SLO signal it was meant to improve; a change whose signal regressed
  past the revert margin is **auto-reverted** and the knob enters a
  capped-exponential **backoff hold** (the PR-7 supervisor's backoff
  discipline applied to control decisions).  The revert itself is a
  recorded decision chained to the original via ``revert_of``.

The controller never takes the router down: a failing tick is counted and
skipped, actuation errors surface in :meth:`snapshot` (the exporter's
``controller`` source), and :meth:`stop` resolves every pending
evaluation so flushed traces always validate.

Proving ground: ``bench.py --replay`` replays recorded arrival processes
(:mod:`pdnlp_tpu.serve.replay`) through controller-vs-static pools across
steady / diurnal-ramp / flash-crowd shapes with a mid-storm replica kill,
and gates that the controller wins the p99 x throughput frontier while
auto-reverting an injected bad actuation.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from pdnlp_tpu.obs.decision import mint_decision_id, record_decision
from pdnlp_tpu.serve.fleet import RolloutPlan  # noqa: F401 — the rollout
#   law's config type (re-exported so callers configure rollouts from the
#   controller module they already import)


class KnobSpec:
    """Safe range + anti-oscillation policy for one tunable knob."""

    __slots__ = ("name", "lo", "hi", "cooldown_s", "hysteresis",
                 "signal", "noise_floor", "integer")

    def __init__(self, name: str, lo: float, hi: float, *,
                 cooldown_s: float = 10.0, hysteresis: float = 0.25,
                 signal: str = "p99_ms", noise_floor: float = 0.0,
                 integer: bool = False):
        self.name = name
        self.lo = lo
        self.hi = hi
        self.cooldown_s = float(cooldown_s)
        #: minimum RELATIVE change decide() must want before an actuation
        #: is considered at all — the no-flap band
        self.hysteresis = float(hysteresis)
        #: the SLO signal an actuation of this knob is judged against
        self.signal = signal
        #: absolute signal slack added to the revert margin (percentile
        #: jitter on a quiet pool must not read as a regression)
        self.noise_floor = float(noise_floor)
        self.integer = bool(integer)

    def clamp(self, value: float) -> float:
        v = min(self.hi, max(self.lo, value))
        return int(round(v)) if self.integer else float(v)


def default_specs() -> Dict[str, KnobSpec]:
    """The declared safe ranges (README "Control plane" table)."""
    return {
        "hedge_ms": KnobSpec("hedge_ms", 5.0, 2000.0, cooldown_s=10.0,
                             hysteresis=0.25, signal="p99_ms",
                             noise_floor=5.0),
        "max_wait_ms": KnobSpec("max_wait_ms", 1.0, 250.0, cooldown_s=5.0,
                                hysteresis=0.3, signal="p99_ms",
                                noise_floor=5.0),
        "backpressure_at": KnobSpec("backpressure_at", 1, 10 ** 9,
                                    cooldown_s=10.0, hysteresis=0.2,
                                    signal="slo_pressure",
                                    noise_floor=0.02, integer=True),
        "shed_slack_ms": KnobSpec("shed_slack_ms", 1.0, 1000.0,
                                  cooldown_s=10.0, hysteresis=0.2,
                                  signal="slo_pressure",
                                  noise_floor=0.02),
        # evaluated against p99: a bad scale-DOWN shows up as queueing
        # latency long before it shows up as misses/sheds (scale-UPS are
        # never revert candidates — see _evaluate)
        "replicas": KnobSpec("replicas", 1, 64, cooldown_s=15.0,
                             hysteresis=0.0, signal="p99_ms",
                             noise_floor=5.0, integer=True),
        # the fleet's canary traffic fraction: hysteresis 0 so the small
        # first rollout step (0.05) actuates; judged against p99 like a
        # scale change (the rollout law's OWN parity/latency regression
        # check is the primary rollback trigger — the eval window is the
        # second line of defense)
        "canary_fraction": KnobSpec("canary_fraction", 0.0, 1.0,
                                    cooldown_s=5.0, hysteresis=0.0,
                                    signal="p99_ms", noise_floor=5.0),
        # speculative decoding's draft depth: judged against spec_waste
        # (1 - acceptance, "bad is high" like every revert signal) so a
        # k the drafter cannot cash auto-reverts; hysteresis 0 because
        # the law moves in single integer steps
        "draft_k": KnobSpec("draft_k", 0, 8, cooldown_s=5.0,
                            hysteresis=0.0, signal="spec_waste",
                            noise_floor=0.05, integer=True),
        # the disaggregated pool split (fraction of engines in the
        # prefill role): the law moves in whole-engine quanta (the
        # router's prefill_share_step), so hysteresis 0; the actuation
        # is judged against the signal the DIRECTION it moved puts at
        # risk (growing prefill starves decode -> inter_token_p99_ms,
        # shrinking starves prefill -> ttft_p99_ms) — the law passes
        # the signal explicitly, this default covers injected writes
        "prefill_share": KnobSpec("prefill_share", 0.1, 0.9,
                                  cooldown_s=10.0, hysteresis=0.0,
                                  signal="ttft_p99_ms",
                                  noise_floor=5.0),
    }


class _Sense:
    """One tick's reduced telemetry (plain attrs; JSON-able via vars())."""

    def __init__(self, **kw):
        self.t: float = kw.get("t", 0.0)
        self.arrival_rate: Optional[float] = kw.get("arrival_rate")
        self.miss_rate: Optional[float] = kw.get("miss_rate")
        self.shed_rate: Optional[float] = kw.get("shed_rate")
        self.reject_rate: Optional[float] = kw.get("reject_rate")
        self.backpressure_rate: Optional[float] = kw.get(
            "backpressure_rate")
        self.p99_ms: Optional[float] = kw.get("p99_ms")
        self.queue_depth: float = kw.get("queue_depth", 0.0)
        self.util: Optional[float] = kw.get("util")
        self.active: int = kw.get("active", 0)
        self.standby: int = kw.get("standby", 0)
        #: windowed speculative-decoding acceptance (accepted/drafted
        #: over this tick's counter delta; None = no drafting happened)
        self.accept_rate: Optional[float] = kw.get("accept_rate")
        #: disaggregated pools: the two latency signals the pool-split
        #: law trades off (blending them into one p99 would hide the
        #: tradeoff the split exists to move), plus per-pool pressure
        self.ttft_p99_ms: Optional[float] = kw.get("ttft_p99_ms")
        self.inter_token_p99_ms: Optional[float] = kw.get(
            "inter_token_p99_ms")
        self.prefill_backlog: Optional[float] = kw.get("prefill_backlog")
        self.decode_backlog: Optional[float] = kw.get("decode_backlog")
        self.knobs: Dict = kw.get("knobs", {})

    @property
    def spec_waste(self) -> Optional[float]:
        """Fraction of drafted tokens the verify call threw away —
        speculation's "bad is high" signal (the ``draft_k`` knob's
        revert judge)."""
        if self.accept_rate is None:
            return None
        return 1.0 - self.accept_rate

    @property
    def slo_pressure(self) -> Optional[float]:
        """The request-weighted fraction of traffic the pool is failing
        (deadline misses + sheds + rejects) — the admission and scaling
        laws' composite signal."""
        parts = [self.miss_rate, self.shed_rate, self.reject_rate]
        if all(p is None for p in parts):
            return None
        return sum(p or 0.0 for p in parts)

    def signal(self, key: str) -> Optional[float]:
        if key == "slo_pressure":
            return self.slo_pressure
        return getattr(self, key, None)

    def as_dict(self) -> Dict:
        out = {k: v for k, v in vars(self).items() if k != "knobs"}
        out["slo_pressure"] = self.slo_pressure
        out["spec_waste"] = self.spec_waste
        return out


class _PendingEval:
    """One actuation awaiting its evaluation-window verdict."""

    __slots__ = ("did", "knob", "old", "new", "signal", "baseline",
                 "t_eval", "revert_of")

    def __init__(self, did, knob, old, new, signal, baseline, t_eval,
                 revert_of):
        self.did = did
        self.knob = knob
        self.old = old
        self.new = new
        self.signal = signal
        self.baseline = baseline
        self.t_eval = t_eval
        self.revert_of = revert_of


class ServeController:
    """The serve tier's feedback controller (module docstring).

    ``router`` needs the :class:`~pdnlp_tpu.serve.router.ReplicaRouter`
    tuning surface: ``snapshot()``, ``apply_knob``/``knob_values``,
    ``deactivate_replica``/``activate_replica``, ``active_count``/
    ``standby_count`` — a test double with those quacks fine.  ``clock``
    is injectable; :meth:`step` runs one full tick without the thread, so
    the control laws are testable without sleeping.
    """

    def __init__(self, router, *,
                 interval_s: float = 1.0,
                 min_replicas: int = 1,
                 specs: Optional[Dict[str, KnobSpec]] = None,
                 eval_window_s: float = 10.0,
                 revert_margin: float = 0.2,
                 hold_base_s: float = 30.0,
                 hold_cap_s: float = 480.0,
                 hedge_factor: float = 2.0,
                 manage_hedge: Optional[bool] = None,
                 manage_flush: bool = True,
                 manage_admission: bool = True,
                 fill_fraction: float = 0.5,
                 wait_budget_ms: Optional[float] = 50.0,
                 pressure_hi: float = 0.05,
                 pressure_lo: float = 0.005,
                 util_low: float = 0.15,
                 util_high: float = 0.75,
                 util_batch: float = 0.5,
                 accept_floor: float = 0.35,
                 accept_high: float = 0.85,
                 spec_patience: int = 2,
                 split_patience: int = 2,
                 split_backlog_min: float = 2.0,
                 scale_patience: int = 3,
                 ewma_alpha: float = 0.4,
                 batch_rows: Optional[int] = None,
                 rollout: Optional[RolloutPlan] = None,
                 clock: Callable[[], float] = time.monotonic,
                 tracer=None):
        self.router = router
        self.interval_s = float(interval_s)
        self.min_replicas = max(1, int(min_replicas))
        self.specs = dict(default_specs())
        if specs:
            self.specs.update(specs)
        self.specs["replicas"].lo = self.min_replicas
        slots = getattr(router, "_slots", None)
        if slots is not None:
            self.specs["replicas"].hi = len(slots)
        self.eval_window_s = float(eval_window_s)
        self.revert_margin = float(revert_margin)
        self.hold_base_s = float(hold_base_s)
        self.hold_cap_s = float(hold_cap_s)
        self.hedge_factor = float(hedge_factor)
        # hedging is managed only where it is wired at all: a router
        # launched with hedge_ms=None (hedging off) keeps it off unless
        # explicitly opted in
        self.manage_hedge = (router.knob_values().get("hedge_ms")
                             is not None if manage_hedge is None
                             else bool(manage_hedge))
        self.manage_flush = bool(manage_flush)
        self.manage_admission = bool(manage_admission)
        self.fill_fraction = float(fill_fraction)
        #: cap on the flush age the arrival law may ask for — batching
        #: never buys latency past the point a deadline-bound service can
        #: afford (the clamp range is the SAFE bound; this is the law's
        #: SENSIBLE bound, and the gap between the two is exactly where
        #: the bad-actuation probe injects)
        self.wait_budget_ms = (None if wait_budget_ms is None
                               else float(wait_budget_ms))
        self.pressure_hi = float(pressure_hi)
        self.pressure_lo = float(pressure_lo)
        self.util_low = float(util_low)
        self.util_high = float(util_high)
        #: below this utilization the flush-age law floors the age:
        #: batches execute as FIXED padded shapes, so waiting to fill rows
        #: only pays when the pool actually needs the capacity — an idle
        #: pool should trade its abundant rows for latency, not the
        #: reverse
        self.util_batch = float(util_batch)
        #: speculation law bands: below the floor for ``spec_patience``
        #: consecutive ticks the drafter is wasting its k (halve it /
        #: switch speculation off); above the high band the drafter is
        #: cashing almost everything (a deeper k is free upside)
        self.accept_floor = float(accept_floor)
        self.accept_high = float(accept_high)
        self.spec_patience = int(spec_patience)
        self._spec_low_ticks = 0
        #: pool-split law: sustained one-sided backlog pressure (at least
        #: ``split_backlog_min`` more queued streams than the other pool)
        #: for ``split_patience`` consecutive ticks earns one whole-engine
        #: re-role; the signed counter means flapping pressure resets it
        self.split_patience = int(split_patience)
        self.split_backlog_min = float(split_backlog_min)
        self._split_ticks = 0
        self.scale_patience = int(scale_patience)
        self.ewma_alpha = float(ewma_alpha)
        self.batch_rows = int(batch_rows
                              if batch_rows is not None
                              else getattr(router, "max_batch_size", 8))
        self.clock = clock
        self.tracer = tracer if tracer is not None \
            else getattr(router, "tracer", None)

        #: the canary-rollout law's config (None = no rollout management;
        #: also requires the router to BE a fleet — rollout_sense() is the
        #: FleetRouter surface the law reads)
        self.rollout = rollout
        self._rollout_ticks = 0
        self._rollout_aborted = False
        self.rollbacks_total = 0
        knobs0 = router.knob_values()
        self._default_backpressure_at = knobs0.get("backpressure_at")
        self._default_shed_slack_ms = knobs0.get("shed_slack_ms")
        self._prev_counters: Optional[Dict] = None
        self._prev_t: Optional[float] = None
        self._util_ew: Optional[float] = None
        self._low_ticks = 0
        self._pending: List[_PendingEval] = []
        self._last_actuated: Dict[str, float] = {}
        self._last_did: Dict[str, str] = {}  # per-knob latest decision id
        self._hold_until: Dict[str, float] = {}
        self._strikes: Dict[str, int] = {}
        self.last_sense: Optional[_Sense] = None
        self.actuations_total = 0
        self.reverts_total = 0
        self.blocked_total = 0     # cooldown/hold/clamp-no-op refusals
        self.errors_total = 0
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()   # protects _pending vs snapshot()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ServeController":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="pdnlp-serve-controller")
            self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the loop and RESOLVE every pending evaluation (outcome
        ``shutdown``) — a flushed trace must never carry an action without
        an outcome."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        with self._lock:
            pending, self._pending = self._pending, []
        sense = self.last_sense
        for p in pending:
            observed = sense.signal(p.signal) if sense is not None else None
            self._record_outcome(p, "shutdown", observed)

    def __enter__(self) -> "ServeController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — the control plane
                # must never take the serving tier down with it
                self.errors_total += 1
                self.last_error = f"{type(e).__name__}: {e}"

    # ---------------------------------------------------------------- sense
    def step(self) -> Optional[_Sense]:
        """One full control tick: sense -> evaluate pending -> decide ->
        actuate.  Public so tests (and the bench) can drive the loop with
        an injected clock instead of the thread."""
        sense = self._sense()
        if sense is None:
            return None  # first tick primes the counter deltas only
        self.last_sense = sense
        self._evaluate(sense)
        self._decide(sense)
        return sense

    def _sense(self) -> Optional[_Sense]:
        # prefer the router's lightweight control_snapshot: the full
        # snapshot copies every per-replica histogram window, and at a
        # sub-second control interval that steals real time from the
        # serving workers it is supposed to be helping
        snap_fn = getattr(self.router, "control_snapshot", None) \
            or self.router.snapshot
        snap = snap_fn()
        now = self.clock()
        r = snap.get("router", {})
        adm = r.get("admission", {})
        spec = snap.get("speculation") or {}
        counters = {
            "requests": r.get("requests_total", 0),
            "deadline": r.get("deadline_expired_total", 0),
            "shed": adm.get("shed", 0),
            "rejected": adm.get("rejected", 0),
            "backpressure": adm.get("backpressure_waits", 0),
            "draft_tokens": spec.get("draft_tokens", 0),
            "accepted_tokens": spec.get("accepted_tokens", 0),
        }
        prev, prev_t = self._prev_counters, self._prev_t
        self._prev_counters, self._prev_t = counters, now
        if prev is None or prev_t is None or now <= prev_t:
            return None
        dt = now - prev_t
        d = {k: counters[k] - prev[k] for k in counters}
        # arrival rate = admissions + hard rejects; sheds are deliberately
        # EXCLUDED — shed_total mixes arrival sheds (not in
        # requests_total) with shed-while-queued (already counted at
        # admit), and double-counting the latter would inflate the
        # arrival rate exactly when the pool is shedding, pushing the
        # flush-age law toward shorter waits mid-overload
        arrived = d["requests"] + d["rejected"]
        per_req = max(1.0, float(arrived))
        lat = r.get("request_latency_ms", {}) or {}
        # disaggregated routers surface the split latency signals and a
        # per-pool pressure block; absent on every other router shape
        lat2 = snap.get("latency") or {}
        pools = snap.get("by_pool") or {}
        active = snap.get("active",
                          getattr(self.router, "active_count", 1))
        queue_depth = float(r.get("queue_depth", 0.0))
        util = queue_depth / max(1.0, active * self.batch_rows)
        a = self.ewma_alpha
        self._util_ew = util if self._util_ew is None \
            else a * util + (1 - a) * self._util_ew
        return _Sense(
            t=now,
            arrival_rate=arrived / dt,
            miss_rate=d["deadline"] / per_req,
            shed_rate=d["shed"] / per_req,
            reject_rate=d["rejected"] / per_req,
            backpressure_rate=d["backpressure"] / per_req,
            p99_ms=lat.get("p99"),
            queue_depth=queue_depth,
            util=self._util_ew,
            active=active,
            standby=snap.get("standby",
                             getattr(self.router, "standby_count", 0)),
            # no eager default: knob_values() takes the pool lock, and
            # control_snapshot already carries the knobs on every tick
            knobs=(snap["knobs"] if "knobs" in snap
                   else self.router.knob_values()),
            # windowed acceptance: this tick's drafted/accepted deltas,
            # not the lifetime ratio — a drafter that goes cold must show
            # up within spec_patience ticks, and a cumulative rate
            # converges far too slowly for that
            accept_rate=(d["accepted_tokens"] / d["draft_tokens"]
                         if d["draft_tokens"] > 0 else None),
            ttft_p99_ms=lat2.get("ttft_p99_ms"),
            inter_token_p99_ms=lat2.get("inter_token_p99_ms"),
            prefill_backlog=(pools.get("prefill") or {}).get("backlog"),
            decode_backlog=(pools.get("decode") or {}).get("backlog"),
        )

    # --------------------------------------------------------------- decide
    def _decide(self, s: _Sense) -> None:
        cause = {k: round(v, 6) for k, v in s.as_dict().items()
                 if isinstance(v, (int, float))}
        self._decide_hedge(s, cause)
        self._decide_flush_age(s, cause)
        self._decide_admission(s, cause)
        self._decide_replicas(s, cause)
        self._decide_speculation(s, cause)
        self._decide_pool_split(s, cause)
        self._decide_rollout(s, cause)

    def _wants(self, knob: str, current, target) -> bool:
        """The decide-side hysteresis band: only a relative change beyond
        the knob's band is worth actuating (no oscillation around the
        setpoint)."""
        spec = self.specs[knob]
        if current is None:
            return True
        cur = float(current)
        if cur == 0:
            return target != 0
        return abs(float(target) - cur) / abs(cur) > spec.hysteresis

    def _decide_hedge(self, s: _Sense, cause: Dict) -> None:
        if not self.manage_hedge or s.p99_ms is None:
            return
        target = self.specs["hedge_ms"].clamp(self.hedge_factor * s.p99_ms)
        if self._wants("hedge_ms", s.knobs.get("hedge_ms"), target):
            self._actuate("hedge_ms", target, cause)

    def _decide_flush_age(self, s: _Sense, cause: Dict) -> None:
        if not self.manage_flush or not s.arrival_rate:
            return
        # batching buys CAPACITY (batches execute as fixed padded shapes,
        # so per-batch cost is flat in real rows) at the price of waiting.
        # Under low utilization capacity is abundant — flush immediately.
        # Once the pool is working for a living, wait a fraction of the
        # observed batch fill time (arrival-rate tracked), capped by the
        # wait budget a deadline-bound service can afford.
        if s.util is not None and s.util < self.util_batch:
            target_ms = self.specs["max_wait_ms"].lo
        else:
            per_replica = s.arrival_rate / max(1, s.active)
            fill_s = self.batch_rows / max(per_replica, 1e-6)
            target_ms = 1e3 * self.fill_fraction * fill_s
            if self.wait_budget_ms is not None:
                target_ms = min(target_ms, self.wait_budget_ms)
        target = self.specs["max_wait_ms"].clamp(target_ms)
        if self._wants("max_wait_ms", s.knobs.get("max_wait_ms"), target):
            self._actuate("max_wait_ms", target, cause)

    def _decide_admission(self, s: _Sense, cause: Dict) -> None:
        if not self.manage_admission:
            return
        pressure = s.slo_pressure
        if pressure is None:
            return
        cur = s.knobs.get("backpressure_at")
        if cur is not None:
            spec = self.specs["backpressure_at"]
            shed_at = s.knobs.get("shed_at")
            hi = min(spec.hi, shed_at if shed_at is not None else spec.hi,
                     self._default_backpressure_at or spec.hi)
            if pressure > self.pressure_hi:
                # failing traffic: convert bursts to latency earlier
                target = max(spec.lo, int(cur * 0.75))
            elif pressure < self.pressure_lo and cur < hi:
                # clean pool: relax back toward the configured default
                target = min(hi, max(cur + 1, int(cur * 1.25)))
            else:
                target = cur
            if target != cur and self._wants("backpressure_at", cur,
                                             target):
                self._actuate("backpressure_at", target, cause)
        # the shed tier's viability floor rides the same pressure signal:
        # when deadline-miss/shed rates say the pool is failing traffic,
        # raise the floor so doomed work is dropped EARLIER (freeing
        # capacity for requests that can still make it); decay back
        # toward the configured default when the pool runs clean
        slack = s.knobs.get("shed_slack_ms")
        if slack is not None:
            sspec = self.specs["shed_slack_ms"]
            default = self._default_shed_slack_ms or sspec.lo
            if pressure > self.pressure_hi:
                target = sspec.clamp(max(slack * 1.5, default))
            elif pressure < self.pressure_lo and slack > default:
                target = sspec.clamp(max(default, slack / 1.5))
            else:
                target = slack
            if target != slack and self._wants("shed_slack_ms", slack,
                                               target):
                self._actuate("shed_slack_ms", target, cause)

    def _decide_replicas(self, s: _Sense, cause: Dict) -> None:
        pressure = s.slo_pressure or 0.0
        rising = (s.util is not None and s.util > self.util_high) \
            or (s.backpressure_rate or 0.0) > 0 \
            or pressure > self.pressure_hi
        if rising and s.standby > 0:
            self._low_ticks = 0
            self._actuate("replicas", s.active + 1, cause)
            return
        low = (s.util is not None and s.util < self.util_low
               and (s.backpressure_rate or 0.0) == 0
               and pressure <= self.pressure_lo)
        if low and s.active > self.min_replicas:
            self._low_ticks += 1
            if self._low_ticks >= self.scale_patience:
                self._low_ticks = 0
                self._actuate("replicas", s.active - 1, cause)
        else:
            self._low_ticks = 0

    def _decide_speculation(self, s: _Sense, cause: Dict) -> None:
        """The speculation law: the drafter earns its k or loses it.

        Windowed acceptance below ``accept_floor`` for ``spec_patience``
        consecutive ticks means the cheap model is drafting tokens the
        primary keeps refusing — every rejected draft is a wasted drafter
        step AND a wasted verify column, so halve k (switch speculation
        off entirely when acceptance is catastrophic or k is already at
        1).  Acceptance above ``accept_high`` means nearly every draft is
        landing: a deeper k is close-to-free upside, step it up by one.
        Both moves route through :meth:`_actuate`, so they are clamped to
        the ``draft_k`` spec, hold-off/cooldown gated, decision-recorded,
        and auto-revert-eligible on ``spec_waste`` regression.
        ``accept_rate is None`` (no drafting happened in the window —
        speculation off or traffic idle) ticks the law to a standstill:
        re-enable is the revert path's job, not a blind retry."""
        cur = s.knobs.get("draft_k")
        if s.accept_rate is None or cur is None or cur <= 0:
            self._spec_low_ticks = 0
            return
        cur = int(cur)
        if s.accept_rate < self.accept_floor:
            self._spec_low_ticks += 1
            if self._spec_low_ticks >= self.spec_patience:
                self._spec_low_ticks = 0
                target = 0 if (s.accept_rate < self.accept_floor / 2
                               or cur <= 1) else cur // 2
                self._actuate("draft_k", target, cause)
            return
        self._spec_low_ticks = 0
        if s.accept_rate > self.accept_high \
                and cur < int(self.specs["draft_k"].hi):
            self._actuate("draft_k", cur + 1, cause)

    def _decide_pool_split(self, s: _Sense, cause: Dict) -> None:
        """The pool-split law: the controller's first STRUCTURAL knob.

        Dormant unless the router is disaggregated (``prefill_share`` +
        its quantum ``prefill_share_step`` in the sensed knobs).  The
        pressure signal is the BACKLOG imbalance — streams queued for a
        prefill slot vs payloads queued at decode doors — because
        backlog leads latency: by the time ``ttft_p99`` degrades, the
        prefill queue has been starved for a full histogram window.
        Sustained imbalance (``split_backlog_min`` for
        ``split_patience`` ticks, signed so flapping resets) moves the
        split ONE engine quantum, through :meth:`_actuate` with the
        eval signal the move puts at risk: growing the prefill pool is
        judged against ``inter_token_p99_ms`` (decode lost an engine),
        shrinking against ``ttft_p99_ms`` — so a re-balance that hurts
        the side it taxed auto-reverts.  Targets are quantized exactly
        as the router reports them (``round(cur ± step, 6)``), so the
        eval window's staleness check compares equal."""
        cur = s.knobs.get("prefill_share")
        step = s.knobs.get("prefill_share_step")
        if cur is None or step is None:
            return  # not a disaggregated pool
        pb = s.prefill_backlog
        db = s.decode_backlog
        if pb is None and db is None:
            return
        pb = float(pb or 0.0)
        db = float(db or 0.0)
        spec = self.specs["prefill_share"]
        if pb >= db + self.split_backlog_min:
            self._split_ticks = max(0, self._split_ticks) + 1
            if self._split_ticks >= self.split_patience:
                self._split_ticks = 0
                target = round(float(cur) + float(step), 6)
                if spec.lo <= target <= spec.hi:
                    self._actuate("prefill_share", target, cause,
                                  signal="inter_token_p99_ms")
        elif db >= pb + self.split_backlog_min:
            self._split_ticks = min(0, self._split_ticks) - 1
            if -self._split_ticks >= self.split_patience:
                self._split_ticks = 0
                target = round(float(cur) - float(step), 6)
                if spec.lo <= target <= spec.hi:
                    self._actuate("prefill_share", target, cause,
                                  signal="ttft_p99_ms")
        else:
            self._split_ticks = 0

    def _decide_rollout(self, s: _Sense, cause: Dict) -> None:
        """The canary-rollout law: step ``canary_fraction`` up the
        :class:`RolloutPlan` while shadow parity and candidate p99 hold;
        ROLL BACK to 0 — through the same ``_actuate`` choke point, so
        the undo is clamped, decision-recorded and chained to the advance
        it reverses — the moment either regresses.  A rolled-back rollout
        stays down: re-trying a candidate the evidence condemned needs an
        operator (a new candidate resets the controller)."""
        plan = self.rollout
        sense_fn = getattr(self.router, "rollout_sense", None)
        if plan is None or sense_fn is None:
            return
        rs = sense_fn()
        frac = rs.get("canary_fraction") or 0.0
        cause = {**cause,
                 **{f"rollout_{k}": round(v, 6) for k, v in rs.items()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)}}
        checked = rs.get("parity_checked") or 0
        mismatch = rs.get("mismatch_rate") or 0.0
        p_p99 = rs.get("primary_p99_ms")
        c_p99 = rs.get("candidate_p99_ms")
        evidence = checked >= plan.min_shadow_checked
        parity_bad = evidence and mismatch > plan.parity_tolerance
        p99_bad = (p_p99 is not None and c_p99 is not None
                   and c_p99 > plan.p99_factor * p_p99 + plan.p99_floor_ms)
        if frac > 0 and (parity_bad or p99_bad):
            # ROLLBACK: the fraction drops to 0 (the fleet drains the
            # candidate's queue back to the primary), force=True so a
            # cooldown can never delay the undo, revert_of chains it to
            # the advance (and keeps the eval window from "reverting the
            # rollback" — re-installing a condemned canary)
            if self._actuate(
                    "canary_fraction", 0.0,
                    {**cause, "rollback": True,
                     "parity_bad": parity_bad, "p99_bad": p99_bad},
                    force=True,
                    revert_of=self._last_did.get("canary_fraction",
                                                 "rollout")):
                self.rollbacks_total += 1
                self._rollout_aborted = True
                self._rollout_ticks = 0
            return
        if self._rollout_aborted or frac >= plan.steps[-1]:
            return  # rolled back for good, or rollout complete
        if not evidence or parity_bad or p99_bad:
            self._rollout_ticks = 0
            return
        self._rollout_ticks += 1
        if self._rollout_ticks < plan.patience:
            return
        self._rollout_ticks = 0
        nxt = next((st for st in plan.steps if st > frac + 1e-9),
                   plan.steps[-1])
        self._actuate("canary_fraction", nxt, cause)

    # -------------------------------------------------------------- actuate
    def _actuate(self, knob: str, value, cause: Dict, *,
                 signal: Optional[str] = None, force: bool = False,
                 revert_of: Optional[str] = None) -> bool:
        """THE choke point: every knob write in the control plane comes
        through here (jaxlint R13 flags any other path).  Enforces the
        backoff hold, the per-knob cooldown and the clamp range, applies
        the change through the router's thread-safe setter surface,
        records the decision chain, and opens the evaluation window."""
        spec = self.specs[knob]
        now = self.clock()
        if not force:
            if now < self._hold_until.get(knob, 0.0):
                self.blocked_total += 1
                return False
            if now - self._last_actuated.get(knob, -1e18) < spec.cooldown_s:
                self.blocked_total += 1
                return False
        # None is a legitimate knob value (hedge_ms=None = hedging off) —
        # both as the pre-actuation old value a revert restores and as a
        # revert target; clamp only applies to numbers
        value = spec.clamp(value) if value is not None else None
        old = self._knob_value(knob)
        if value == (spec.clamp(old)
                     if spec.integer and old is not None else old):
            self.blocked_total += 1
            return False
        signal_key = signal or spec.signal
        baseline = (self.last_sense.signal(signal_key)
                    if self.last_sense is not None else None)
        try:
            self._apply(knob, value, old)
        except Exception as e:  # noqa: BLE001 — a refused apply (e.g. the
            # last dispatchable replica) is a blocked decision, not a
            # controller crash
            self.errors_total += 1
            self.last_error = f"{type(e).__name__}: {e}"
            return False
        did = mint_decision_id()
        if self.tracer is not None:
            record_decision(self.tracer, did, "action", knob=knob,
                            old=old, new=value, cause=cause,
                            signal=signal_key,
                            **({"baseline": baseline}
                               if baseline is not None else {}),
                            **({"revert_of": revert_of}
                               if revert_of else {}))
        self.actuations_total += 1
        self._last_actuated[knob] = now
        self._last_did[knob] = did
        with self._lock:
            self._pending.append(_PendingEval(
                did, knob, old, value, signal_key, baseline,
                now + self.eval_window_s, revert_of))
        return True

    def _knob_value(self, knob: str):
        if knob == "replicas":
            return getattr(self.router, "active_count", None)
        return self.router.knob_values().get(knob)

    def _apply(self, knob: str, value, old) -> None:
        if knob == "replicas":
            current = self.router.active_count
            if value < current:
                self.router.deactivate_replica()
            elif value > current:
                self.router.activate_replica()
            return
        self.router.apply_knob(knob, value)

    def inject(self, knob: str, value, cause_label: str = "injected"
               ) -> bool:
        """Chaos/test hook: push an actuation through the SAME ``_actuate``
        choke point (clamped, decision-recorded, evaluated) bypassing only
        cooldown/hold — the ``bench.py --replay`` smoke injects a bad
        value here and gates that the evaluation window auto-reverts it."""
        return self._actuate(knob, value, {"note": cause_label},
                             force=True)

    # ------------------------------------------------------------- evaluate
    def _evaluate(self, s: _Sense) -> None:
        with self._lock:
            due = [p for p in self._pending if s.t >= p.t_eval]
            self._pending = [p for p in self._pending if s.t < p.t_eval]
        for p in due:
            observed = s.signal(p.signal)
            spec = self.specs[p.knob]
            # staleness: if the knob no longer holds the value this
            # actuation set (something else — a forced rollback, a crash
            # changing active_count — moved it since), there is nothing
            # left to keep OR revert: "reverting" to p.old would
            # re-install state a later decision deliberately replaced
            # (e.g. routing caller traffic back onto a canary the
            # rollout law just condemned)
            current = self._knob_value(p.knob)
            if current != p.new:
                self._record_outcome(p, "superseded", observed)
                continue
            # a scale-UP is never a revert candidate: the ambient signal
            # can keep worsening while the burst that triggered it is
            # still building, and "reverting" would drain capacity at
            # exactly the moment the SLO is failing — the symmetric risk
            # (drained too much) is what revert exists for, and that is
            # the scale-DOWN direction, which stays fully revertable
            scale_up = (p.knob == "replicas"
                        and isinstance(p.old, (int, float))
                        and isinstance(p.new, (int, float))
                        and p.new > p.old)
            regressed = (
                p.revert_of is None and not scale_up
                and observed is not None and p.baseline is not None
                and (observed - p.baseline)
                > max(self.revert_margin * abs(p.baseline),
                      spec.noise_floor))
            if not regressed:
                if p.revert_of is None:
                    self._strikes[p.knob] = 0
                self._record_outcome(p, "kept", observed)
                continue
            # the change made its own SLO signal worse: undo it and hold
            # this knob under capped-exponential backoff
            self._record_outcome(p, "reverted", observed)
            self.reverts_total += 1
            strikes = self._strikes.get(p.knob, 0) + 1
            self._strikes[p.knob] = strikes
            self._hold_until[p.knob] = s.t + min(
                self.hold_cap_s, self.hold_base_s * (2 ** (strikes - 1)))
            self._actuate(p.knob, p.old,
                          {"reverting": p.did,
                           "observed": observed, "baseline": p.baseline},
                          signal=p.signal, force=True, revert_of=p.did)

    def _record_outcome(self, p: _PendingEval, result: str,
                        observed) -> None:
        if self.tracer is None:
            return
        delta = (observed / p.baseline - 1.0
                 if isinstance(observed, (int, float))
                 and isinstance(p.baseline, (int, float)) and p.baseline
                 else None)
        record_decision(self.tracer, p.did, "outcome", knob=p.knob,
                        result=result, signal=p.signal,
                        **({"observed": observed}
                           if observed is not None else {}),
                        **({"baseline": p.baseline}
                           if p.baseline is not None else {}),
                        **({"delta_ratio": round(delta, 6)}
                           if delta is not None else {}))

    # ------------------------------------------------------------ reporting
    def snapshot(self) -> Dict:
        """JSON-ready controller state — the live exporter's
        ``controller`` source on ``/metrics``."""
        now = self.clock()
        with self._lock:
            pending = len(self._pending)
        holds = {k: round(t - now, 3)
                 for k, t in self._hold_until.items() if t > now}
        return {
            "knobs": {**self.router.knob_values(),
                      "replicas": getattr(self.router, "active_count",
                                          None)},
            "active": getattr(self.router, "active_count", None),
            "standby": getattr(self.router, "standby_count", None),
            "min_replicas": self.min_replicas,
            "actuations_total": self.actuations_total,
            "reverts_total": self.reverts_total,
            "rollbacks_total": self.rollbacks_total,
            "rollout": ({"aborted": self._rollout_aborted,
                         "healthy_ticks": self._rollout_ticks,
                         "steps": list(self.rollout.steps)}
                        if self.rollout is not None else None),
            "blocked_total": self.blocked_total,
            "errors_total": self.errors_total,
            "pending_evals": pending,
            "holds_s": holds,
            "strikes": dict(self._strikes),
            "sense": (self.last_sense.as_dict()
                      if self.last_sense is not None else None),
        }

    def health_summary(self) -> Dict:
        """The compact ``/healthz`` summary (exporter ``health_sources``):
        what an operator wants at a glance — is the control plane alive,
        what is it holding, how often has it had to undo itself."""
        now = self.clock()
        return {
            "running": self._thread is not None,
            "active": getattr(self.router, "active_count", None),
            "standby": getattr(self.router, "standby_count", None),
            "actuations": self.actuations_total,
            "reverts": self.reverts_total,
            "held_knobs": sorted(k for k, t in self._hold_until.items()
                                 if t > now),
            "last_error": self.last_error,
        }
