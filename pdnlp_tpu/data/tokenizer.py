"""BERT-style WordPiece tokenizer with a corpus-built vocabulary.

The reference tokenizes with HF ``BertTokenizer`` over the published
``chinese-bert-wwm-ext`` vocab (``single-gpu-cls.py:221``).  This image has
zero egress and no cached vocab, so the framework builds its own WordPiece
vocab from the training corpus (same special tokens, same basic-tokenizer
semantics: every CJK char is its own token, latin words greedy-matched with
``##`` continuations).  Encoding semantics mirror
``tokenizer.encode_plus(max_length=128, padding="max_length",
truncation="longest_first")`` (``single-gpu-cls.py:52-84``):
``[CLS] tokens [SEP]`` then zero-pad.

A C++ implementation of the hot path (``csrc/wordpiece.cpp``) is loaded via
ctypes when built; this module is the reference implementation and the
fallback, and both must agree bit-for-bit (tested in
``tests/test_native_tokenizer.py``).
"""
from __future__ import annotations

import collections
import os
import unicodedata
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"
SPECIALS = [PAD, UNK, CLS, SEP, MASK]
DEFAULT_VOCAB_SIZE = 21_128  # shape parity with chinese-bert-wwm-ext


def _is_cjk(cp: int) -> bool:
    return (
        0x4E00 <= cp <= 0x9FFF
        or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF
        or 0x2A700 <= cp <= 0x2B73F
        or 0x2B740 <= cp <= 0x2B81F
        or 0x2B820 <= cp <= 0x2CEAF
        or 0xF900 <= cp <= 0xFAFF
        or 0x2F800 <= cp <= 0x2FA1F
    )


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def basic_tokenize(text: str, lower: bool = True) -> List[str]:
    """Whitespace/punct split with each CJK char isolated (BERT basic tokenizer)."""
    if lower:
        text = text.lower()
    out: List[str] = []
    buf: List[str] = []

    def flush():
        if buf:
            out.append("".join(buf))
            buf.clear()

    for ch in text:
        cp = ord(ch)
        if cp == 0 or cp == 0xFFFD or unicodedata.category(ch) in ("Cc", "Cf"):
            continue
        if ch.isspace():
            flush()
        elif _is_cjk(cp) or _is_punct(ch):
            flush()
            out.append(ch)
        else:
            buf.append(ch)
    flush()
    return out


def wordpiece(token: str, vocab: Dict[str, int], max_chars: int = 100) -> List[str]:
    """Greedy longest-match-first subword split; whole-token [UNK] on failure."""
    if len(token) > max_chars:
        return [UNK]
    pieces: List[str] = []
    start = 0
    while start < len(token):
        end = len(token)
        cur = None
        while start < end:
            sub = token[start:end]
            if start > 0:
                sub = "##" + sub
            if sub in vocab:
                cur = sub
                break
            end -= 1
        if cur is None:
            return [UNK]
        pieces.append(cur)
        start = end
    return pieces


def build_vocab(
    texts: Iterable[str],
    size: int = DEFAULT_VOCAB_SIZE,
    min_freq: int = 1,
) -> List[str]:
    """Deterministic corpus-driven vocab: specials, then tokens by (-freq, token).

    Whole basic-tokens are kept, plus ``##``-suffix pieces of every non-CJK
    token so OOV latin words still decompose instead of collapsing to [UNK].
    """
    counts: collections.Counter = collections.Counter()
    for text in texts:
        for tok in basic_tokenize(text):
            counts[tok] += 1
            if len(tok) > 1 and not _is_cjk(ord(tok[0])):
                # credit continuation pieces (cheap stand-in for WordPiece training)
                for i in range(1, len(tok)):
                    counts["##" + tok[i]] += 1
    ranked = sorted(
        (t for t, c in counts.items() if c >= min_freq),
        key=lambda t: (-counts[t], t),
    )
    return SPECIALS + ranked[: size - len(SPECIALS)]


def save_vocab(vocab: Sequence[str], path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # atomic write: concurrent processes (multi-host launch) each build the
    # same deterministic vocab; rename makes the race harmless
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write("\n".join(vocab) + "\n")
    os.replace(tmp, path)


def load_vocab(path: str) -> List[str]:
    with open(path, encoding="utf-8") as f:
        return [line.rstrip("\n") for line in f if line.rstrip("\n")]


class WordPieceTokenizer:
    """End-to-end encoder: text -> fixed-length (ids, mask, type_ids).

    ``encode`` mirrors the reference collator's ``encode_plus`` call
    (``single-gpu-cls.py:61-76``): single segment, ``[CLS]``/``[SEP]``,
    truncate to ``max_len``, pad to ``max_len`` with id 0 (= [PAD]).
    """

    def __init__(self, vocab: Sequence[str], lower: bool = True):
        self.vocab_list = list(vocab)
        self.vocab = {t: i for i, t in enumerate(self.vocab_list)}
        self.lower = lower
        self.pad_id = self.vocab[PAD]
        self.unk_id = self.vocab[UNK]
        self.cls_id = self.vocab[CLS]
        self.sep_id = self.vocab[SEP]
        self._native = None  # set by data.native.attach() when csrc build exists

    @classmethod
    def from_file(cls, path: str) -> "WordPieceTokenizer":
        return cls(load_vocab(path))

    @property
    def vocab_size(self) -> int:
        return len(self.vocab_list)

    def tokenize(self, text: str) -> List[str]:
        pieces: List[str] = []
        for tok in basic_tokenize(text, self.lower):
            pieces.extend(wordpiece(tok, self.vocab))
        return pieces

    def encode_ids(self, text: str, max_len: int = 128) -> List[int]:
        """Unpadded ``[CLS] ids [SEP]`` (truncated to ``max_len``) — the
        framing shared by fixed-shape ``encode`` and the packing path."""
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2 ([CLS]+[SEP]), got {max_len}")
        ids = [self.vocab.get(p, self.unk_id) for p in self.tokenize(text)]
        return [self.cls_id] + ids[: max_len - 2] + [self.sep_id]

    def encode(self, text: str, max_len: int = 128) -> Tuple[List[int], List[int], List[int]]:
        ids = self.encode_ids(text, max_len)
        mask = [1] * len(ids)
        pad = max_len - len(ids)
        ids += [self.pad_id] * pad
        mask += [0] * pad
        return ids, mask, [0] * max_len

    def encode_ragged(self, texts: Sequence[str], max_len: int = 128) -> List[List[int]]:
        """Unpadded ``[CLS] ids [SEP]`` per text — the serving front half:
        true lengths pick the pad bucket (``serve.batcher.pick_bucket``)
        before ``data.collate.pad_ids_to_bucket`` fixes the shape."""
        return [self.encode_ids(t, max_len) for t in texts]

    def encode_batch(self, texts: Sequence[str], max_len: int = 128) -> Dict[str, np.ndarray]:
        if self._native is not None:
            return self._native.encode_batch(texts, max_len)
        n = len(texts)
        input_ids = np.zeros((n, max_len), dtype=np.int32)
        attention_mask = np.zeros((n, max_len), dtype=np.int32)
        for i, text in enumerate(texts):
            ids, mask, _ = self.encode(text, max_len)
            input_ids[i] = ids
            attention_mask[i] = mask
        return {
            "input_ids": input_ids,
            "attention_mask": attention_mask,
            "token_type_ids": np.zeros((n, max_len), dtype=np.int32),
        }


def get_or_build_vocab(args) -> List[str]:
    """Load the cached corpus vocab, building it on first use."""
    from pdnlp_tpu.data.corpus import load_data

    if os.path.exists(args.vocab_path):
        return load_vocab(args.vocab_path)
    data = load_data(args.data_path)
    vocab = build_vocab(t for t, _ in data)
    save_vocab(vocab, args.vocab_path)
    return vocab
