"""Batched data loader with background tokenization prefetch.

The reference pushes tokenization into ``DataLoader(num_workers=2)``
subprocesses (``multi-gpu-distributed-cls.py:318``).  Python
multiprocessing buys little here (this image has one core and the tokenizer
releases no GIL in its Python fallback), so the loader instead overlaps
collation with device compute via a single background thread and a bounded
queue — with the C++ tokenizer (``csrc/wordpiece.cpp``) doing the heavy
lifting outside the GIL when built.

Every batch is padded to a full static shape; short final batches carry
``example_weight == 0`` filler rows (see ``data.collate``).
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

from pdnlp_tpu.data.collate import Batch, Collator, EncodedDataset
from pdnlp_tpu.data.sampler import DistributedShardSampler


class DataLoader:
    def __init__(
        self,
        data: Sequence[Tuple[str, int]],
        collator: Collator,
        batch_size: int,
        sampler: Optional[DistributedShardSampler] = None,
        drop_last: bool = False,
        prefetch: int = 2,
        encoded: Optional[EncodedDataset] = None,
    ):
        """``encoded`` (an :class:`EncodedDataset`) short-circuits collation:
        batches become numpy fancy-indexes into the one-time-encoded split
        instead of re-tokenizing every epoch."""
        self.data = data
        self.collator = collator
        self.batch_size = batch_size
        self.sampler = sampler or DistributedShardSampler(len(data), shuffle=False)
        self.drop_last = drop_last
        self.prefetch = prefetch
        self.encoded = encoded

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def set_epoch(self, epoch: int) -> None:
        self.sampler.set_epoch(epoch)

    def _chunks(self) -> Iterator[List[int]]:
        idx = list(self.sampler)
        for i in range(0, len(idx), self.batch_size):
            chunk = idx[i : i + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                return
            yield chunk

    def _make(self, chunk: List[int]) -> Batch:
        if self.encoded is not None:
            return self.encoded.take(chunk, pad_to=self.batch_size)
        return self.collator([self.data[j] for j in chunk], pad_to=self.batch_size)

    def __iter__(self) -> Iterator[Batch]:
        if self.prefetch <= 0:
            for chunk in self._chunks():
                yield self._make(chunk)
            return
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        _SENTINEL = object()
        stop = threading.Event()

        def put_or_stop(item) -> bool:
            """Bounded put that notices consumer abandonment: EVERY worker
            put (batches, the sentinel, a raised exception) polls the stop
            flag, so an early ``break`` in the consumer can never strand the
            thread blocked on a full queue."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for chunk in self._chunks():
                    if not put_or_stop(self._make(chunk)):
                        return
                put_or_stop(_SENTINEL)
            except BaseException as e:  # propagate to the consumer, not /dev/null
                put_or_stop(e)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # every worker put is stop-aware (0.1 s poll), so abandonment
            # tears down in ONE bounded join — no drain busy-spin
            stop.set()
            t.join(timeout=2.0)
