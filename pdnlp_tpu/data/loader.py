"""Batched data loader with background tokenization prefetch.

The reference pushes tokenization into ``DataLoader(num_workers=2)``
subprocesses (``multi-gpu-distributed-cls.py:318``).  Python
multiprocessing buys little here (this image has one core and the tokenizer
releases no GIL in its Python fallback), so the loader instead overlaps
collation with device compute via a single background thread and a bounded
queue — with the C++ tokenizer (``csrc/wordpiece.cpp``) doing the heavy
lifting outside the GIL when built.

Every batch is padded to a full static shape; short final batches carry
``example_weight == 0`` filler rows (see ``data.collate``).
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

from pdnlp_tpu.data.collate import Batch, Collator, EncodedDataset
from pdnlp_tpu.data.sampler import DistributedShardSampler


class DataLoader:
    def __init__(
        self,
        data: Sequence[Tuple[str, int]],
        collator: Collator,
        batch_size: int,
        sampler: Optional[DistributedShardSampler] = None,
        drop_last: bool = False,
        prefetch: int = 2,
        encoded: Optional[EncodedDataset] = None,
    ):
        """``encoded`` (an :class:`EncodedDataset`) short-circuits collation:
        batches become numpy fancy-indexes into the one-time-encoded split
        instead of re-tokenizing every epoch."""
        self.data = data
        self.collator = collator
        self.batch_size = batch_size
        self.sampler = sampler or DistributedShardSampler(len(data), shuffle=False)
        self.drop_last = drop_last
        self.prefetch = prefetch
        self.encoded = encoded
        if (hasattr(self.sampler, "chunks") and self.drop_last
                and not getattr(self.sampler, "drop_last", False)):
            # the sampler chunks GLOBAL batches; a shard-local length test
            # here would drop different steps on different processes (a
            # 15-row global tail = 8 rows on shard 0, 7 on shard 1) and
            # hang the SPMD collectives — short-tail dropping must be the
            # sampler's, where it is global
            raise ValueError(
                "drop_last with a batching sampler must be set on the "
                "sampler (it owns the global chunking), not the loader")

    def __len__(self) -> int:
        # a batching sampler (LengthGroupedSampler) owns the chunking and
        # its batch count is epoch-invariant; the flat-stream samplers
        # keep the classic division
        n_batches = getattr(self.sampler, "batches_per_epoch", None)
        if n_batches is not None:
            return n_batches
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def set_epoch(self, epoch: int) -> None:
        self.sampler.set_epoch(epoch)

    def _chunks(self) -> Iterator[Tuple[List[int], int]]:
        """Yield ``(indices, seq_len)`` per batch; ``seq_len`` 0 = the
        collator's full ``max_seq_len`` (the classic path).  A sampler
        with its own ``chunks()`` (length-grouped batching) supplies both
        the chunking and the bucket width.  The drop_last/batching-sampler
        conflict is refused at construction (``__init__``)."""
        if hasattr(self.sampler, "chunks"):
            yield from self.sampler.chunks()
            return
        idx = list(self.sampler)
        for i in range(0, len(idx), self.batch_size):
            chunk = idx[i : i + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                return
            yield chunk, 0

    def _make(self, chunk: List[int], seq_len: int = 0) -> Batch:
        # seq_len is only forwarded when a bucketing sampler supplied one:
        # custom collators/encodings predating the kwarg stay compatible
        if self.encoded is not None:
            if seq_len:
                return self.encoded.take(chunk, pad_to=self.batch_size,
                                         seq_len=seq_len)
            return self.encoded.take(chunk, pad_to=self.batch_size)
        examples = [self.data[j] for j in chunk]
        if seq_len:
            return self.collator(examples, pad_to=self.batch_size,
                                 seq_len=seq_len)
        return self.collator(examples, pad_to=self.batch_size)

    def __iter__(self) -> Iterator[Batch]:
        if self.prefetch <= 0:
            for chunk, seq_len in self._chunks():
                yield self._make(chunk, seq_len)
            return
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        _SENTINEL = object()
        stop = threading.Event()

        def put_or_stop(item) -> bool:
            """Bounded put that notices consumer abandonment: EVERY worker
            put (batches, the sentinel, a raised exception) polls the stop
            flag, so an early ``break`` in the consumer can never strand the
            thread blocked on a full queue."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for chunk, seq_len in self._chunks():
                    if not put_or_stop(self._make(chunk, seq_len)):
                        return
                put_or_stop(_SENTINEL)
            except BaseException as e:  # propagate to the consumer, not /dev/null
                put_or_stop(e)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # every worker put is stop-aware (0.1 s poll), so abandonment
            # tears down in ONE bounded join — no drain busy-spin
            stop.set()
            t.join(timeout=2.0)
