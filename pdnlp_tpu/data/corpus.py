"""Corpus loading and the seeded train/dev split.

Reference behavior being reproduced (not its code):
- ``load_data`` reads ``data/train.json`` — one JSON array of
  ``[text, label]`` pairs where the text is pre-tokenized with spaces —
  and re-joins by stripping the spaces (``single-gpu-cls.py:26-41``).
- The split takes the first 10,000 examples, shuffles them under seed 123,
  and cuts 92/8 into 9,200 train / 800 dev; dev doubles as the test set
  (``single-gpu-cls.py:226-247``).
"""
from __future__ import annotations

import json
import random
from typing import List, Sequence, Tuple

Example = Tuple[str, int]

# 6-class Chinese emotion labels (single-gpu-cls.py:212-219):
# other / like / sad / disgust / anger / happy
LABELS = ["其他", "喜好", "悲伤", "厌恶", "愤怒", "高兴"]
label2id = {name: i for i, name in enumerate(LABELS)}
id2label = {i: name for i, name in enumerate(LABELS)}


def load_data(path: str) -> List[Example]:
    """Read the corpus and strip pre-tokenization spaces."""
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    out: List[Example] = []
    for text, label in raw:
        text = "".join(text.split(" ")).strip()
        out.append((text, int(label)))
    return out


def split_data(
    data: Sequence[Example],
    seed: int = 123,
    limit: int = 10_000,
    ratio: float = 0.92,
) -> Tuple[List[Example], List[Example]]:
    """Seeded shuffle + split; returns (train, dev). Dev is also the test set."""
    data = list(data[:limit])
    rng = random.Random(seed)
    rng.shuffle(data)
    cut = int(len(data) * ratio)
    return data[:cut], data[cut:]
