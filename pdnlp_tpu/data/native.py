"""ctypes binding for the C++ WordPiece tokenizer (``csrc/wordpiece.cpp``).

The reference outsources its native tokenization to HF's compiled
tokenizers (``/root/reference/single-gpu-cls.py:221`` — ``BertTokenizer``
backed by native code in the fast path); this framework owns the native
piece.  ctypes releases the GIL during ``wp_encode_batch``, so the data
loader's prefetch thread tokenizes concurrently with device compute — the
reason the loader is thread- not process-based (``data/loader.py``).

``attach(tokenizer)`` is opportunistic: it binds the shared library if it
has been built (``make -C csrc`` or ``build()``), else leaves the pure-
Python path in place.  Both implementations are bit-identical (generated
Unicode tables + ``tests/test_native_tokenizer.py`` corpus parity).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, Optional, Sequence

import numpy as np

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "libwordpiece.so")


def build(force: bool = False) -> Optional[str]:
    """Compile the shared library (requires g++); returns its path or None."""
    if force:
        subprocess.run(["make", "-C", _CSRC, "clean"], capture_output=True)
    r = subprocess.run(["make", "-C", _CSRC], capture_output=True, text=True)
    if r.returncode != 0:
        return None
    return _SO if os.path.exists(_SO) else None


class NativeEncoder:
    """Wraps one ``wp_create`` handle; mirrors ``encode_batch``'s contract."""

    def __init__(self, vocab: Sequence[str], so_path: str = _SO):
        self._lib = ctypes.CDLL(so_path)
        self._lib.wp_create.restype = ctypes.c_void_p
        self._lib.wp_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        self._lib.wp_destroy.argtypes = [ctypes.c_void_p]
        self._lib.wp_vocab_size.restype = ctypes.c_int32
        self._lib.wp_vocab_size.argtypes = [ctypes.c_void_p]
        self._lib.wp_encode_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ctypes.c_int32, ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ]
        buf = ("\n".join(vocab) + "\n").encode("utf-8")
        self._handle = self._lib.wp_create(buf, len(buf))
        if not self._handle:
            raise ValueError("vocab is missing required special tokens")
        native_n = self._lib.wp_vocab_size(self._handle)
        if native_n != len(vocab):
            raise ValueError(
                f"vocab has {len(vocab) - native_n} duplicate tokens — native "
                "and Python id assignment would disagree")

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.wp_destroy(self._handle)
            self._handle = None

    def encode_batch(self, texts: Sequence[str], max_len: int = 128
                     ) -> Dict[str, np.ndarray]:
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2 ([CLS]+[SEP]), got {max_len}")
        n = len(texts)
        raw = [t.encode("utf-8") for t in texts]
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(b) for b in raw], out=offsets[1:])
        blob = b"".join(raw)
        input_ids = np.zeros((n, max_len), dtype=np.int32)
        attention_mask = np.zeros((n, max_len), dtype=np.int32)
        self._lib.wp_encode_batch(self._handle, blob, offsets, n, max_len,
                                  input_ids, attention_mask)
        return {
            "input_ids": input_ids,
            "attention_mask": attention_mask,
            "token_type_ids": np.zeros((n, max_len), dtype=np.int32),
        }


def attach(tokenizer, so_path: str = _SO) -> bool:
    """Bind the native encoder to a ``WordPieceTokenizer`` if the library is
    built; returns True on success (tokenizer.encode_batch now native)."""
    if not os.path.exists(so_path):
        return False
    try:
        tokenizer._native = NativeEncoder(tokenizer.vocab_list, so_path)
        return True
    except (OSError, ValueError):
        return False


if __name__ == "__main__":
    import sys

    path = build(force="--force" in sys.argv)
    print(f"built: {path}" if path else "build failed (is g++ available?)")
    sys.exit(0 if path else 1)
