"""Deterministic distributed sharding of the dataset — the
``DistributedSampler`` analog.

The reference relies on ``torch.utils.data.DistributedSampler`` to give each
rank a disjoint 1/world_size slice of an epoch-seeded permutation, padding so
every rank sees the same number of steps
(``multi-gpu-distributed-cls.py:314-330``; ``set_epoch`` at ``:164``).

On TPU the "rank" is the host process: each host materializes only its shard
of the global batch and the arrays are assembled into one global-sharded
``jax.Array`` (see ``parallel.collectives.make_global_batch``).  Indices pad
by wrapping, like the reference's sampler, so step counts match (144 steps at
2-way DP for the 9,200-example epoch, ``SURVEY.md`` §6).
"""
from __future__ import annotations

from typing import Iterator, List

import numpy as np


class DistributedShardSampler:
    def __init__(
        self,
        num_examples: int,
        num_shards: int = 1,
        shard_id: int = 0,
        shuffle: bool = True,
        seed: int = 123,
        drop_last: bool = False,
    ):
        assert 0 <= shard_id < num_shards
        self.num_examples = num_examples
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.shard_len = num_examples // num_shards
        else:
            self.shard_len = -(-num_examples // num_shards)  # ceil

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle differently each epoch (DistributedSampler.set_epoch analog)."""
        self.epoch = epoch

    def global_order(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            return rng.permutation(self.num_examples)
        return np.arange(self.num_examples)

    def shard_indices(self) -> np.ndarray:
        """This shard's indices: strided slice of the (padded) global order."""
        order = self.global_order()
        total = self.shard_len * self.num_shards
        if total > len(order):  # pad by wrapping, like DistributedSampler
            order = np.concatenate([order, order[: total - len(order)]])
        else:
            order = order[:total]
        return order[self.shard_id :: self.num_shards]

    def __iter__(self) -> Iterator[int]:
        return iter(self.shard_indices().tolist())

    def __len__(self) -> int:
        return self.shard_len
