"""Deterministic distributed sharding of the dataset — the
``DistributedSampler`` analog.

The reference relies on ``torch.utils.data.DistributedSampler`` to give each
rank a disjoint 1/world_size slice of an epoch-seeded permutation, padding so
every rank sees the same number of steps
(``multi-gpu-distributed-cls.py:314-330``; ``set_epoch`` at ``:164``).

On TPU the "rank" is the host process: each host materializes only its shard
of the global batch and the arrays are assembled into one global-sharded
``jax.Array`` (see ``parallel.collectives.make_global_batch``).  Indices pad
by wrapping, like the reference's sampler, so step counts match (144 steps at
2-way DP for the 9,200-example epoch, ``SURVEY.md`` §6).

Elastic-width contract: every epoch order is a pure function of
``(seed, epoch)`` and row assignment a pure function of
``(num_shards, shard_id)`` over it — nothing is cached across widths — so
a gang that resumes at a DIFFERENT data-parallel width (a dead host
evicted, ``parallel/watchdog.GangSupervisor``) recomputes row assignment
correctly just by being rebuilt at the new width.  Same-width resume
replays the identical stream (bitwise continuation); across widths the
consumed-example SET is only approximately the old prefix (the interleave
changes), which is why ``Trainer._remap_elastic_width`` continues by epoch
fraction and documents the few-rows skip.
"""
from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import numpy as np


class DistributedShardSampler:
    def __init__(
        self,
        num_examples: int,
        num_shards: int = 1,
        shard_id: int = 0,
        shuffle: bool = True,
        seed: int = 123,
        drop_last: bool = False,
    ):
        assert 0 <= shard_id < num_shards
        self.num_examples = num_examples
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.shard_len = num_examples // num_shards
        else:
            self.shard_len = -(-num_examples // num_shards)  # ceil

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle differently each epoch (DistributedSampler.set_epoch analog)."""
        self.epoch = epoch

    def global_order(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            return rng.permutation(self.num_examples)
        return np.arange(self.num_examples)

    def shard_indices(self) -> np.ndarray:
        """This shard's indices: strided slice of the (padded) global order."""
        order = self.global_order()
        total = self.shard_len * self.num_shards
        if total > len(order):  # pad by wrapping, like DistributedSampler
            order = np.concatenate([order, order[: total - len(order)]])
        else:
            order = order[:total]
        return order[self.shard_id :: self.num_shards]

    def __iter__(self) -> Iterator[int]:
        return iter(self.shard_indices().tolist())

    def __len__(self) -> int:
        return self.shard_len


# --------------------------------------------------------------------------
# length-aware batching (--length_mode)
# --------------------------------------------------------------------------

def parse_buckets(spec: str, max_seq_len: int) -> Tuple[int, ...]:
    """``"32,64,128"`` -> sorted bucket widths, clipped to ``max_seq_len``.

    Widths over ``max_seq_len`` are dropped (the encoding truncates there —
    a wider bucket could never fill) and ``max_seq_len`` itself is always
    the last bucket, so every example has a covering bucket."""
    try:
        widths = {int(w) for w in str(spec).split(",") if str(w).strip()}
    except ValueError:
        raise ValueError(f"--length_buckets must be comma-separated ints, "
                         f"got {spec!r}")
    if any(w < 2 for w in widths):
        raise ValueError(f"bucket widths must be >= 2 ([CLS]+[SEP]), "
                         f"got {sorted(widths)}")
    return tuple(sorted(w for w in widths if w < max_seq_len)) + (max_seq_len,)


def validate_length_buckets(widths: Sequence[int], *, max_position: int,
                            model: str, mode: str = "bucket",
                            max_seq_len: int = None) -> None:
    """SETUP-time position-table validation of ``--length_buckets``.

    Position embeddings are a gather into the model's ``[max_position, H]``
    table, and JAX clamps out-of-bounds gathers instead of raising — an
    unpacked 1024-wide bucket on bert-base (512 positions) would silently
    train on garbage embeddings for every position past 511.  Loudly
    refuse at setup instead, with the fix named.

    - ``mode="bucket"`` (unpacked rows, positions 0..width-1): every
      bucket width must fit the table;
    - ``mode="pack"`` (packed rows, positions restart per segment): the
      bound is the longest possible SEGMENT — the encode width
      (``max_seq_len``) — so pack widths may legitimately exceed the
      table (a 2048-wide packed row of <=512-token documents is exactly
      the long-context payoff).
    """
    if mode == "bucket":
        bad = sorted(int(w) for w in widths if int(w) > int(max_position))
        if bad:
            raise ValueError(
                f"--length_buckets includes {bad} but {model}'s position "
                f"table has only {max_position} positions — an unpacked "
                f"{bad[0]}-wide batch would gather position embeddings "
                "past the table (JAX clamps the gather: silent garbage, "
                "no error).  Use a long-position model (--model "
                "bert-base-long has 2048 positions) or drop the bucket")
    elif max_seq_len is not None and int(max_seq_len) > int(max_position):
        raise ValueError(
            f"--length_mode pack with --max_seq_len {max_seq_len} exceeds "
            f"{model}'s {max_position}-position table — packed positions "
            "restart per segment, so the bound is the longest segment "
            "(= the encode width), and a longer one would silently gather "
            "garbage position embeddings.  Lower --max_seq_len or use a "
            "long-position model (--model bert-base-long)")


def resolve_length_mode(args) -> str:
    """The ``--length_mode`` decision, in one place.

    ``auto`` resolves to ``full``: bucket/pack keep per-example math intact
    but change batch COMPOSITION (which examples co-occur in a step), so
    every committed loss trace and golden run stays reference-exact unless
    a run opts in.  ``bench.py --length`` measures what opting in buys."""
    mode = getattr(args, "length_mode", "auto") or "auto"
    if mode not in ("auto", "full", "bucket", "pack"):
        raise ValueError(f"unknown length_mode {mode!r}; use "
                         "auto|full|bucket|pack")
    return "full" if mode == "auto" else mode


class LengthGroupedSampler:
    """Seeded length-grouped batching: bucket-homogeneous batches that
    still shard deterministically across processes.

    Every process computes the SAME global batch sequence from the seed —
    per epoch, examples are permuted within their length bucket, chopped
    into global batches of ``batch_size * num_shards``, and the epoch
    visits the buckets as contiguous BLOCKS in a seeded order — then takes
    its strided slice of each global batch.  Three consequences the
    trainer and pipelines rely on:

    - at any global step every process feeds the same bucket (the SPMD
      global batch stays shape-consistent across hosts);
    - within a bucket block every batch shares one shape, so
      ``fuse_steps``-sized fusion groups are shape-homogeneous by
      construction and the compile count stays bounded at
      ``len(buckets) x len(step-variants)``, never per-batch;
    - the epoch's RUN STRUCTURE (batches per bucket, fused groups per
      bucket) is epoch-invariant — bucket membership is a function of the
      data, only the order within and across blocks reshuffles — so the
      device-resident pipeline's per-bucket gather programs and the step
      programs compile on epoch one and never re-trace, and resume
      fast-forward by step count stays exact.

    Determinism note: length-grouping changes which examples CO-OCCUR in
    a batch (and bucket-blocking makes batch order length-correlated
    within an epoch); it never changes any example's own tokens, mask, or
    loss weight.  The last batch of each bucket may be short; the loader
    pads it with the usual zero-weight filler.
    """

    def __init__(
        self,
        lengths: Sequence[int],
        batch_size: int,
        buckets: Sequence[int] = (32, 64, 128),
        num_shards: int = 1,
        shard_id: int = 0,
        shuffle: bool = True,
        seed: int = 123,
        drop_last: bool = False,
    ):
        assert 0 <= shard_id < num_shards
        self.lengths = np.asarray(lengths, np.int64)
        self.num_examples = len(self.lengths)
        self.batch_size = int(batch_size)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        # bucket membership is epoch-invariant: smallest covering width
        # (over-long examples land in the last bucket — the encoding
        # truncates to max_seq_len there, same longest-first outcome)
        edges = np.asarray(self.buckets, np.int64)
        self._member = edges[np.minimum(
            np.searchsorted(edges, self.lengths), len(edges) - 1)]
        G = self.batch_size * self.num_shards
        self.batches_per_epoch = 0
        for b in self.buckets:
            n = int((self._member == b).sum())
            self.batches_per_epoch += (n // G if drop_last
                                       else -(-n // G)) if n else 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def global_batches(self) -> List[Tuple[np.ndarray, int]]:
        """This epoch's ``(global_indices, bucket)`` sequence — identical
        on every process (seeded).  Buckets come as contiguous blocks (a
        bucket's short tail batch last in its block) in a seeded block
        order; see the class docstring for why the structure must be
        epoch-invariant."""
        rng = np.random.RandomState(self.seed + self.epoch)
        G = self.batch_size * self.num_shards
        blocks: List[List[Tuple[np.ndarray, int]]] = []
        for b in self.buckets:  # ascending: deterministic rng consumption
            idx = np.flatnonzero(self._member == b)
            if not len(idx):
                continue
            if self.shuffle:
                idx = idx[rng.permutation(len(idx))]
            chunks = [(idx[i: i + G], int(b)) for i in range(0, len(idx), G)]
            if self.drop_last and len(chunks) and len(chunks[-1][0]) < G:
                chunks.pop()
            if chunks:
                blocks.append(chunks)
        if self.shuffle:
            blocks = [blocks[i] for i in rng.permutation(len(blocks))]
        return [c for block in blocks for c in block]

    def chunks(self) -> Iterator[Tuple[List[int], int]]:
        """Yield ``(local_indices, bucket)`` per batch: this shard's
        strided slice of each global batch (rows, not batches, shard —
        every process sees every step, in the same bucket)."""
        for gidx, bucket in self.global_batches():
            yield gidx[self.shard_id:: self.num_shards].tolist(), bucket

    def __iter__(self) -> Iterator[int]:
        for chunk, _bucket in self.chunks():
            yield from chunk

    def __len__(self) -> int:
        # examples this shard feeds per epoch (loader __len__ uses
        # batches_per_epoch for the step count instead) — arithmetic over
        # the epoch-invariant bucket membership, no epoch materialization:
        # a full global batch slices to exactly batch_size rows per shard;
        # a tail of t rows slices to |{i in [0,t): i ≡ shard_id (mod S)}|
        G = self.batch_size * self.num_shards
        total = 0
        for b in self.buckets:
            n = int((self._member == b).sum())
            full, tail = divmod(n, G)
            total += full * self.batch_size
            if not self.drop_last and tail > self.shard_id:
                total += -(-(tail - self.shard_id) // self.num_shards)
        return total
