"""Batch collation: (text, label) pairs -> fixed-shape numpy batches.

Mirrors ``Collate.collate_fn`` (``single-gpu-cls.py:44-84``) but returns
numpy (host) arrays sized for static XLA shapes.  Two TPU-specific additions:

- an ``example_weight`` channel so padded filler rows (needed to keep the
  last batch full — XLA wants static shapes, unlike the reference's ragged
  288th step of 16 examples, ``SURVEY.md`` §7 hard-part (c)) contribute zero
  loss and are excluded from metrics;
- int32 instead of int64 (TPUs have no fast int64 path).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from pdnlp_tpu.data.tokenizer import WordPieceTokenizer

Batch = Dict[str, np.ndarray]


class Collator:
    def __init__(self, tokenizer: WordPieceTokenizer, max_seq_len: int = 128):
        self.tokenizer = tokenizer
        self.max_seq_len = max_seq_len

    def __call__(self, examples: Sequence[Tuple[str, int]], pad_to: int = 0,
                 seq_len: int = 0) -> Batch:
        """Encode a list of examples; pad the batch up to ``pad_to`` rows.

        ``seq_len`` pads token columns to that width instead of
        ``max_seq_len`` — the bucket-mode path (``--length_mode bucket``)
        where the batch's longest example picked the bucket."""
        texts = [t for t, _ in examples]
        labels = [l for _, l in examples]
        enc = self.tokenizer.encode_batch(texts, seq_len or self.max_seq_len)
        n = len(examples)
        rows = max(pad_to, n)
        batch: Batch = {
            k: _pad_rows(v, rows) for k, v in enc.items()
        }
        lab = np.zeros((rows,), dtype=np.int32)
        lab[:n] = labels
        w = np.zeros((rows,), dtype=np.float32)
        w[:n] = 1.0
        batch["label"] = lab
        batch["example_weight"] = w
        return batch


def _pad_rows(a: np.ndarray, rows: int) -> np.ndarray:
    if a.shape[0] == rows:
        return a
    out = np.zeros((rows,) + a.shape[1:], dtype=a.dtype)
    out[: a.shape[0]] = a
    return out


def pad_ids_to_bucket(id_lists: Sequence[Sequence[int]], seq_len: int,
                      rows: int = 0, pad_id: int = 0) -> Batch:
    """Ragged token-id lists -> one fixed ``[rows, seq_len]`` batch.

    The serving twin of :class:`Collator`: requests arrive pre-encoded but
    unpadded (their true length picked the bucket — ``serve.batcher``), and
    the batch pads every row to the bucket length and the row count up to
    ``rows`` with zero-weight filler, so one compiled forward per
    ``(seq_len, rows)`` shape covers every batch in the bucket.  Rows longer
    than ``seq_len`` are a caller bug (the bucket must cover its rows) and
    raise rather than silently truncate.
    """
    n = len(id_lists)
    rows = max(rows, n)
    input_ids = np.full((rows, seq_len), pad_id, dtype=np.int32)
    attention_mask = np.zeros((rows, seq_len), dtype=np.int32)
    for i, ids in enumerate(id_lists):
        if len(ids) > seq_len:
            raise ValueError(f"row {i} has {len(ids)} tokens > bucket "
                             f"{seq_len} — pick_bucket must cover its rows")
        input_ids[i, : len(ids)] = ids
        attention_mask[i, : len(ids)] = 1
    w = np.zeros((rows,), np.float32)
    w[:n] = 1.0
    return {
        "input_ids": input_ids,
        "attention_mask": attention_mask,
        "token_type_ids": np.zeros((rows, seq_len), dtype=np.int32),
        "example_weight": w,
    }


class EncodedDataset:
    """The whole split tokenized ONCE into contiguous arrays.

    A fixed dataset re-encodes identically every epoch (and every run), so
    the per-batch work collapses to a numpy fancy-index — the loader's
    tokenization cost goes from O(epochs x dataset) to O(dataset).  ~15 MB
    for the 10k-example corpus at seq 128: RAM-resident, no memmap needed.
    """

    def __init__(self, data: Sequence[Tuple[str, int]],
                 tokenizer: WordPieceTokenizer, max_seq_len: int = 128):
        texts = [t for t, _ in data]
        enc = tokenizer.encode_batch(texts, max_seq_len)  # one (native) pass
        self.arrays = dict(enc)
        self.arrays["label"] = np.asarray([l for _, l in data], np.int32)
        self.n = len(texts)
        self.seq_len = max_seq_len

    def __len__(self) -> int:
        return self.n

    def lengths(self) -> np.ndarray:
        """Real token count per example (incl. [CLS]/[SEP]) — what the
        length-grouped sampler buckets on."""
        return self.arrays["attention_mask"].sum(axis=1).astype(np.int64)

    def take(self, indices: Sequence[int], pad_to: int = 0,
             seq_len: int = 0) -> Batch:
        """Assemble a batch by row indices; pad with zero-weight filler.

        ``seq_len`` narrows token columns to that bucket width: the split
        was encoded once at ``max_seq_len``, and an example whose true
        length fits the bucket carries only [PAD] (zeros) beyond it, so
        the column slice is bitwise the direct encoding at ``seq_len``.
        Only full-width ``[N, max_seq_len]`` channels are sliced —
        per-segment channels (packed rows' ``cls_positions``/``label``)
        keep their own width.
        """
        idx = np.asarray(indices, np.int64)
        n = len(idx)
        rows = max(pad_to, n)
        batch: Batch = {}
        for k, v in self.arrays.items():
            g = v[idx]
            if seq_len and v.ndim == 2 and v.shape[1] == self.seq_len \
                    and seq_len < self.seq_len:
                g = g[:, :seq_len]
            batch[k] = _pad_rows(g, rows)
        if "example_weight" not in batch:  # packed rows carry their own
            w = np.zeros((rows,), np.float32)
            w[:n] = 1.0
            batch["example_weight"] = w
        return batch
