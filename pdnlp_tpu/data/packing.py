"""Sequence packing for MLM pretraining — fill every row, waste no MXU.

The corpus texts average ~18 tokens (`data/train.json`), so padding each to
`max_seq_len=128` would burn ~85% of the FLOPs on [PAD].  TPU-natively the
fix is *packing*: concatenate `[CLS] text [SEP]` segments back-to-back into
fixed `[N, S]` rows and carry a `segment_ids` channel; attention uses a
block-diagonal bias (`segment_bias`) so tokens never attend across text
boundaries, while every position in the row still trains the full 0..S-1
position-embedding table.  This has no reference twin — the reference never
pretrains (`/root/reference/single-gpu-cls.py:252-255` downloads pretrained
weights; this environment has no egress, so pretraining is built instead).

Shapes stay fully static: one (num_rows, S) int32 array per channel.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from pdnlp_tpu.data.tokenizer import WordPieceTokenizer


def pack_texts(
    tok: WordPieceTokenizer,
    texts: Sequence[str],
    max_seq_len: int = 128,
) -> Dict[str, np.ndarray]:
    """Greedy first-fit packing of tokenized texts into `[N, S]` rows.

    Returns `{"input_ids", "segment_ids"}`; `segment_ids` is 1-based per
    text within a row, 0 = padding.  A text longer than `S-2` tokens is
    truncated (same `longest_first` outcome as the fine-tune collator).
    """
    S = max_seq_len
    rows: List[List[int]] = []
    segs: List[List[int]] = []
    for text in texts:
        ids = tok.encode_ids(text, S)
        if not rows or len(rows[-1]) + len(ids) > S:
            rows.append([])
            segs.append([])
        seg = (segs[-1][-1] + 1) if segs[-1] else 1
        rows[-1].extend(ids)
        segs[-1].extend([seg] * len(ids))
    n = len(rows)
    input_ids = np.zeros((n, S), np.int32)
    segment_ids = np.zeros((n, S), np.int32)
    for i, (r, s) in enumerate(zip(rows, segs)):
        input_ids[i, : len(r)] = r
        segment_ids[i, : len(s)] = s
    return {"input_ids": input_ids, "segment_ids": segment_ids}


def segment_bias(segment_ids: np.ndarray, dtype=np.float32) -> np.ndarray:
    """`[B, S]` segment ids -> `[B, 1, S, S]` additive attention bias.

    0 where query and key share a (nonzero) segment, -1e9 elsewhere — the
    block-diagonal mask that keeps packed texts independent.  Pure
    arithmetic/broadcast ops so the same function traces under jit (jnp
    arrays) and runs on host numpy.
    """
    q = segment_ids[:, :, None]
    k = segment_ids[:, None, :]
    same = ((q == k) & (q > 0)).astype(dtype)
    return ((1.0 - same) * -1e9)[:, None, :, :]
